"""Load-balancing algorithms: unit + property tests for both objectives.

The reference ships these as pure functions with zero tests (SURVEY.md §4);
§7.3 hard part 6 calls out the subtle invariants: min_block floor, disjoint-
pipeline guard, oscillation eps-guards, deterministic accumulation.
"""

import numpy as np
import pytest

from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.scheduling.load_balancing import (
    MINMAX,
    WEAKEST,
    Span,
    choose_best_blocks,
    choose_best_start,
    compute_block_throughputs,
    should_choose_other_blocks,
    spans_from_records,
)
from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.scheduling.registry import (
    ServerRecord,
    ServerState,
)


def rec(pid, start, end, tput=1.0, state=ServerState.ONLINE):
    return ServerRecord(peer_id=pid, start_block=start, end_block=end,
                        throughput=tput, state=state)


def test_spans_filter_offline():
    spans = spans_from_records([
        rec("a", 0, 4), rec("b", 4, 8, state=ServerState.OFFLINE),
        rec("c", 4, 8, state=ServerState.JOINING),
    ])
    assert set(spans) == {"a", "c"}


def test_block_throughputs_deterministic_under_ordering():
    spans1 = {p: Span(p, 0, 8, 0.1 + i * 0.371) for i, p in enumerate("abcdef")}
    spans2 = dict(reversed(list(spans1.items())))
    th1 = compute_block_throughputs(spans1, 8)
    th2 = compute_block_throughputs(spans2, 8)
    assert (th1 == th2).all()  # bit-identical, not just close


def test_choose_best_start_fills_weakest_segment():
    # coverage: blocks 0-3 strong (2.0), 4-7 weak (0.5)
    th = np.array([2.0, 2.0, 2.0, 2.0, 0.5, 0.5, 0.5, 0.5])
    assert choose_best_start(th, 4, objective=WEAKEST) == 4
    assert choose_best_start(th, 4, objective=MINMAX) == 4


def test_weakest_vs_minmax_divergence():
    """The two objectives disagree when the weakest block ties: weakest then
    compares window MEANS, minmax compares the full sorted windows."""
    th = np.array([0.5, 3.0, 3.0, 0.5, 1.0, 1.0])
    # windows of 2: [0]=.5,3 [1]=3,3 [2]=3,.5 [3]=.5,1 [4]=1,1
    # weakest: min=.5 for windows 0,2,3 -> mean tiebreak: window 3 (0.75)
    assert choose_best_start(th, 2, objective=WEAKEST) == 3
    # minmax: sorted windows [.5,3] [3,3] [.5,3] [.5,1] [1,1] -> min is [.5,1]
    assert choose_best_start(th, 2, objective=MINMAX) == 3
    th2 = np.array([0.5, 3.0, 0.5, 2.0, 9.0])
    # windows of 2: [.5,3] [3,.5] [.5,2] [2,9]
    # weakest: min .5 at 0,1,2; means 1.75, 1.75, 1.25 -> window 2
    assert choose_best_start(th2, 2, objective=WEAKEST) == 2
    # minmax sorted: [.5,3] [.5,3] [.5,2] [2,9] -> [.5,2] at 2
    assert choose_best_start(th2, 2, objective=MINMAX) == 2


def test_min_block_floor_protects_client_prefix():
    """A server must never take blocks below min_block even if they are the
    weakest (the lb_min_block=splits[0] rule, src/main.py:338-339)."""
    th = np.array([0.0, 0.0, 5.0, 5.0, 1.0, 1.0, 1.0, 1.0])
    assert choose_best_start(th, 4, min_block=0, objective=WEAKEST) == 0
    assert choose_best_start(th, 4, min_block=2, objective=WEAKEST) >= 2
    blocks = choose_best_blocks(4, [rec("a", 2, 6, 5.0)], total_blocks=8,
                                min_block=2)
    assert min(blocks) >= 2


def test_joining_server_covers_empty_tail():
    records = [rec("a", 0, 4, 2.0)]
    blocks = choose_best_blocks(4, records, total_blocks=8)
    assert blocks == [4, 5, 6, 7]


def test_rebalance_false_when_already_optimal():
    records = [rec("a", 0, 4, 1.0), rec("b", 4, 8, 1.0)]
    assert not should_choose_other_blocks(
        "a", records, total_blocks=8, rng=np.random.default_rng(0))


def test_rebalance_false_for_unknown_peer():
    assert not should_choose_other_blocks(
        "ghost", [rec("a", 0, 8)], total_blocks=8,
        rng=np.random.default_rng(0))


def test_rebalance_forced_when_quality_above_one():
    assert should_choose_other_blocks(
        "a", [rec("a", 0, 8)], total_blocks=8, balance_quality=1.5)


def test_disjoint_pipeline_guard():
    """A sole-coverage server must not move even if another segment is weaker:
    moving would zero out its blocks (src/load_balancing.py:323-324)."""
    records = [rec("a", 0, 4, 0.1), rec("b", 4, 8, 5.0), rec("c", 4, 8, 5.0)]
    # 'a' is the only server for blocks 0-3; removing it zeroes them.
    assert not should_choose_other_blocks(
        "a", records, total_blocks=8, rng=np.random.default_rng(0))


@pytest.mark.parametrize("objective", [WEAKEST, MINMAX])
def test_rebalance_triggers_on_gross_imbalance(objective):
    """Three servers stacked on one half, one weak server alone on the other:
    a stacked server should want to move once its own removal leaves the
    pipeline connected."""
    records = [
        rec("a", 0, 4, 3.0), rec("b", 0, 4, 3.0), rec("c", 0, 4, 3.0),
        rec("d", 4, 8, 1.0),
    ]
    assert should_choose_other_blocks(
        "a", records, total_blocks=8, balance_quality=0.75,
        objective=objective, rng=np.random.default_rng(0))


@pytest.mark.parametrize("objective", [WEAKEST, MINMAX])
@pytest.mark.parametrize("seed", range(5))
def test_property_simulation_never_disconnects(objective, seed):
    """Property: across random swarms, a positive verdict implies the
    simulated relaxation kept every block covered (bottleneck > 0) — the
    rebalance decision never points at a disconnecting layout."""
    rng = np.random.default_rng(seed)
    total = 12
    records = []
    for i in range(6):
        length = int(rng.integers(2, 6))
        start = int(rng.integers(0, total - length + 1))
        records.append(rec(f"p{i}", start, start + length,
                           float(rng.uniform(0.5, 5.0))))
    # ensure full coverage with a backstop server
    records.append(rec("backstop", 0, total, 0.25))
    for pid in [r.peer_id for r in records]:
        # must not raise, and must return a bool
        verdict = should_choose_other_blocks(
            pid, records, total_blocks=total, objective=objective,
            rng=np.random.default_rng(seed))
        assert verdict in (True, False)
