#!/usr/bin/env python
"""Kill a running swarm stage process — the reference's fault-injection tool
(``scripts/kill_stage.py:16-67``: grep ``ps aux`` for ``--stage N`` and
SIGTERM it) for the TCP swarm's process layout.

Targets processes running ``--mode serve`` (optionally filtered by
``--stage N`` or ``--peer_id``), the registry (``--registry``), or an
elastic server by pid order (``--nth``). Use while a client generates to
watch the failover path (docs/FAULT_TOLERANCE.md): the client must mark the
peer failed, re-discover, replay its journal, and keep producing tokens.

    python scripts/kill_stage.py --stage 2          # SIGTERM stage-2 server
    python scripts/kill_stage.py --nth 0 --signal 9 # SIGKILL first server
    python scripts/kill_stage.py --list             # show candidates only
"""

import argparse
import os
import signal
import subprocess
import sys


def _flag_value(tokens, flag):
    """Value of --flag in an argv token list; handles '--flag v' and
    '--flag=v'. None when absent."""
    for i, t in enumerate(tokens):
        if t == flag:
            return tokens[i + 1] if i + 1 < len(tokens) else None
        if t.startswith(flag + "="):
            return t.split("=", 1)[1]
    return None


def find_processes(stage=None, peer_id=None, registry=False):
    out = subprocess.run(["ps", "-eo", "pid,args"], capture_output=True,
                         text=True).stdout
    hits = []
    for line in out.splitlines()[1:]:
        line = line.strip()
        if not line:
            continue
        pid_s, _, args = line.partition(" ")
        if "main" not in args or str(os.getpid()) == pid_s:
            continue
        # Token-exact matching: substring tests would make --stage 1 match
        # '--stage 12' and --peer_id lb1 match 'lb10'.
        tokens = args.split()
        if _flag_value(tokens, "--mode") != ("registry" if registry
                                             else "serve"):
            continue
        if stage is not None and _flag_value(tokens, "--stage") != str(stage):
            continue
        if peer_id is not None and _flag_value(tokens, "--peer_id") != peer_id:
            continue
        hits.append((int(pid_s), args))
    return hits


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--stage", type=int, default=None,
                   help="fixed-split server stage number to kill")
    p.add_argument("--peer_id", default=None,
                   help="kill the server advertising this peer id")
    p.add_argument("--registry", action="store_true",
                   help="kill the registry process instead of a server")
    p.add_argument("--nth", type=int, default=None,
                   help="kill the nth matching process (pid order)")
    p.add_argument("--signal", type=int, default=signal.SIGTERM,
                   help="signal number (default SIGTERM; 9 = SIGKILL models "
                        "a hard crash — no TCP FIN until the OS cleans up)")
    p.add_argument("--list", action="store_true",
                   help="only print matching processes")
    args = p.parse_args()

    hits = sorted(find_processes(args.stage, args.peer_id, args.registry))
    if not hits:
        print("no matching swarm processes", file=sys.stderr)
        return 1
    if args.nth is not None:
        if args.nth >= len(hits):
            print(f"only {len(hits)} matches", file=sys.stderr)
            return 1
        hits = [hits[args.nth]]
    for pid, cmd in hits:
        print(f"{'would kill' if args.list else 'killing'} {pid}: {cmd[:120]}")
        if not args.list:
            os.kill(pid, args.signal)
    return 0


if __name__ == "__main__":
    sys.exit(main())
