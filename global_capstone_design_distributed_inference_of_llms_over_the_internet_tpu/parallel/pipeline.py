"""Fused ICI pipeline: all stages in ONE jitted program, ppermute between them.

This is the TPU-native replacement for the reference's per-hop
serialize → libp2p → deserialize data plane (``src/rpc_transport.py:744``,
``src/rpc_handler.py:422`` — its dominant latency term, SURVEY.md §3.2): when
the pipeline stages are co-located on one TPU slice, the whole multi-stage
step compiles to a single XLA program and inter-stage activations move
HBM-to-HBM over ICI via ``jax.lax.ppermute``. The client/transport path
(`runtime.client`) remains the elastic multi-host story; this is the hot path
(SURVEY.md §7.3 hard part 1: no host round-trips between stages).

Design (GPipe-style microbatching under ``shard_map``):

  * the mesh has one axis ``"stage"`` of size S; stacked layer params
    [L, ...] are reshaped to [S, L/S, ...] and sharded on the leading axis —
    each device holds exactly its span's weights;
  * embedding and lm_head run OUTSIDE the shard_map (embedding is a cheap
    replicated gather; the head runs once on the psum-collected final hidden)
    so the shard-mapped body is uniform across stages — no role dispatch,
    no wasted head FLOPs on intermediate stages;
  * the batch is split into M microbatches; the body runs M + S - 1 ticks in
    a ``lax.fori_loop``. Each tick every stage runs its span on its current
    microbatch and ppermutes the result to its successor; stage s processes
    microbatch ``t - s`` at tick t (valid iff 0 <= t-s < M). Invalid ticks
    (pipeline bubble) compute on garbage and their KV writes are masked out;
  * KV caches are [S, L/S, M, B_mb, max_len, Hkv, Dh], sharded on stage —
    each stage's cache never leaves its device.

Capability parity note: the reference has NO intra-program pipelining at all —
every hop re-enters Python and the WAN. Matching its 4-stage topology with
M=1 microbatch already removes the per-hop overhead; M>1 additionally hides
the pipeline bubble for batched serving.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.config import ModelConfig
from ..models.transformer import embed_tokens, lm_head, stack_forward

Params = Dict[str, Any]


def make_pipeline_mesh(num_stages: int, devices=None, tp: int = 1) -> Mesh:
    """1-D ("stage",) pipeline mesh, or 2-D ("stage", "tp") when tp > 1 —
    tensor parallelism nests INSIDE each pipeline stage's device group, so
    the per-stage psums ride the innermost (fastest) mesh axis."""
    need = num_stages * tp
    devices = devices if devices is not None else jax.devices()[:need]
    if len(devices) < need:
        raise ValueError(
            f"need {need} devices for the fused pipeline "
            f"({num_stages} stages x {tp} tp), have {len(devices)}"
        )
    import numpy as np

    arr = np.asarray(devices[:need])
    if tp == 1:
        return Mesh(arr, ("stage",))
    return Mesh(arr.reshape(num_stages, tp), ("stage", "tp"))


def stack_pipeline_params(params: Params, num_stages: int) -> Params:
    """Reshape stacked layers [L, ...] -> [S, L/S, ...] for stage sharding."""
    num_layers = jax.tree.leaves(params["layers"])[0].shape[0]
    if num_layers % num_stages:
        raise ValueError(
            f"fused pipeline needs equal spans: {num_layers} layers % "
            f"{num_stages} stages != 0 (uneven spans run on runtime.client)"
        )
    per = num_layers // num_stages
    return jax.tree.map(
        lambda x: x.reshape((num_stages, per) + x.shape[1:]), params["layers"]
    )


def _kv_spec(tp: int) -> P:
    """PartitionSpec for the pipeline KV cache laid out by `init_pipeline_kv`:
    [S, L/S, M, B, max_len, Hkv, Dh] — "stage" on axis 0, "tp" on the Hkv
    axis when TP is on. Single source of truth for build() and init_kv()."""
    return P("stage", None, None, None, None, "tp") if tp > 1 else P("stage")


def init_pipeline_kv(
    cfg: ModelConfig, num_stages: int, num_micro: int, micro_batch: int,
    max_len: int, dtype=jnp.float32,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    per = cfg.num_layers // num_stages
    shape = (num_stages, per, num_micro, micro_batch, max_len,
             cfg.num_kv_heads, cfg.head_dim)
    return jnp.zeros(shape, dtype), jnp.zeros(shape, dtype)


def _pipeline_layer_specs(cfg: ModelConfig, layers_stacked: Params,
                          tp: int) -> Params:
    """PartitionSpecs for the [S, L/S, ...] stacked layer tree: axis 0 on
    "stage", plus the TP table (axes shifted +1 for the stage dim) when
    tp > 1."""
    if tp == 1:
        return jax.tree.map(lambda _: P("stage"), layers_stacked)
    from .tensor_parallel import layer_partition_specs

    spec_for = layer_partition_specs(cfg, "tp")

    def f(path, _leaf):
        sub = spec_for(path)  # spec for the [L, ...] leaf (axis 0 = layers)
        parts = ["stage"] + list(sub)
        return P(*parts)

    return jax.tree_util.tree_map_with_path(f, layers_stacked)


def _pipeline_body(cfg: ModelConfig, num_stages: int, num_micro: int,
                   tp_axis: Optional[str] = None):
    """Builds the shard-mapped tick loop. Local views per stage device:
    layers [1, L/S, ...(tp-sharded dims)]; stream [M, B, T, D] (replicated);
    kv [1, L/S, M, B, max_len, Hkv(/tp), Dh]; positions [B, T] (replicated)."""

    def body(layers, stream, k_all, v_all, positions, cache_len):
        layers = jax.tree.map(lambda x: x[0], layers)   # [L/S, ...]
        k_all, v_all = k_all[0], v_all[0]               # [L/S, M, B, ...]
        s = jax.lax.axis_index("stage")
        is_last = s == num_stages - 1
        m, b, t, d = stream.shape
        perm = [(i, (i + 1) % num_stages) for i in range(num_stages)]

        def tick(ti, carry):
            received, k_all, v_all, outs = carry
            mb = ti - s
            valid = (mb >= 0) & (mb < num_micro)
            mbc = jnp.clip(mb, 0, num_micro - 1)
            x_in = jnp.where(
                s == 0,
                jax.lax.dynamic_index_in_dim(stream, mbc, 0, keepdims=False),
                received,
            )
            kc = jax.lax.dynamic_index_in_dim(k_all, mbc, 1, keepdims=False)
            vc = jax.lax.dynamic_index_in_dim(v_all, mbc, 1, keepdims=False)
            # kc/vc: [L/S, B, max_len, Hkv(/tp), Dh]
            out, nk, nv = stack_forward(
                cfg, layers, x_in, positions, kc, vc, cache_len,
                tp_axis=tp_axis,
            )
            # Mask bubble ticks: garbage KV writes must not land.
            nk = jnp.where(valid, nk, kc)
            nv = jnp.where(valid, nv, vc)
            k_all = jax.lax.dynamic_update_index_in_dim(k_all, nk, mbc, 1)
            v_all = jax.lax.dynamic_update_index_in_dim(v_all, nv, mbc, 1)
            outs = jnp.where(
                is_last & valid,
                jax.lax.dynamic_update_index_in_dim(outs, out, mbc, 0),
                outs,
            )
            received = jax.lax.ppermute(out, "stage", perm)
            return received, k_all, v_all, outs

        received = jax.lax.pcast(
            jnp.zeros((b, t, d), stream.dtype), ("stage",), to="varying"
        )
        outs = jax.lax.pcast(
            jnp.zeros((m, b, t, d), stream.dtype), ("stage",), to="varying"
        )
        received, k_all, v_all, outs = jax.lax.fori_loop(
            0, num_micro + num_stages - 1, tick,
            (received, k_all, v_all, outs),
        )
        # Only the last stage populated outs; psum replicates it everywhere.
        outs = jax.lax.psum(
            jnp.where(is_last, outs, jnp.zeros_like(outs)), "stage"
        )
        return outs, k_all[None], v_all[None]

    return body


@dataclasses.dataclass
class IciPipeline:
    """Compiled fused-pipeline runner. Holds the mesh + jitted step.

    Usage::

        pipe = IciPipeline.build(cfg, params, num_stages=4, num_micro=2)
        logits, kv = pipe.forward(ids, kv, cache_len)   # prefill or decode
    """

    cfg: ModelConfig
    mesh: Mesh
    num_stages: int
    num_micro: int
    tp: int
    embed: Params               # replicated
    head: Params                # replicated: final_norm (+ lm_head / tied wte)
    layers_stacked: Params      # [S, L/S, ...] sharded on stage (+ tp dims)
    _step: Any

    @staticmethod
    def build(
        cfg: ModelConfig,
        params: Params,
        num_stages: int,
        num_micro: int = 1,
        mesh: Optional[Mesh] = None,
        tp: int = 1,
    ) -> "IciPipeline":
        if tp > 1:
            from .tensor_parallel import validate_tp

            validate_tp(cfg, tp)
        mesh = mesh or make_pipeline_mesh(num_stages, tp=tp)
        if mesh.shape.get("stage") != num_stages or mesh.shape.get("tp", 1) != tp:
            raise ValueError(
                f"mesh axes {dict(mesh.shape)} do not match num_stages="
                f"{num_stages}, tp={tp} — pass the same tp to both "
                "make_pipeline_mesh and build"
            )
        layers = stack_pipeline_params(params, num_stages)
        if tp == 1:
            # Engine-side fused QKV + gate/up layouts (bitwise-identical;
            # TP keeps the canonical splits so its per-projection shard
            # boundaries hold).
            from ..models.transformer import (
                fuse_gate_up_layers,
                fuse_qkv_layers,
            )

            layers = fuse_gate_up_layers(fuse_qkv_layers(layers))
        layer_specs = _pipeline_layer_specs(cfg, layers, tp)
        layers = jax.tree.map(
            lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
            layers, layer_specs,
        )
        repl = NamedSharding(mesh, P())
        embed = jax.device_put(params["embed"], repl)
        head = {"final_norm": params["final_norm"]}
        if cfg.tie_word_embeddings:
            head["embed"] = {"wte": params["embed"]["wte"]}
        else:
            head["lm_head"] = params["lm_head"]
        head = jax.device_put(head, repl)

        tp_axis = "tp" if tp > 1 else None
        body = _pipeline_body(cfg, num_stages, num_micro, tp_axis=tp_axis)
        spec_kv = _kv_spec(tp)

        # Donation stays UNgated here (cf. utils.platform.engine_donation):
        # the fused pipeline is a single-controller engine — one thread owns
        # the mesh and every dispatch — so the CPU async-dispatch/free race
        # the serving engines gate against has no second thread to race.
        @partial(jax.jit, donate_argnums=(3, 4))
        def step(embed_p, head_p, layers_p, k_all, v_all, ids, cache_len):
            m, b, t = ids.shape
            positions = cache_len + jnp.arange(t, dtype=jnp.int32)[None, :]
            # Replicated embedding gather for the whole stream [M, B, T, D].
            x = jax.vmap(
                lambda i: embed_tokens(cfg, embed_p, i, positions)
            )(ids)
            sharded = shard_map(
                body,
                mesh=mesh,
                in_specs=(layer_specs, P(), spec_kv, spec_kv, P(), P()),
                out_specs=(P(), spec_kv, spec_kv),
            )
            outs, k_all, v_all = sharded(
                layers_p, x, k_all, v_all,
                jnp.broadcast_to(positions, (b, t)), cache_len,
            )
            # Head once, on the collected final hidden [M, B, T, D].
            logits = jax.vmap(lambda h: lm_head(cfg, head_p, h))(outs)
            return logits, k_all, v_all

        return IciPipeline(
            cfg=cfg, mesh=mesh, num_stages=num_stages, num_micro=num_micro,
            tp=tp, embed=embed, head=head, layers_stacked=layers, _step=step,
        )

    def init_kv(self, micro_batch: int, max_len: int, dtype=jnp.float32):
        k, v = init_pipeline_kv(
            self.cfg, self.num_stages, self.num_micro, micro_batch, max_len, dtype
        )
        sh = NamedSharding(self.mesh, _kv_spec(self.tp))
        return jax.device_put(k, sh), jax.device_put(v, sh)

    def forward(
        self,
        ids: jnp.ndarray,            # [M, B, T] int32 microbatched token ids
        k_all: jnp.ndarray,
        v_all: jnp.ndarray,
        cache_len: jnp.ndarray,      # scalar int32
    ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
        """One pipelined forward over all stages. Returns
        (logits [M, B, T, V], new k, new v)."""
        if ids.shape[0] != self.num_micro:
            raise ValueError(
                f"ids has {ids.shape[0]} microbatches, pipeline compiled for "
                f"{self.num_micro} (the clamped tick indexing would silently "
                "corrupt outputs otherwise)"
            )
        if ids.shape[1] != k_all.shape[3]:
            raise ValueError(
                f"ids micro-batch size {ids.shape[1]} != KV cache batch "
                f"{k_all.shape[3]}"
            )
        return self._step(
            self.embed, self.head, self.layers_stacked, k_all, v_all,
            ids, cache_len,
        )
