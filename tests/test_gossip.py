"""Decentralized control plane (round 5): the gossip-replicated registry.

Every ``--mode serve`` process embeds a GossipNode — a version-stamped
record store whose merge is a deterministic semilattice join (newest seq
wins, tombstone beats live on ties) — and answers the registry service's
verbs from its mirror, so ANY live stage server can bootstrap a client
after every seed registry dies. The reference build gets this property
from the Kademlia DHT (``src/dht_utils.py``); here it is explicit
anti-entropy over the existing framed-TCP plane.

The convergence property test and the in-process registry-loss soak are
the PR's acceptance bars; the rest pins the wire contract piece by piece.
"""

import os
import random
import subprocess
import sys
import time

import jax
import pytest

from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu import (
    main as main_mod,
)
from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu import (
    telemetry,
)
from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.models import (
    init_params,
)
from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.models.partition import (
    parse_splits,
)
from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.runtime.faults import (
    FaultPlan,
    FaultRule,
)
from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.runtime.net import (
    RegistryServer,
    RemoteRegistry,
    TcpStageServer,
    gossip_exchange,
)
from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.scheduling.gossip import (
    GossipNode,
)
from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.scheduling.registry import (
    ServerRecord,
    rec_to_dict,
)
from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.telemetry import (
    catalog,
    events,
)

from test_runtime_pipeline import tiny_cfg


def _rec(peer, stage=1, addr="127.0.0.1:1"):
    return ServerRecord(peer_id=peer, start_block=0, end_block=4,
                        stage_index=stage, address=addr)


def _wire(origin, seq, dead=False, ttl_s=30.0, window=45.0,
          addr="127.0.0.1:1"):
    """One gossip wire entry, as delta_for would encode it."""
    return {"origin": origin, "seq": seq, "dead": dead,
            "rec": None if dead else rec_to_dict(_rec(origin, addr=addr)),
            "window": window, "ttl_s": ttl_s}


def _mirror_server(peer_id, **kw):
    """An executor-less stage server with an embedded gossip mirror — the
    control-plane surface without the data plane."""
    node = GossipNode(peer_id, ttl=30.0, rng=random.Random(0))
    srv = TcpStageServer(None, wire_dtype="f32", peer_id=peer_id,
                         gossip=node, **kw)
    srv.start()
    node.self_address = srv.address
    return node, srv


# -- merge semantics (the semilattice join) -----------------------------------

def test_merge_newest_seq_wins_in_any_order():
    """Applying versions out of order converges to the same state as in
    order: seq is the total order, not arrival time."""
    new = _wire("pA", 2, addr="127.0.0.1:2")
    old = _wire("pA", 1, addr="127.0.0.1:1")

    fwd = GossipNode("n0", ttl=30.0)
    assert fwd.merge([old]) == 1
    assert fwd.merge([new]) == 1
    rev = GossipNode("n1", ttl=30.0)
    assert rev.merge([new]) == 1
    assert rev.merge([old]) == 0        # stale version changes nothing

    assert fwd.digest() == rev.digest() == {"pA": 2}
    for n in (fwd, rev):
        assert [r.address for r in n.live_servers()] == ["127.0.0.1:2"]


def test_tombstone_blocks_resurrection_until_newer_live_version():
    """A circulating tombstone beats any OLDER live version (and the
    equal-seq tie), so a slow replica can't resurrect an unregistered
    peer; a strictly newer live version (the peer actually came back)
    wins immediately."""
    n = GossipNode("n0", ttl=30.0)
    n.merge([_wire("pA", 3, dead=True, ttl_s=60.0, window=60.0)])
    assert n.live_count() == 0

    assert n.merge([_wire("pA", 2)]) == 0       # older live: rejected
    assert n.merge([_wire("pA", 3)]) == 0       # tie: tombstone wins
    assert n.live_count() == 0
    assert n.digest() == {"pA": 3}

    assert n.merge([_wire("pA", 4)]) == 1       # genuine rejoin
    assert [r.peer_id for r in n.live_servers()] == ["pA"]


def test_tombstone_expires_after_grace():
    """Tombstones are garbage-collected after their grace window — the
    deletion stops being re-announced instead of circulating forever."""
    n = GossipNode("n0", ttl=30.0)
    n.merge([_wire("pA", 5, dead=True, ttl_s=0.05, window=0.05)])
    assert n.digest() == {"pA": 5}
    time.sleep(0.1)
    assert n.digest() == {}
    # After the grace the origin may legitimately start over at seq 1.
    assert n.merge([_wire("pA", 1)]) == 1
    assert [r.peer_id for r in n.live_servers()] == ["pA"]


def test_convergence_property_randomized_delivery_orders():
    """The acceptance property: N replicas receiving the same version set
    in DIFFERENT (seeded) orders, with duplicates and arbitrary batch
    splits, end with identical digests and identical live sets —
    tombstones included."""
    master = random.Random(1234)
    origins = [f"p{i}" for i in range(6)]
    versions = []
    want_digest = {}
    want_live = []
    for i, origin in enumerate(origins):
        top = master.randint(1, 4)
        ends_dead = i < 2               # two origins end tombstoned
        for seq in range(1, top + 1):
            versions.append(_wire(origin, seq,
                                  dead=ends_dead and seq == top,
                                  ttl_s=60.0, window=90.0,
                                  addr=f"10.0.0.{i}:{seq}"))
        want_digest[origin] = top
        if not ends_dead:
            want_live.append(origin)

    nodes = [GossipNode(f"n{k}", ttl=60.0, tombstone_grace_s=120.0,
                        rng=random.Random(k)) for k in range(4)]
    for k, node in enumerate(nodes):
        rng = random.Random(9000 + k)
        feed = list(versions) + rng.sample(versions, len(versions) // 2)
        rng.shuffle(feed)
        while feed:
            batch = [feed.pop()
                     for _ in range(min(len(feed), rng.randint(1, 5)))]
            node.merge(batch)

    for node in nodes:
        assert node.digest() == want_digest
        assert sorted(r.peer_id for r in node.live_servers()) == \
            sorted(want_live)


# -- the wire: anti-entropy rounds and the mirror's registry verbs ------------

def test_gossip_exchange_converges_both_sides():
    """One digest-then-delta round leaves BOTH mirrors with the union:
    the response delta teaches the initiator, the push-back teaches the
    responder."""
    na, sa = _mirror_server("na")
    nb, sb = _mirror_server("nb")
    try:
        na.publish(rec_to_dict(_rec("pa", addr="127.0.0.1:21")))
        nb.publish(rec_to_dict(_rec("pb", addr="127.0.0.1:22")))
        sent, merged = gossip_exchange(na, sb.address)
        assert sent == 1 and merged == 1
        assert {r.peer_id for r in na.live_servers()} == {"pa", "pb"}
        assert {r.peer_id for r in nb.live_servers()} == {"pa", "pb"}
        assert na.digest() == nb.digest()
    finally:
        sa.stop()
        sb.stop()


def test_stage_server_answers_registry_verbs():
    """Any-peer bootstrap: a RemoteRegistry pointed at a STAGE SERVER
    speaks the registry service unmodified — register, the heartbeat
    known/unknown contract, list, unregister."""
    node, srv = _mirror_server("mirror")
    try:
        rr = RemoteRegistry(srv.address)
        rr.register(_rec("p1", addr="127.0.0.1:9"))
        assert rr.heartbeat("p1") is True
        assert rr.heartbeat("ghost") is False    # re-register trigger
        assert [r.peer_id for r in rr.live_servers()] == ["p1"]
        rr.unregister("p1")
        assert rr.live_servers() == []
        assert "p1" in node.digest()             # tombstone circulates
    finally:
        srv.stop()


def test_gossip_drop_fault_then_reconverge():
    """The chaos layer's gossip_drop kind swallows one anti-entropy frame
    (the initiator's round dies on read timeout); the NEXT round sails
    through and the mirror still converges."""
    node, srv = _mirror_server("flaky", allow_fault_injection=True)
    try:
        other = GossipNode("initiator", ttl=30.0)
        other.publish(rec_to_dict(_rec("pc", addr="127.0.0.1:31")))
        srv.fault_plan = FaultPlan(
            [FaultRule("gossip_drop", side="server", verb="gossip",
                       times=1)])
        with pytest.raises((TimeoutError, OSError)):
            gossip_exchange(other, srv.address, timeout=0.6)
        assert node.live_count() == 0            # the frame really died
        gossip_exchange(other, srv.address, timeout=5.0)
        assert {r.peer_id for r in node.live_servers()} == {"pc"}
    finally:
        srv.stop()


# -- total-outage survival (client side) --------------------------------------

def test_peers_cache_bootstraps_fresh_client_through_mirror(tmp_path):
    """A FRESH client with an empty snapshot and every seed dead finds the
    swarm through the --peers_cache file + a stage server's mirror, and
    the fallback is surfaced (event + counter)."""
    telemetry.enable()
    events.get_recorder().enable()
    cache = str(tmp_path / "peers.json")
    node, srv = _mirror_server("gs1")
    seed = RegistryServer()
    seed.start()
    try:
        rec = _rec("gs1", addr=srv.address)
        node.publish(rec_to_dict(rec))
        rr1 = RemoteRegistry(seed.address, peers_cache=cache)
        rr1.register(rec)
        assert [r.peer_id for r in rr1.live_servers()] == ["gs1"]
        assert os.path.exists(cache)             # snapshot persisted

        seed.stop()
        fallback = catalog.get("client_registry_fallback_reads_total")
        before = fallback.value
        rr2 = RemoteRegistry(seed.address, timeout=0.5, peers_cache=cache)
        recs = rr2.live_servers()                # dead seed, cache → mirror
        assert [r.peer_id for r in recs] == ["gs1"]
        assert fallback.value == before + 1
        names = [e.name for e in events.get_recorder().events()]
        assert "gossip_fallback" in names
        assert rr2.stale_info()["seeds_down"]
    finally:
        srv.stop()
        seed.stop()


def test_stale_serve_and_recovery_are_surfaced():
    """Satellite: serving from the stale snapshot is an OBSERVABLE
    degradation — registry_stale_serve + the stale-reads counter while the
    seeds are down, registry_recovered once a seed answers again."""
    telemetry.enable()
    recorder = events.get_recorder()
    recorder.enable()
    a = RegistryServer()
    a.start()
    host, port = a.address.rsplit(":", 1)
    rr = RemoteRegistry(a.address, timeout=0.5)
    rec = _rec("p1")                    # address 127.0.0.1:1 — no mirror
    rr.register(rec)
    assert [r.peer_id for r in rr.live_servers()] == ["p1"]

    stale = catalog.get("client_registry_stale_reads_total")
    before = stale.value
    a.stop()
    assert [r.peer_id for r in rr.live_servers()] == ["p1"]   # TTL grace
    assert stale.value == before + 1
    info = rr.stale_info()
    assert info["seeds_down"] and info["stale"]
    names = [e.name for e in recorder.events()]
    assert "registry_unreachable" in names
    assert "registry_stale_serve" in names

    a2 = RegistryServer(host=host, port=int(port))
    a2.start()
    try:
        rr.register(rec)                # what the serve heartbeat loop does
        assert [r.peer_id for r in rr.live_servers()] == ["p1"]
        info = rr.stale_info()
        assert not info["seeds_down"] and not info["stale"]
        recovered = [e for e in recorder.events()
                     if e.name == "registry_recovered"]
        assert recovered and recovered[-1].fields.get("source") == "seed"
    finally:
        a2.stop()


def test_registry_loss_soak_inprocess(tmp_path):
    """The tentpole's acceptance scenario, tier-1 edition: primary AND
    standby killed deterministically mid-generation — the in-flight
    generation and a fresh mirror-bootstrapped client both produce the
    clean run's exact tokens, a restarted seed is re-adopted, and the
    doctor reconstructs the outage as one failure chain."""
    cfg = tiny_cfg()
    params = init_params(jax.random.PRNGKey(0), cfg)
    res = main_mod.registry_loss_soak(
        cfg, params, prompt_ids=[5, 9, 23, 7, 81], max_new_tokens=5,
        seed=0, splits=parse_splits("3,6"),
        peers_cache=str(tmp_path / "peers.json"))
    assert res["ok"], res["problems"]
    assert res["tokens_chaos"] == res["tokens_clean"]
    assert res["tokens_bootstrap"] == res["tokens_clean"]
    assert res["chains"], "doctor found no registry-outage chain"


@pytest.mark.slow
def test_chaos_swarm_kill_registries_drill():
    """Multi-process twin: scripts/chaos_swarm.py --kill_registries
    SIGKILLs both seed registries under a live client; the in-flight
    client must finish and a second, freshly started client must
    bootstrap through a stage server's gossip mirror."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "scripts", "chaos_swarm.py"),
         "--kill_registries", "--splits", "4",
         "--max_new_tokens", "6", "--registry_port", "31377"],
        cwd=repo, env=env, timeout=900,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    out = proc.stdout.decode(errors="replace")
    assert proc.returncode == 0, out
    assert "REGISTRY-LOSS DRILL PASS" in out
