"""Continuous batching: many concurrent sessions, ONE decode step.

The reference serves each session's decode step as its own forward
(``src/rpc_handler.py:149-325`` — one request, one compute); N concurrent
clients cost N sequential forwards per token. On TPU the idiomatic fix is
STATIC-SHAPE slot batching (the shape-stable cousin of vLLM-style
continuous batching): the server owns one slot-major KV cache
``[L, S, max_len, Hkv, Dh]``, every live session occupies a slot, and one
jitted step advances EVERY active slot at once — per-slot cache lengths, an
active mask for empty slots, zero gathers/copies of cache rows. Compute
scales with the slot count S (the server's intended concurrency), not with
how many requests happen to arrive, and the step is one compiled program
replayed forever.

Sessions join at prefill (slot allocated, prompt written into the slot's
rows), decode via `decode_batch` (whatever subset of sessions has a token
ready — inactive slots are masked), and leave via `end_session` (slot
recycled). Token parity with the per-session oracle is asserted in
tests/test_batching.py.

Scope: the batched path covers plain greedy/sampled decode AND speculative
verification — a draft step is rows of [last_accepted, d_1..d_K], i.e. a
multi-token batched forward plus per-row accept/reject, so spec sessions
coalesce the same way plain ones do (rounds are keyed by step width T; all
requests in a round share one compiled step). Beam reorder and training
still ride the per-session StageExecutor — servers route those requests to
it unchanged. Replay is accepted (prefill + multi-token KV rebuild rounds)
so a replacement batched peer can adopt a failed-over burst session.

BURST DECODE (the continuous-batching serving core, ROADMAP Open item 1):
a FULL-SPAN batched engine can additionally run N decode ticks in ONE
jitted dispatch — ``lax.scan`` over ticks, each tick embedding the carry
token, running the layer scan, sampling ON DEVICE with the session-local
seed schedule ``PRNGKey(step_seed + i)`` (bit-identical to the sequential
``_sample_rows`` path), and maintaining per-slot alive masks so eos /
repeat / budget stops truncate mid-scan without a host round trip. The
host pays one dispatch per N tokens instead of one per token, and
``burst_stream`` double-buffers dispatch k+1 against burst k's readback.
"""

from __future__ import annotations

import threading
import time
from functools import partial
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..models.config import ModelConfig
from ..utils.platform import engine_donation
from ..models.partition import StageSpec
from ..models.transformer import (
    _dot,
    _mlp,
    _norm,
    embed_tokens,
    make_rope,
    qkv_proj,
)
from ..ops.rotary import apply_rope
from ..parallel.ring_attention import NEG_INF
from ..telemetry import catalog as _tm
from ..telemetry import events as _ev
from ..telemetry.profiling import get_profiler as _get_profiler
from .errors import register as _catalog
from .kv_cache import round_to_bucket

Params = Dict[str, Any]


def _burst_entry(rq) -> dict:
    """A StageRequest's burst spec in the engine's stateless per-burst form
    (everything the wire ships every step, so failover needs no server-side
    sampler state — the module-docstring contract)."""
    sp = rq.sampling
    return {
        "token": int(np.asarray(rq.hidden).reshape(-1)[0]),
        "seed": int(rq.step_seed),
        "budget": int(rq.burst_budget),
        "eos": rq.eos_token_id,
        "generated": rq.generated_tokens,
        "temperature": sp.temperature,
        "top_p": sp.top_p,
        "top_k": sp.top_k,
        "repetition_penalty": sp.repetition_penalty,
    }

PREFILL_BUCKETS = (8, 16, 32, 64, 128, 256, 512, 1024)

# The client's repeat-stop heuristic (runtime.client.REPEAT_STOP), mirrored
# on device so a burst truncates exactly where the sequential host loop
# would have stopped. Keep the two in lockstep.
BURST_REPEAT_STOP = 5


@_catalog
class SlotFull(RuntimeError):
    """No free slot (admission control — the caller queues or fails over)."""


# -- gemma2-aware layer pieces shared by the three batched bodies ----------

def _qscale(cfg) -> float:
    """Attention score scale (gemma2 query_pre_attn_scalar override)."""
    return cfg.query_scale or cfg.head_dim ** -0.5


def _layer_mask(lp, mask, q_pos, k_pos):
    """Intersect the body's mask with this layer's window (the traced
    "window" leaf of alternating local/global models — gemma2): <= 0
    means global. q_pos/k_pos are broadcastable position grids matching
    the mask's trailing dims. The int32 cast is load-bearing: a dtype
    sweep over the layer tree (checkpoint conversion at bf16) would
    otherwise compute the window boundary in bfloat16 and mis-mask keys
    past position ~256."""
    w = lp.get("window")
    if w is None:
        return mask
    w = jnp.asarray(w, jnp.int32)
    return mask & ((k_pos > q_pos - w) | (w <= 0))


def _softcap_and_mask(cfg, scores, allowed):
    """Softcap scores (gemma2 attn_logit_softcapping, pre-mask) then mask."""
    if cfg.attn_softcap:
        scores = cfg.attn_softcap * jnp.tanh(scores / cfg.attn_softcap)
    return jnp.where(allowed, scores, NEG_INF)


def _residual(cfg, lp, h, attn_out):
    """Residual + MLP with optional sandwich norms (gemma2 post_norms:
    ln3 after attention, ln4 after the MLP, before each residual add)."""
    if cfg.post_norms:
        attn_out = _norm(cfg, lp["ln3"], attn_out)
    h = h + attn_out
    mlp_out = _mlp(cfg, lp["mlp"], _norm(cfg, lp["ln2"], h), None)
    if cfg.post_norms:
        mlp_out = _norm(cfg, lp["ln4"], mlp_out)
    return h + mlp_out


class BatchedStageExecutor:
    """One stage span serving up to `slots` sessions with batched decode."""

    def __init__(
        self,
        cfg: ModelConfig,
        spec: StageSpec,
        params: Params,
        *,
        slots: int = 8,
        max_len: int = 2048,
        dtype=jnp.float32,
        prefix_cache_bytes: int = 0,
        model: Optional[str] = None,
    ):
        self.cfg = cfg
        self.spec = spec
        # Model tag for prefix-store digest coords: two models with the same
        # span indices must never share cache entries (multi-model serving).
        self.model = model
        # Engine-side fused-QKV layout (one projection matmul per layer,
        # bitwise-identical — models/transformer.fuse_qkv_params).
        from ..models.transformer import fuse_qkv_params

        self.params = params = fuse_qkv_params(params)
        self.slots = slots
        self.max_len = max_len
        self.dtype = jnp.dtype(dtype)
        l = max(spec.num_layers, 1)
        shape = (l, slots, max_len, cfg.num_kv_heads, cfg.head_dim)
        self.k = jnp.zeros(shape, self.dtype)
        self.v = jnp.zeros(shape, self.dtype)
        self.lengths = np.zeros((slots,), np.int32)   # host-side truth
        self._slot_of: Dict[str, int] = {}
        self._free: List[int] = list(range(slots))
        self.decode_steps = 0                          # batched steps executed
        self._prefill_jit = None
        self._decode_jits: Dict[int, Any] = {}         # step width T -> jit
        # Burst decode (full-span engines only): n_ticks -> jitted scan.
        self._burst_jits: Dict[int, Any] = {}
        self.burst_dispatches = 0          # burst programs executed
        self.burst_tokens = 0              # tokens emitted by bursts
        self._m_burst_ticks = _tm.get("server_burst_ticks")
        self._m_burst_disp = _tm.get("server_burst_dispatches_total")
        self._m_burst_toks = _tm.get("server_burst_tokens_total")
        # Prompt-prefix KV reuse (runtime.prefix_cache), slot-layout
        # variant: entries hold [L, G, Hkv, Dh] KV segments (+ [1, G, D]
        # output rows off the final stage). Same grain-chained rolling
        # digests as the session executor's store.
        self.prefix_store = None
        if prefix_cache_bytes > 0:
            from .prefix_cache import PrefixStore

            self.prefix_store = PrefixStore(prefix_cache_bytes)
        self._suffix_jit = None
        self._chain_write_jit = None
        self._grain_split_jits: Dict[tuple, Any] = {}

    # ------------------------------------------------------------------
    # Slots
    # ------------------------------------------------------------------

    def slot(self, session_id: str) -> Optional[int]:
        return self._slot_of.get(session_id)

    def _alloc(self, session_id: str) -> int:
        old = self._slot_of.pop(session_id, None)
        if old is not None:                  # re-prefill restarts the session
            self._free.append(old)
        if not self._free:
            raise SlotFull(f"all {self.slots} session slots in use")
        s = self._free.pop()
        self._slot_of[session_id] = s
        return s

    def end_session(self, session_id: str) -> None:
        s = self._slot_of.pop(session_id, None)
        if s is not None:
            self.lengths[s] = 0
            self._free.append(s)

    def rewind(self, session_id: str, pos: int) -> None:
        """Shrink a session's valid KV prefix to `pos` (the
        ``start_from_position`` semantics of petals handler.py:163-168,
        reused as speculative rollback). Host-side only: rows past `pos`
        are never attended (the decode mask allows positions <= length)
        and are overwritten as the session advances."""
        s = self._slot_of.get(session_id)
        if s is None:
            raise KeyError(f"unknown session {session_id}")
        if not 0 <= pos <= int(self.lengths[s]):
            raise ValueError(
                f"rewind to {pos} outside [0, {int(self.lengths[s])}]")
        self.lengths[s] = pos

    # ------------------------------------------------------------------
    # Prefill: per-session, writes the prompt's KV into the slot's rows
    # ------------------------------------------------------------------

    def _build_prefill(self):
        cfg, spec = self.cfg, self.spec

        @partial(jax.jit, donate_argnums=engine_donation(3, 4))
        def fn(params, x, slot, k_all, v_all, t_real):
            b = 1
            t = x.shape[1]
            positions = jnp.arange(t, dtype=jnp.int32)[None, :]
            h = (embed_tokens(cfg, params["embed"], x, positions)
                 if spec.is_first else x)
            rope = make_rope(cfg, positions)
            # Causal self-attention over the fresh prompt (prefill restarts
            # the session, so there is no prior cache to attend to). O(T^2)
            # scores — long prompts belong to the sp engine or the chunked
            # per-session executor.
            causal = jnp.tril(jnp.ones((t, t), bool))
            valid = jnp.arange(t)[None, :] < t_real       # mask pad columns
            mask = causal & valid
            rows = jnp.arange(t)[:, None]
            cols = jnp.arange(t)[None, :]
            if cfg.sliding_window:
                # Mistral-style local attention: row i sees cols
                # (i - window, i].
                mask &= cols > rows - cfg.sliding_window

            def layer(h, lp):
                from ..models.quant import dequant_tree

                lp = dequant_tree(lp, keep_experts=cfg.is_moe)
                a = _norm(cfg, lp["ln1"], h)
                q, k, v = qkv_proj(cfg, lp["attn"], a)
                if rope is not None:
                    q = apply_rope(q, *rope)
                    k = apply_rope(k, *rope)
                groups = cfg.num_heads // cfg.num_kv_heads
                qg = q.reshape(b, t, cfg.num_kv_heads, groups, cfg.head_dim)
                scores = jnp.einsum(
                    "bthgd,bshd->bhgts", qg * _qscale(cfg), k,
                    preferred_element_type=jnp.float32)
                m = _layer_mask(lp, mask, rows, cols)
                scores = _softcap_and_mask(cfg, scores, m[None, None, None])
                probs = jax.nn.softmax(scores, axis=-1)
                out = jnp.einsum("bhgts,bshd->bthgd",
                                 probs.astype(v.dtype), v)
                out = _dot(out.reshape(b, t, -1), lp["attn"]["wo"])
                if "bo" in lp["attn"]:
                    out = out + lp["attn"]["bo"]
                h = _residual(cfg, lp, h, out)
                return h, (k[0], v[0])

            h, (ks, vs) = jax.lax.scan(layer, h, params["layers"])
            # ks/vs: [L, T, Hkv, Dh] -> write rows [slot, 0:T).
            k_all = jax.lax.dynamic_update_slice(
                k_all, ks[:, None].astype(k_all.dtype),
                (0, slot, 0, 0, 0))
            v_all = jax.lax.dynamic_update_slice(
                v_all, vs[:, None].astype(v_all.dtype),
                (0, slot, 0, 0, 0))
            return h, k_all, v_all

        return fn

    def _build_prefill_suffix(self):
        """Prefill CONTINUATION for a prefix-cache hit: the suffix enters at
        position p_len and attends over the slot's cache rows (the copied
        prefix) plus its own fresh keys — the slot-batched analogue of the
        session executor's chunked continuation."""
        cfg, spec = self.cfg, self.spec

        @partial(jax.jit, donate_argnums=engine_donation(3, 4))
        def fn(params, x, slot, k_all, v_all, p_len, t_real):
            b = 1
            t = x.shape[1]
            positions = p_len + jnp.arange(t, dtype=jnp.int32)[None, :]
            h = (embed_tokens(cfg, params["embed"], x, positions)
                 if spec.is_first else x)
            rope = make_rope(cfg, positions)
            groups = cfg.num_heads // cfg.num_kv_heads
            m = k_all.shape[2]
            pos_grid = jnp.arange(m, dtype=jnp.int32)
            qpos = positions[0][:, None]                     # [T, 1]
            allowed = pos_grid[None, :] <= qpos              # [T, M] causal
            if cfg.sliding_window:
                allowed &= pos_grid[None, :] > qpos - cfg.sliding_window
            k_slot = jax.lax.dynamic_index_in_dim(k_all, slot, 1,
                                                  keepdims=False)
            v_slot = jax.lax.dynamic_index_in_dim(v_all, slot, 1,
                                                  keepdims=False)

            def layer(h, xs):
                from ..models.quant import dequant_tree

                lp, k_l, v_l = xs                    # k_l: [M, Hkv, Dh]
                lp = dequant_tree(lp, keep_experts=cfg.is_moe)
                a = _norm(cfg, lp["ln1"], h)
                q, k, v = qkv_proj(cfg, lp["attn"], a)
                if rope is not None:
                    q = apply_rope(q, *rope)
                    k = apply_rope(k, *rope)
                k_l = jax.lax.dynamic_update_slice_in_dim(
                    k_l, k[0].astype(k_l.dtype), p_len, 0)
                v_l = jax.lax.dynamic_update_slice_in_dim(
                    v_l, v[0].astype(v_l.dtype), p_len, 0)
                qg = q.reshape(b, t, cfg.num_kv_heads, groups, cfg.head_dim)
                scores = jnp.einsum(
                    "bthgd,shd->bhgts", qg * _qscale(cfg),
                    k_l.astype(q.dtype),
                    preferred_element_type=jnp.float32)
                m = _layer_mask(lp, allowed, qpos, pos_grid[None, :])
                scores = _softcap_and_mask(cfg, scores, m[None, None, None])
                probs = jax.nn.softmax(scores, axis=-1)
                out = jnp.einsum("bhgts,shd->bthgd",
                                 probs.astype(v_l.dtype),
                                 v_l.astype(q.dtype))
                out = _dot(out.reshape(b, t, -1), lp["attn"]["wo"])
                if "bo" in lp["attn"]:
                    out = out + lp["attn"]["bo"]
                h = _residual(cfg, lp, h, out)
                return h, (k_l, v_l)

            h, (ks, vs) = jax.lax.scan(
                layer, h, (params["layers"], k_slot, v_slot))
            k_all = jax.lax.dynamic_update_slice(
                k_all, ks[:, None], (0, slot, 0, 0, 0))
            v_all = jax.lax.dynamic_update_slice(
                v_all, vs[:, None], (0, slot, 0, 0, 0))
            del t_real  # mask correctness needs only qpos; kept for parity
            return h, k_all, v_all

        return fn

    def _write_prefix_chain(self, slot: int, chain) -> None:
        """Write a chain's KV segments into the slot's leading cache rows
        in ONE jitted dispatch (specialized per chain length)."""
        if self._chain_write_jit is None:
            @partial(jax.jit, donate_argnums=engine_donation(0, 1))
            def fn(k_all, v_all, slot, segs_k, segs_v):
                kc = (segs_k[0] if len(segs_k) == 1
                      else jnp.concatenate(segs_k, axis=1))
                vc = (segs_v[0] if len(segs_v) == 1
                      else jnp.concatenate(segs_v, axis=1))
                start = (0, slot, 0, 0, 0)
                return (jax.lax.dynamic_update_slice(
                            k_all, kc[:, None].astype(k_all.dtype), start),
                        jax.lax.dynamic_update_slice(
                            v_all, vc[:, None].astype(v_all.dtype), start))

            self._chain_write_jit = fn
        self.k, self.v = self._chain_write_jit(
            self.k, self.v, jnp.int32(slot),
            [e.k for e in chain], [e.v for e in chain])

    def _split_grains(self, slot: int, n_grains: int, grain: int):
        """All grain KV segments of a slot's leading rows as one jitted
        call (n outputs, ONE dispatch — eager per-grain slicing would pay
        a device round trip per grain on registration)."""
        key = (n_grains, grain)
        fn = self._grain_split_jits.get(key)
        if fn is None:
            @jax.jit
            def fn(k_all, v_all, slot):
                k_s = jax.lax.dynamic_index_in_dim(k_all, slot, 1,
                                                   keepdims=False)
                v_s = jax.lax.dynamic_index_in_dim(v_all, slot, 1,
                                                   keepdims=False)
                return ([k_s[:, g * grain:(g + 1) * grain]
                         for g in range(n_grains)],
                        [v_s[:, g * grain:(g + 1) * grain]
                         for g in range(n_grains)])

            self._grain_split_jits[key] = fn
        return fn(self.k, self.v, jnp.int32(slot))

    def prefill(self, session_id: str, x, prefix_len: int = 0) -> jnp.ndarray:
        """Join/restart a session: x = ids [1, T] (first stage) or hidden
        [1, T, D]. Returns hidden rows (pad trimmed): all T rows normally;
        on a prefix-cache hit, the stored prefix rows prepended to the
        computed suffix (final stage: suffix only — it samples from the
        last row and stores no outputs)."""
        if self.prefix_store is not None and prefix_len > 0:
            return self._prefill_with_store(session_id, x, prefix_len)
        return self._prefill_full(session_id, x)

    def _prefill_with_store(self, session_id: str, x,
                            prefix_len: int) -> jnp.ndarray:
        from .prefix_cache import chain_digests

        x_np = np.asarray(x)
        t = x_np.shape[1]
        grain = self.prefix_store.grain
        n_grains = min(prefix_len, t - 1) // grain
        if n_grains <= 0:
            return self._prefill_full(session_id, x)
        # Batch dim rides the coords because stored segments are [L, G, ...]
        # slices of a fixed-batch slot layout; model tag because digests are
        # content-addressed across sessions, and two models' identical token
        # prefixes must not alias (the session executor's coords already
        # carry req.model — this engine learns it at construction).
        coords = ("slot", self.spec.start, self.spec.end,
                  str(x_np.dtype), str(self.dtype),
                  x_np.shape[0], self.model)
        blocks = [np.ascontiguousarray(x_np[:, g * grain:(g + 1) * grain])
                  .tobytes() for g in range(n_grains)]
        keys = chain_digests(blocks, coords)
        chain = self.prefix_store.lookup_chain(
            keys, need_out=not self.spec.is_last)
        if not chain:
            h = self._prefill_full(session_id, x)
            s = self._slot_of[session_id]
            segs_k, segs_v = self._split_grains(s, n_grains, grain)
            for g in range(n_grains):
                out = (None if self.spec.is_last
                       else h[:, g * grain:(g + 1) * grain])
                self.prefix_store.put(keys[g], segs_k[g], segs_v[g], out)
            return h
        # Hit (possibly partial): copy the chain's KV, compute the rest.
        p = len(chain) * grain
        if t > self.max_len:
            raise ValueError(f"prompt {t} exceeds slot max_len {self.max_len}")
        s = self._alloc(session_id)
        suffix = x_np[:, p:]
        ts = suffix.shape[1]
        tb = (ts if ts > PREFILL_BUCKETS[-1]
              else min(round_to_bucket(ts, PREFILL_BUCKETS),
                       self.max_len - p))
        if tb != ts:
            pad = ((0, 0), (0, tb - ts)) + (((0, 0),) if x_np.ndim == 3
                                            else ())
            suffix = np.pad(suffix, pad)
        if self._suffix_jit is None:
            self._suffix_jit = self._build_prefill_suffix()
        try:
            self._write_prefix_chain(s, chain)
            h, self.k, self.v = self._suffix_jit(
                self.params, jnp.asarray(suffix), jnp.int32(s), self.k,
                self.v, jnp.int32(p), jnp.int32(ts))
        except Exception:
            self._recover_slot(session_id, s)
            raise
        self.lengths[s] = t
        h = h[:, :ts]
        full = (h if self.spec.is_last
                else jnp.concatenate([*(e.out for e in chain), h], axis=1))
        if len(chain) < n_grains:
            # Register the grains the chain didn't cover (and REPAIR chains
            # truncated by LRU eviction of a middle link — the session
            # executor's pfx_register does the same).
            segs_k, segs_v = self._split_grains(s, n_grains, grain)
            for g in range(len(chain), n_grains):
                out = (None if self.spec.is_last
                       else full[:, g * grain:(g + 1) * grain])
                self.prefix_store.put(keys[g], segs_k[g], segs_v[g], out)
        return full

    def _recover_slot(self, session_id: str, s: int) -> None:
        """Shared failure recovery for every prefill path: a failed
        dispatch (e.g. device OOM) must not leak the slot — the session
        was never established, so recycle it with a clean length. The
        jitted calls DONATE self.k/self.v, so a failure DURING execution
        (vs before dispatch) leaves them deleted, which would crash every
        later step with 'Array has been deleted'; rebuild empty caches and
        evict all sessions — their KV is gone either way, and a refused
        decode is retryable (clients fail over and replay) where a
        poisoned engine is not."""
        self._slot_of.pop(session_id, None)
        self.lengths[s] = 0
        self._free.append(s)
        if getattr(self.k, "is_deleted", lambda: False)():
            shape = (max(self.spec.num_layers, 1), self.slots, self.max_len,
                     self.cfg.num_kv_heads, self.cfg.head_dim)
            self.k = jnp.zeros(shape, self.dtype)
            self.v = jnp.zeros(shape, self.dtype)
            self._slot_of.clear()
            self.lengths[:] = 0
            self._free = list(range(self.slots))

    def _prefill_full(self, session_id: str, x) -> jnp.ndarray:
        x = jnp.asarray(x)
        t = x.shape[1]
        if t > self.max_len:
            raise ValueError(f"prompt {t} exceeds slot max_len {self.max_len}")
        s = self._alloc(session_id)
        # Bucket-pad the prompt so an epoch of varied lengths compiles a
        # handful of shapes; beyond the bucket table, exact length (one
        # compile) beats failing.
        tb = (t if t > PREFILL_BUCKETS[-1]
              else min(round_to_bucket(t, PREFILL_BUCKETS), self.max_len))
        if tb != t:
            pad = ((0, 0), (0, tb - t)) + (((0, 0),) if x.ndim == 3 else ())
            x = jnp.pad(x, pad)
        if self._prefill_jit is None:
            self._prefill_jit = self._build_prefill()
        try:
            h, self.k, self.v = self._prefill_jit(
                self.params, x, jnp.int32(s), self.k, self.v, jnp.int32(t))
        except Exception:
            self._recover_slot(session_id, s)
            raise
        self.lengths[s] = t
        return h[:, :t]

    # ------------------------------------------------------------------
    # Batched decode: one step for EVERY active slot
    # ------------------------------------------------------------------

    def _build_decode(self, t_step: int):
        """One batched step of `t_step` tokens per active slot. t_step == 1
        is plain decode; t_step == K+1 is a speculative verify round (the
        draft block enters as new tokens, causal within itself)."""
        cfg, spec = self.cfg, self.spec
        S = self.slots
        T = t_step

        @partial(jax.jit, donate_argnums=engine_donation(4, 5))
        def fn(params, x, lengths, active, k_all, v_all):
            # x: ids [S, T] or hidden [S, T, D]; lengths/active: [S].
            offs = jnp.arange(T, dtype=jnp.int32)
            positions = lengths[:, None] + offs[None, :]       # [S, T]
            h = (embed_tokens(cfg, params["embed"], x, positions)
                 if spec.is_first else x)
            rope = make_rope(cfg, positions)
            groups = cfg.num_heads // cfg.num_kv_heads
            pos_grid = jnp.arange(k_all.shape[2], dtype=jnp.int32)  # [max_len]
            # allowed[s, tq, m]: key position m visible to query token tq of
            # slot s — everything up to and including the query's own
            # position (causal within the new block too).
            qpos = positions[:, :, None]                        # [S, T, 1]
            allowed = pos_grid[None, None, :] <= qpos           # [S, T, M]
            if cfg.sliding_window:
                # Window spans (qpos - window, qpos].
                allowed &= pos_grid[None, None, :] > qpos - cfg.sliding_window

            def layer(h, lp_kv):
                lp, (k_l, v_l) = lp_kv                 # k_l: [S,max_len,Hkv,Dh]
                from ..models.quant import dequant_tree

                lp = dequant_tree(lp, keep_experts=cfg.is_moe)
                a = _norm(cfg, lp["ln1"], h)
                q, k, v = qkv_proj(cfg, lp["attn"], a)     # [S,T,H/Hkv,Dh]
                if rope is not None:
                    q = apply_rope(q, *rope)
                    k = apply_rope(k, *rope)
                # Per-slot cache write of T rows at each slot's own length
                # (vmap'd dynamic_update_slice). Inactive slots write their
                # OWN current rows back: a slot parked near max_len would
                # clamp its start and clobber that session's last real KV
                # rows, so the write value for inactive slots is the rows
                # already there (read and write clamp to the SAME start, so
                # the round trip is a no-op — cheaper than a full-cache
                # select on the donated buffers).
                upd = jax.vmap(
                    lambda cache, new, start, act:
                    jax.lax.dynamic_update_slice_in_dim(
                        cache,
                        jnp.where(
                            act, new,
                            jax.lax.dynamic_slice_in_dim(cache, start, T, 0)),
                        start, 0)
                )
                k_l = upd(k_l, k.astype(k_l.dtype), lengths, active)
                v_l = upd(v_l, v.astype(v_l.dtype), lengths, active)
                # Attention over [0, query position] per new token.
                qg = q.reshape(S, T, cfg.num_kv_heads, groups, cfg.head_dim)
                scores = jnp.einsum(
                    "bthgd,bshd->bhgts", qg * _qscale(cfg),
                    k_l.astype(q.dtype),
                    preferred_element_type=jnp.float32)      # [S,Hkv,G,T,M]
                m = _layer_mask(lp, allowed, qpos, pos_grid[None, None, :])
                scores = _softcap_and_mask(cfg, scores, m[:, None, None])
                probs = jax.nn.softmax(scores, axis=-1)
                out = jnp.einsum("bhgts,bshd->bthgd",
                                 probs.astype(v_l.dtype),
                                 v_l.astype(q.dtype))
                out = _dot(out.reshape(S, T, -1), lp["attn"]["wo"])
                if "bo" in lp["attn"]:
                    out = out + lp["attn"]["bo"]
                h = _residual(cfg, lp, h, out)
                return h, (k_l, v_l)

            h, (k_all, v_all) = jax.lax.scan(
                layer, h, (params["layers"], (k_all, v_all)))
            # Inactive slots produced garbage — zero them so nothing
            # downstream can mistake them for real activations.
            h = jnp.where(active[:, None, None], h, 0.0)
            return h, k_all, v_all

        return fn

    def tokens_left(self) -> int:
        """Admission headroom for heartbeats/info (the slot-batched analogue
        of KVArena.tokens_left): free slots at full length plus the unused
        tail of every occupied slot."""
        occupied = set(self._slot_of.values())
        free = self.slots - len(occupied)
        return int(free * self.max_len
                   + sum(self.max_len - int(self.lengths[s])
                         for s in occupied))

    def decode_batch(self, inputs: Dict[str, Any]) -> Dict[str, jnp.ndarray]:
        """One batched step. inputs: {session_id: ids [1,T] or hidden
        [1,T,D]} — every session in the call shares one step width T (T=1
        plain decode, T=K+1 speculative verify). Returns {session_id:
        hidden [1,T,D]}. Sessions not in `inputs` are untouched (masked)."""
        if not inputs:
            return {}
        sids = list(inputs)
        t = int(np.asarray(inputs[sids[0]]).shape[1])
        rows = []
        for sid in sids:
            if int(np.asarray(inputs[sid]).shape[1]) != t:
                raise ValueError(
                    "all sessions in one batched step share one width "
                    f"(got {np.asarray(inputs[sid]).shape[1]} vs {t})")
            if sid not in self._slot_of:
                raise KeyError(f"unknown session {sid} (prefill first)")
            if self.lengths[self._slot_of[sid]] + t > self.max_len:
                raise RuntimeError(
                    f"session {sid}: {t} tokens past length "
                    f"{int(self.lengths[self._slot_of[sid]])} exceeds "
                    f"max_len {self.max_len}")
            rows.append(self._slot_of[sid])

        first = self.spec.is_first
        d = self.cfg.hidden_size
        if first:
            x = np.zeros((self.slots, t), np.int32)
        else:
            x = np.zeros((self.slots, t, d), np.float32)
        for sid, s in zip(sids, rows):
            x[s] = np.asarray(inputs[sid])[0]
        active = np.zeros((self.slots,), bool)
        active[rows] = True

        step = self._decode_jits.get(t)
        if step is None:
            step = self._decode_jits[t] = self._build_decode(t)
        h, self.k, self.v = step(
            self.params, jnp.asarray(x), jnp.asarray(self.lengths),
            jnp.asarray(active), self.k, self.v)
        for s in rows:
            self.lengths[s] += t
        self.decode_steps += 1
        return {sid: h[s:s + 1] for sid, s in zip(sids, rows)}

    # ------------------------------------------------------------------
    # Burst decode: N ticks per dispatch, sampling on device
    # ------------------------------------------------------------------

    def _build_burst(self, n_ticks: int):
        """N decode ticks in one program: ``lax.scan`` over ticks, each tick
        a T=1 batched decode body (same graph as ``_build_decode(1)``) plus
        the final head and per-slot sampling.

        Determinism contract: tick i of a slot whose request shipped
        ``step_seed`` samples with ``PRNGKey(step_seed + i)`` — exactly the
        key the sequential client would ship for that token (its step_seed
        is ``seed + len(generated)``), and the same ``sample_token`` /
        ``push_recent`` math as executor._sample_rows, so burst tokens are
        bit-identical to the per-tick baseline.

        Host stop rules are mirrored ON DEVICE, in the host's order (cap
        via the ``left`` budget counter, then eos, then the 5-run repeat
        heuristic), so the emitted count per slot always matches what the
        sequential client would have accepted."""
        cfg, spec = self.cfg, self.spec
        S = self.slots
        N = n_ticks
        from ..models.transformer import lm_head
        from ..ops.sampling import push_recent, sample_token

        @partial(jax.jit, donate_argnums=engine_donation(14, 15))
        def fn(params, tok, lengths, alive, seeds, recent, nvalid, run,
               left, eos_id, temp, top_p, top_k, rp, k_all, v_all):
            pos_grid = jnp.arange(k_all.shape[2], dtype=jnp.int32)
            len0 = lengths

            def tick(carry, i):
                (tok, lengths, alive, recent, nvalid, run, left,
                 stop, k_all, v_all) = carry
                active = alive
                x = tok[:, None]                              # [S, 1] ids
                positions = lengths[:, None]                  # [S, 1]
                h = embed_tokens(cfg, params["embed"], x, positions)
                rope = make_rope(cfg, positions)
                groups = cfg.num_heads // cfg.num_kv_heads
                qpos = positions[:, :, None]                  # [S, 1, 1]
                allowed = pos_grid[None, None, :] <= qpos
                if cfg.sliding_window:
                    allowed &= (pos_grid[None, None, :]
                                > qpos - cfg.sliding_window)

                def layer(h, lp_kv):
                    lp, (k_l, v_l) = lp_kv
                    from ..models.quant import dequant_tree

                    lp = dequant_tree(lp, keep_experts=cfg.is_moe)
                    a = _norm(cfg, lp["ln1"], h)
                    q, k, v = qkv_proj(cfg, lp["attn"], a)
                    if rope is not None:
                        q = apply_rope(q, *rope)
                        k = apply_rope(k, *rope)
                    upd = jax.vmap(
                        lambda cache, new, start, act:
                        jax.lax.dynamic_update_slice_in_dim(
                            cache,
                            jnp.where(
                                act, new,
                                jax.lax.dynamic_slice_in_dim(
                                    cache, start, 1, 0)),
                            start, 0)
                    )
                    k_l = upd(k_l, k.astype(k_l.dtype), lengths, active)
                    v_l = upd(v_l, v.astype(v_l.dtype), lengths, active)
                    qg = q.reshape(S, 1, cfg.num_kv_heads, groups,
                                   cfg.head_dim)
                    scores = jnp.einsum(
                        "bthgd,bshd->bhgts", qg * _qscale(cfg),
                        k_l.astype(q.dtype),
                        preferred_element_type=jnp.float32)
                    m = _layer_mask(lp, allowed, qpos,
                                    pos_grid[None, None, :])
                    scores = _softcap_and_mask(cfg, scores, m[:, None, None])
                    probs = jax.nn.softmax(scores, axis=-1)
                    out = jnp.einsum("bhgts,bshd->bthgd",
                                     probs.astype(v_l.dtype),
                                     v_l.astype(q.dtype))
                    out = _dot(out.reshape(S, 1, -1), lp["attn"]["wo"])
                    if "bo" in lp["attn"]:
                        out = out + lp["attn"]["bo"]
                    h = _residual(cfg, lp, h, out)
                    return h, (k_l, v_l)

                h, (k_all, v_all) = jax.lax.scan(
                    layer, h, (params["layers"], (k_all, v_all)))
                h = jnp.where(active[:, None, None], h, 0.0)
                logits = lm_head(cfg, params, h)[:, 0]        # [S, V] fp32
                keys = jax.vmap(jax.random.PRNGKey)(seeds + i)
                sampled = jax.vmap(sample_token)(
                    keys, logits, recent, nvalid, temp, top_p, top_k, rp)
                # Host stop-rule mirror, in host order: the token is always
                # EMITTED (the host appends before checking eos/repeat);
                # stops only gate the NEXT tick.
                eos_hit = active & (eos_id >= 0) & (sampled == eos_id)
                run_next = jnp.where(sampled == tok, run + 1, jnp.int32(1))
                run_next = jnp.where(active, run_next, run)
                rep_hit = active & (run_next >= BURST_REPEAT_STOP)
                left_next = jnp.where(active, left - 1, left)
                rec2, nv2 = jax.vmap(push_recent)(recent, nvalid, sampled)
                recent = jnp.where(active[:, None], rec2, recent)
                nvalid = jnp.where(active, nv2, nvalid)
                lengths = jnp.where(active, lengths + 1, lengths)
                first = stop == 0
                stop = jnp.where(eos_hit & first, jnp.int32(1), stop)
                stop = jnp.where(rep_hit & ~eos_hit & first,
                                 jnp.int32(2), stop)
                alive = active & ~eos_hit & ~rep_hit & (left_next > 0)
                tok = jnp.where(active, sampled, tok)
                out_tok = jnp.where(active, sampled, jnp.int32(-1))
                return (tok, lengths, alive, recent, nvalid, run_next,
                        left_next, stop, k_all, v_all), out_tok

            stop0 = jnp.zeros((S,), jnp.int32)
            carry, toks = jax.lax.scan(
                tick,
                (tok, lengths, alive, recent, nvalid, run, left, stop0,
                 k_all, v_all),
                jnp.arange(N, dtype=jnp.int32))
            (tok, lengths, alive, recent, nvalid, run, left, stop,
             k_all, v_all) = carry
            # Seed base for a CONTINUATION burst: one key was consumed per
            # emitted token (emitted ticks are a prefix of the scan).
            seeds = seeds + (lengths - len0)
            return (toks, stop, tok, lengths, alive, seeds, recent, nvalid,
                    run, left, k_all, v_all)

        return fn

    def _get_burst_jit(self, n_ticks: int):
        fn = self._burst_jits.get(n_ticks)
        if fn is None:
            fn = self._burst_jits[n_ticks] = self._build_burst(n_ticks)
        return fn

    def _burst_prep(self, entries: Dict[str, dict], n_ticks: int):
        """Pack per-session burst specs into the jit's [S]-shaped args.

        entries[sid]: {token, seed, budget, eos (-1 = none), generated,
        temperature, top_p, top_k, repetition_penalty} — the stateless
        per-burst mirror of what the wire protocol ships every step, so
        failover needs no server-side sampler state."""
        from ..ops.sampling import RECENT_WINDOW

        if not (self.spec.is_first and self.spec.is_last):
            raise RuntimeError(
                "burst decode requires the full model span (on-device "
                "sampling feeds tokens straight back into the embedding)")
        if n_ticks < 1:
            raise ValueError(f"burst of {n_ticks} ticks")
        S = self.slots
        tok0 = np.zeros((S,), np.int32)
        seeds = np.zeros((S,), np.int32)
        recent = np.zeros((S, RECENT_WINDOW), np.int32)
        nvalid = np.zeros((S,), np.int32)
        run0 = np.zeros((S,), np.int32)
        left = np.zeros((S,), np.int32)
        eos = np.full((S,), -1, np.int32)
        temp = np.zeros((S,), np.float32)
        top_p = np.ones((S,), np.float32)
        top_k = np.zeros((S,), np.int32)
        rp = np.ones((S,), np.float32)
        alive = np.zeros((S,), bool)
        rows: Dict[str, int] = {}
        for sid, e in entries.items():
            s = self._slot_of.get(sid)
            if s is None:
                raise KeyError(f"unknown session {sid} (prefill first)")
            budget = min(int(e["budget"]), n_ticks)
            if budget < 1:
                raise ValueError(f"session {sid}: burst budget must be >= 1")
            if int(self.lengths[s]) + budget > self.max_len:
                raise RuntimeError(
                    f"session {sid}: burst of {budget} past length "
                    f"{int(self.lengths[s])} exceeds max_len {self.max_len}")
            gen = tuple(int(t) for t in e["generated"])
            win = gen[-RECENT_WINDOW:]
            if win:
                recent[s, :len(win)] = win
            nvalid[s] = len(win)
            r = 0
            for t in reversed(gen):
                if t != gen[-1]:
                    break
                r += 1
            run0[s] = r
            tok0[s] = int(e["token"])
            seeds[s] = int(e["seed"])
            left[s] = budget
            eos[s] = int(e.get("eos", -1) if e.get("eos") is not None else -1)
            temp[s] = float(e["temperature"])
            top_p[s] = float(e["top_p"])
            top_k[s] = int(e["top_k"])
            rp[s] = float(e["repetition_penalty"])
            alive[s] = True
            rows[sid] = s
        args = (jnp.asarray(tok0), jnp.asarray(self.lengths),
                jnp.asarray(alive), jnp.asarray(seeds), jnp.asarray(recent),
                jnp.asarray(nvalid), jnp.asarray(run0), jnp.asarray(left),
                jnp.asarray(eos), jnp.asarray(temp), jnp.asarray(top_p),
                jnp.asarray(top_k), jnp.asarray(rp))
        return rows, args

    _BURST_STOPS = {0: None, 1: "eos", 2: "repeat"}

    def _burst_collect(self, rows: Dict[str, int], toks, stop,
                       lengths_new) -> Dict[str, dict]:
        """Read one burst's results back (the only host sync per burst)."""
        toks_np = np.asarray(toks)            # [N, S]
        stop_np = np.asarray(stop)
        len_np = np.asarray(lengths_new)
        out: Dict[str, dict] = {}
        total = 0
        for sid, s in rows.items():
            m = int(len_np[s] - self.lengths[s])
            emitted = [int(t) for t in toks_np[:m, s]]
            total += m
            out[sid] = {"tokens": emitted,
                        "stop": self._BURST_STOPS[int(stop_np[s])],
                        "cache_len": int(len_np[s])}
            self.lengths[s] = int(len_np[s])
        self.burst_tokens += total
        self._m_burst_toks.inc(total)
        return out

    def decode_burst(self, entries: Dict[str, dict],
                     n_ticks: int) -> Dict[str, dict]:
        """Run up to ``n_ticks`` decode ticks for every session in
        ``entries`` in ONE jitted dispatch. Returns {session_id: {tokens,
        stop, cache_len}} — ``tokens`` are the emitted ids (<= n_ticks;
        device-side eos/repeat/budget stops truncate), ``stop`` is
        None/"eos"/"repeat". Sessions join/leave only between bursts."""
        if not entries:
            return {}
        prof = _get_profiler()
        with prof.phase("burst_build"):
            rows, args = self._burst_prep(entries, n_ticks)
            fn = self._get_burst_jit(n_ticks)
        if prof.enabled:
            # Fenced dispatch: the device phase is dispatch-to-ready, the
            # bubble gauge charges idle time between successive readies.
            t_d = time.perf_counter()
            out = fn(self.params, *args, self.k, self.v)
            prof.observe("dispatch", time.perf_counter() - t_d)
            jax.block_until_ready(out)
            prof.device_interval(t_d, time.perf_counter())
        else:
            out = fn(self.params, *args, self.k, self.v)
        toks, stop = out[0], out[1]
        lengths_new = out[3]
        self.k, self.v = out[-2], out[-1]
        self.decode_steps += 1
        self.burst_dispatches += 1
        self._m_burst_disp.inc()
        self._m_burst_ticks.observe(n_ticks)
        with prof.phase("readback"):
            return self._burst_collect(rows, toks, stop, lengths_new)

    def burst_stream(self, entries: Dict[str, dict], n_ticks: int):
        """Double-buffered burst driver (generator): every carry — tokens,
        lengths, alive masks, sampler state, KV — stays DEVICE-RESIDENT
        across bursts, and burst k+1 is dispatched BEFORE burst k's tokens
        are read back, so on an async backend the host-side readback and
        framing of burst k overlap the device executing burst k+1. Yields
        one {session_id: {tokens, stop, cache_len}} block per burst (empty
        blocks are skipped). The in-process serving/bench driver for one
        resident cohort; the wire path uses per-burst ``decode_burst``."""
        if not entries:
            return
        prof = _get_profiler()
        with prof.phase("burst_build"):
            rows, args = self._burst_prep(entries, n_ticks)
            fn = self._get_burst_jit(n_ticks)
        remaining = {sid: int(e["budget"]) for sid, e in entries.items()}
        finished: Dict[str, bool] = {sid: False for sid in entries}
        # _burst_prep clamps the ``left`` counter to ONE burst's ticks (the
        # per-dispatch wire contract); a stream spans many bursts, so seed
        # the carry with the FULL budget instead — it ticks down on device
        # across dispatches and a slot goes dead exactly when its total
        # budget is spent, no host round-trip in between.
        left_full = np.zeros((self.slots,), np.int32)
        for sid, s in rows.items():
            b = int(entries[sid]["budget"])
            if int(self.lengths[s]) + b > self.max_len:
                raise RuntimeError(
                    f"session {sid}: stream budget of {b} past length "
                    f"{int(self.lengths[s])} exceeds max_len {self.max_len}")
            left_full[s] = b
        carry, static = args[:8], args[8:]   # sampler params never change
        carry = carry[:7] + (jnp.asarray(left_full),)
        pending: List[tuple] = []
        done = False
        while not done or pending:
            if not done:
                t_d = time.perf_counter() if prof.enabled else None
                out = fn(self.params, *carry, *static, self.k, self.v)
                if t_d is not None:
                    prof.observe("dispatch", time.perf_counter() - t_d)
                toks, stop = out[0], out[1]
                carry = out[2:10]
                self.k, self.v = out[-2], out[-1]
                self.decode_steps += 1
                self.burst_dispatches += 1
                self._m_burst_disp.inc()
                self._m_burst_ticks.observe(n_ticks)
                # out[3] is the post-burst lengths (device array, not yet
                # read back — _burst_collect does the sync).
                pending.append((toks, stop, out[3], t_d))
            # Keep exactly one burst in flight: read back the OLDEST burst
            # only once a newer one has been dispatched (or we are done).
            while pending and (done or len(pending) > 1):
                toks_p, stop_p, len_p, t_d = pending.pop(0)
                if t_d is not None and prof.enabled:
                    # Fence device completion apart from the host-side
                    # readback: the fenced burst is the one being collected
                    # anyway, so dispatch overlap is preserved — overlapped
                    # dispatches show up as zero bubble, host stalls between
                    # readies as idle device time.
                    jax.block_until_ready((toks_p, stop_p, len_p))
                    t_r = time.perf_counter()
                    prof.device_interval(t_d, t_r)
                    block = self._burst_collect(rows, toks_p, stop_p, len_p)
                    prof.observe("readback", time.perf_counter() - t_r)
                else:
                    block = self._burst_collect(rows, toks_p, stop_p, len_p)
                live = {}
                for sid, res in block.items():
                    m = len(res["tokens"])
                    remaining[sid] -= m
                    if res["stop"] is not None or remaining[sid] <= 0:
                        finished[sid] = True
                    if m:
                        live[sid] = res
                if all(finished.values()):
                    done = True
                if live:
                    yield live

    # ------------------------------------------------------------------

    def logits(self, hidden: jnp.ndarray) -> jnp.ndarray:
        """Final-stage head over [1, T, D] -> [1, T, V] (fp32)."""
        from ..models.transformer import lm_head

        return lm_head(self.cfg, self.params, hidden)


# ---------------------------------------------------------------------------
# Transport adapter: serve the batched engine behind the StageRequest
# protocol, coalescing CONCURRENT decode requests into one step.
# ---------------------------------------------------------------------------

class _Round:
    """One coalescing window: requests that arrive while it is open share a
    single batched step. Rounds are keyed by step width T (seq_len), so a
    round's sessions always share one compiled step: T=1 plain decode,
    T=K+1 speculative verify."""

    __slots__ = ("reqs", "outs", "err", "bad", "lengths", "spec", "event",
                 "closed", "t_exec")

    def __init__(self):
        self.reqs: Dict[str, Any] = {}
        self.outs: Dict[str, jnp.ndarray] = {}
        self.lengths: Dict[str, int] = {}
        self.spec: Dict[str, Tuple[Tuple[int, ...], int]] = {}  # verified rows
        self.err: Optional[Exception] = None      # whole-round failure
        self.bad: Dict[str, str] = {}             # per-session exclusions
        self.event = threading.Event()
        self.closed = False
        self.t_exec = 0.0    # monotonic instant the round's step started


class _SlotArenaView:
    """KVArena-shaped facade over the slot tables (tokens_left only).

    Takes the adapter's lock (heartbeat/info threads call this while handler
    threads mutate the slot tables under the same lock — an unlocked dict
    iteration there can raise mid-resize), but with a BOUNDED wait: the
    adapter holds its lock across whole prefill dispatches (including
    compiles), and blocking the heartbeat thread past the registry TTL would
    expire a healthy server. A busy adapter returns the last known value."""

    def __init__(self, inner: BatchedStageExecutor, lock: threading.Lock):
        self._inner = inner
        self._lock = lock
        self._last = inner.slots * inner.max_len

    def tokens_left(self) -> int:
        if self._lock.acquire(timeout=0.5):
            try:
                self._last = self._inner.tokens_left()
            finally:
                self._lock.release()
        return self._last


class BatchingStageAdapter:
    """Drop-in StageExecutor replacement for transports: plain
    prefill/decode AND speculative-verify requests ride the batched engine,
    with concurrent decode calls coalesced — the FIRST arrival leads its
    width's round, waits ``window_s`` for followers, runs ONE
    `decode_batch`, and every waiter picks up its own row. Draft steps
    (width K+1) coalesce with each other; the final stage verifies each
    row and rewinds its slot past the rejected tail before releasing
    waiters. Beam/training/replay/sub-span requests are refused with a
    retryable stage error so clients route them to a per-session replica
    (the batched path is the common-case fast lane, not the whole protocol
    — see module docstring)."""

    engine = "batched"   # registry capability tag (ServerRecord.engine)

    def __init__(self, inner: BatchedStageExecutor, *,
                 window_s: float = 0.003, peer_id: str = "batched",
                 step_timeout: float = 120.0):
        self.inner = inner
        self.spec = inner.spec
        self.cfg = inner.cfg
        self.window_s = window_s
        self.peer_id = peer_id
        self.step_timeout = step_timeout
        self.requests_served = 0
        self._lock = threading.Lock()
        # Open coalescing rounds, keyed by step width T (classic decode /
        # speculative verify) or ('burst', N) (burst rounds never share a
        # compiled program with single-tick rounds).
        self._rounds: Dict[Any, _Round] = {}
        # Telemetry (global registry; strict no-op unless enabled). Step
        # latency itself is observed at the serving boundary (LocalTransport
        # / TcpStageServer) — the adapter owns the batching-specific signals.
        self._m_queue_wait = _tm.get("server_queue_wait_seconds")
        self._m_fill = _tm.get("server_batch_fill_sessions")
        self._m_round = _tm.get("server_decode_round_seconds")
        # TcpStageServer's info verb + heartbeat read `.arena.tokens_left()`
        # on whatever executor they serve; point that surface at the slot
        # tables so a batched server advertises real admission headroom.
        self.arena = _SlotArenaView(inner, self._lock)

    def warmup(self, speculative_k: int = 0, burst: int = 0) -> None:
        """Pre-compile the engine's programs (prefill at the smallest
        bucket + the batched decode step) so the first real session doesn't
        pay compile latency — the serve-mode analogue of StageExecutor.warmup.

        ``speculative_k > 0`` additionally warms every speculative decode
        width 2..K+1 — the n-gram drafter returns VARIABLE-length drafts
        (whatever follow it matched, often < K), so any unwarmed width
        would compile inside the round leader's lock hold on first use,
        stalling every concurrent round and the heartbeat's arena view for
        the compile duration."""
        first = self.spec.is_first
        d = self.cfg.hidden_size
        x = (np.zeros((1, 4), np.int32) if first
             else np.zeros((1, 4, d), np.float32))
        self.inner.prefill("__warmup__", x)
        widths = [1] + list(range(2, speculative_k + 2))
        for t in widths:
            step = (np.zeros((1, t), np.int32) if first
                    else np.zeros((1, t, d), np.float32))
            self.inner.rewind("__warmup__", 4)
            out = self.inner.decode_batch({"__warmup__": jnp.asarray(step)})
            if self.spec.is_last and t > 1:
                # The verify path's head projection over [n, K+1, D] is its
                # own program shape — warm it too, or the first speculative
                # round compiles it inside the leader's lock.
                self.inner.logits(out["__warmup__"])
        if burst > 0 and self.spec.is_first and self.spec.is_last:
            # The burst scan is by far the largest program (N unrolled-ish
            # ticks under a scan + head + sampler); compiling it inside the
            # first real round's lock hold would stall every session AND
            # the heartbeat's arena view for the whole compile.
            self.inner.rewind("__warmup__", 4)
            self.inner.decode_burst(
                {"__warmup__": {"token": 1, "seed": 0, "budget": burst,
                                "eos": None, "generated": (1,),
                                "temperature": 0.0, "top_p": 1.0,
                                "top_k": 0, "repetition_penalty": 1.0}},
                burst)
        self.inner.end_session("__warmup__")

    # -- protocol ----------------------------------------------------------

    def forward(self, req) -> "StageResponse":
        from .executor import StageExecutionError

        self.requests_served += 1
        if (req.train or req.hypo_ids is not None or req.num_logprobs
                or req.prompts is not None
                or req.start_from_position not in (None, req.cur_len)):
            _ev.emit("task_rejected", session_id=req.session_id,
                     pool="batched", reason="unsupported request kind")
            raise StageExecutionError(
                "batched peer serves plain prefill/decode, speculative "
                "verify, and replay only (route beam/training/deep-prompt "
                "requests to a per-session replica)")
        if req.start_block is not None and (
                req.start_block != self.spec.start
                or (req.end_block or self.spec.end) != self.spec.end):
            _ev.emit("task_rejected", session_id=req.session_id,
                     pool="batched", reason="sub-span request")
            raise StageExecutionError(
                "batched peer serves its full span only")
        if req.is_prefill:
            return self._prefill(req)
        if req.burst_len:
            if not (self.spec.is_first and self.spec.is_last):
                _ev.emit("task_rejected", session_id=req.session_id,
                         pool="batched", reason="burst without full span")
                raise StageExecutionError(
                    "burst decode requires a full-span peer (on-device "
                    "sampling feeds tokens back into the embedding)")
            if req.seq_len != 1 or req.draft_tokens is not None:
                raise StageExecutionError(
                    "a burst step carries exactly the one last accepted "
                    "token")
            return self._decode_burst(req)
        if req.draft_tokens is not None:
            if req.seq_len != len(req.draft_tokens) + 1:
                raise StageExecutionError(
                    f"speculative step carries {req.seq_len} positions for "
                    f"{len(req.draft_tokens)} drafts (want K+1)")
        elif req.seq_len != 1 and not req.is_replay:
            # Replay chunks are plain multi-token KV rebuilds (the client
            # discards the sampled token) — exactly decode_batch's T>1
            # shape, so a replacement batched peer can adopt a failed-over
            # burst session without per-session machinery.
            raise StageExecutionError(
                "batched decode is single-token (chunked continuation "
                "belongs to the per-session executor)")
        return self._decode(req)

    def drop_session(self, session_id: str) -> None:
        with self._lock:
            self.inner.end_session(session_id)

    # -- phases ------------------------------------------------------------

    def _respond(self, req, hidden_row, cache_len: int):
        from .executor import _sample_last
        from .messages import StageResponse

        if self.spec.is_last:
            logits = self.inner.logits(hidden_row)
            token = _sample_last(logits, hidden_row.shape[1], req)
            return StageResponse(session_id=req.session_id, token_id=token,
                                 cache_len=cache_len)
        return StageResponse(session_id=req.session_id, hidden=hidden_row,
                             cache_len=cache_len)

    def _prefill(self, req):
        from .executor import StageExecutionError

        with self._lock:  # slot tables + cache arrays are shared state
            try:
                h = self.inner.prefill(req.session_id, req.hidden,
                                       prefix_len=req.prefix_len)
            except StageExecutionError:
                raise
            except Exception as exc:
                # Same taxonomy as decode's whole-round failures: the engine
                # recovered its slot/caches specifically so the request is
                # retryable — a raw XlaRuntimeError would cross the wire as a
                # kind-less error outside the client's failover taxonomy and
                # crash the generation instead of re-routing it.
                raise StageExecutionError(str(exc)) from exc
            cache_len = int(self.inner.lengths[self.inner.slot(req.session_id)])
        return self._respond(req, h, cache_len)

    def _validate(self, req) -> Optional[str]:
        """Per-session admission (caller holds the lock). Returns a refusal
        reason or None. A bad session must never poison its round-mates."""
        s = self.inner.slot(req.session_id)
        if s is None:
            return (f"session {req.session_id}: decode without a slot "
                    "(prefill first; replay-rebuild is per-session only)")
        cur = int(self.inner.lengths[s])
        spos = req.start_from_position
        if spos is not None and spos != cur:
            # Speculative rollback: the previous round's rejected overhang
            # is still in the slot; shrink the valid prefix before this
            # round appends (petals start_from_position semantics —
            # forward() already pinned spos == req.cur_len).
            if spos > cur:
                return (f"session {req.session_id}: rewind to {spos} beyond "
                        f"cache {cur}")
            self.inner.rewind(req.session_id, spos)
            cur = spos
        if cur + req.seq_len > self.inner.max_len:
            return (f"session {req.session_id}: {req.seq_len} tokens past "
                    f"{cur} exceeds max_len {self.inner.max_len}")
        if req.cur_len != cur:
            # The per-session executor warns and trusts itself
            # (executor.py past-len mismatch); the batched path REFUSES: the
            # main cause here is a retry after a follower timeout whose step
            # actually advanced — continuing would silently desync. Refusal
            # is retryable, so the client fails over to a per-session
            # replica and replays.
            return (f"session {req.session_id}: cur_len {req.cur_len} != "
                    f"server {cur} (stale retry?)")
        return None

    def _decode(self, req):
        from .executor import StageExecutionError
        from .messages import StageResponse

        sid = req.session_id
        t = req.seq_len
        t_join = time.monotonic()
        with self._lock:
            reason = self._validate(req)
            if reason is not None:
                raise StageExecutionError(reason)
            r = self._rounds.get(t)
            if r is None or r.closed:
                r = self._rounds[t] = _Round()
                leader = True       # explicit: whoever CREATES the round
            else:
                leader = False
            if sid in r.reqs:
                raise StageExecutionError(
                    f"session {sid}: concurrent decode for one session")
            r.reqs[sid] = req
        if leader:
            # The whole leader path runs under try/finally: an unexpected
            # exception anywhere (not just inside decode_batch) must still
            # release the followers, else they block for step_timeout.
            try:
                time.sleep(self.window_s)
                with self._lock:
                    r.closed = True
                    if self._rounds.get(t) is r:
                        del self._rounds[t]
                    # Re-validate under the lock: a session may have been
                    # dropped (or otherwise invalidated) since it joined.
                    # Exclusions fail ONLY their own waiter.
                    good = {}
                    for s_id, rq in r.reqs.items():
                        reason = self._validate(rq)
                        if reason is None:
                            good[s_id] = rq
                        else:
                            r.bad[s_id] = reason
                    if good:
                        r.t_exec = time.monotonic()
                        self._m_fill.observe(len(good))
                        r.outs = self.inner.decode_batch(
                            {s_id: rq.hidden for s_id, rq in good.items()})
                        if self.spec.is_last:
                            self._verify_spec_rows(r, good)
                        r.lengths = {
                            s_id: int(self.inner.lengths[self.inner.slot(s_id)])
                            for s_id in good
                        }
                        self._m_round.observe(time.monotonic() - r.t_exec)
            except Exception as exc:  # whole-round failure
                r.err = exc
                with self._lock:  # a dead round must not accept joiners
                    r.closed = True
                    if self._rounds.get(t) is r:
                        del self._rounds[t]
            finally:
                r.event.set()
        elif not r.event.wait(self.step_timeout):
            raise StageExecutionError("batched step timed out")
        if r.t_exec:
            # Time this session spent parked before its round's step ran —
            # the coalescing window for the leader, window + leader overhead
            # for followers.
            self._m_queue_wait.observe(max(0.0, r.t_exec - t_join))
        if r.err is not None:
            raise StageExecutionError(str(r.err)) from r.err
        if sid in r.bad:
            raise StageExecutionError(r.bad[sid])
        if sid in r.spec:
            tokens, n_acc = r.spec[sid]
            return StageResponse(session_id=sid, tokens=tokens,
                                 n_accepted=n_acc, cache_len=r.lengths[sid])
        return self._respond(req, r.outs[sid], r.lengths[sid])

    def _validate_burst(self, req) -> Optional[str]:
        """Burst-specific admission on top of ``_validate`` (caller holds
        the lock): mirror every condition the engine's ``_burst_prep``
        would raise on, so one bad session never poisons its round-mates
        with a whole-round failure."""
        if req.burst_budget < 1:
            return (f"session {req.session_id}: burst budget "
                    f"{req.burst_budget} (want >= 1)")
        s = self.inner.slot(req.session_id)
        cur = int(self.inner.lengths[s])
        budget = min(int(req.burst_budget), int(req.burst_len))
        if cur + budget > self.inner.max_len:
            return (f"session {req.session_id}: burst of {budget} past "
                    f"{cur} exceeds max_len {self.inner.max_len}")
        return None

    def _decode_burst(self, req):
        """Coalesce concurrent burst requests into ONE N-tick dispatch —
        the same leader/follower round machinery as ``_decode``, keyed by
        ('burst', N) so classic single-tick rounds and burst rounds never
        mix widths. Sessions join/leave only at round (= burst)
        boundaries."""
        from .executor import StageExecutionError
        from .messages import StageResponse

        sid = req.session_id
        n = int(req.burst_len)
        key = ("burst", n)
        t_join = time.monotonic()
        with self._lock:
            reason = self._validate(req) or self._validate_burst(req)
            if reason is not None:
                raise StageExecutionError(reason)
            r = self._rounds.get(key)
            if r is None or r.closed:
                r = self._rounds[key] = _Round()
                leader = True
            else:
                leader = False
            if sid in r.reqs:
                raise StageExecutionError(
                    f"session {sid}: concurrent decode for one session")
            r.reqs[sid] = req
        if leader:
            try:
                time.sleep(self.window_s)
                with self._lock:
                    r.closed = True
                    if self._rounds.get(key) is r:
                        del self._rounds[key]
                    good = {}
                    for s_id, rq in r.reqs.items():
                        reason = (self._validate(rq)
                                  or self._validate_burst(rq))
                        if reason is None:
                            good[s_id] = rq
                        else:
                            r.bad[s_id] = reason
                    if good:
                        r.t_exec = time.monotonic()
                        self._m_fill.observe(len(good))
                        r.outs = self.inner.decode_burst(
                            {s_id: _burst_entry(rq)
                             for s_id, rq in good.items()}, n)
                        r.lengths = {
                            s_id: int(
                                self.inner.lengths[self.inner.slot(s_id)])
                            for s_id in good
                        }
                        self._m_round.observe(time.monotonic() - r.t_exec)
                        _ev.emit("burst_round", sessions=len(good), ticks=n,
                                 tokens=sum(len(o["tokens"])
                                            for o in r.outs.values()))
            except Exception as exc:  # whole-round failure
                r.err = exc
                with self._lock:
                    r.closed = True
                    if self._rounds.get(key) is r:
                        del self._rounds[key]
            finally:
                r.event.set()
        elif not r.event.wait(self.step_timeout):
            raise StageExecutionError("batched step timed out")
        if r.t_exec:
            self._m_queue_wait.observe(max(0.0, r.t_exec - t_join))
        if r.err is not None:
            raise StageExecutionError(str(r.err)) from r.err
        if sid in r.bad:
            raise StageExecutionError(r.bad[sid])
        out = r.outs[sid]
        return StageResponse(session_id=sid,
                             burst_tokens=tuple(out["tokens"]),
                             burst_stop=out["stop"],
                             cache_len=r.lengths[sid])

    def _verify_spec_rows(self, r: _Round, good: Dict[str, Any]) -> None:
        """Per-row speculative verification on the final stage (caller holds
        the lock, the round's batched step has run): compute each draft
        session's logits over its K+1 positions, accept/reject with the
        SAME math as the per-session executor
        (executor.verify_drafts_from_logits), and rewind the slot past the
        rejected tail so the next round's cur_len validates against the
        accepted prefix."""
        from .executor import verify_drafts_from_logits

        spec_ids = [s_id for s_id, rq in good.items()
                    if rq.draft_tokens is not None]
        if not spec_ids:
            return
        # ONE stacked head projection for the whole round ([n, T, D] ->
        # [n, T, V]) — a per-session loop of [1, T, D] head calls would
        # undo the round's batching and stretch the lock hold linearly
        # with slot count.
        stacked = jnp.concatenate([r.outs[s_id] for s_id in spec_ids], axis=0)
        logits = self.inner.logits(stacked)
        for i, s_id in enumerate(spec_ids):
            rq = good[s_id]
            tokens, n_acc = verify_drafts_from_logits(logits[i], rq)
            self.inner.rewind(s_id, rq.cur_len + n_acc + 1)
            r.spec[s_id] = (tokens, n_acc)
