"""Tier-1 wrapper for scripts/check_quant_coverage.py: every quant format
in models/quant.py::QUANT_BITS must have a bench row in bench.py and a
token-parity test under tests/ — a new format cannot ship benchmarked-
but-unverified or verified-but-unmeasured."""

import pathlib
import subprocess
import sys

REPO = pathlib.Path(__file__).resolve().parents[1]


def test_every_quant_format_has_bench_and_parity():
    proc = subprocess.run(
        [sys.executable,
         str(REPO / "scripts" / "check_quant_coverage.py")],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, (
        f"quant coverage drift:\n{proc.stdout}{proc.stderr}"
    )
