"""Tier-1 wrapper for scripts/check_no_bare_print.py: library modules must
log through ``logging``, and main.py's stdout must route through its
``_emit()`` helper — the CLI output boundary stays one grep-able function."""

import pathlib
import subprocess
import sys

REPO = pathlib.Path(__file__).resolve().parents[1]


def test_no_bare_print_in_library_code():
    proc = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "check_no_bare_print.py")],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, (
        f"bare print() drift:\n{proc.stdout}{proc.stderr}"
    )
