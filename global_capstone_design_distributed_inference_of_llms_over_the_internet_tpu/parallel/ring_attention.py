"""Ring attention: sequence-parallel exact attention over a mesh axis.

The reference has NO long-context story beyond single-server chunked prefill
(SURVEY.md §5.7: no ring/Ulysses/blockwise anywhere; chunking at
``petals/server/backend.py:129-143`` just bounds one GPU's peak memory). On
TPU the natural long-context design is to shard the SEQUENCE across an
intra-stage mesh axis: each device holds a slice of queries and a slice of
keys/values, and the KV slices rotate around the ring via ``ppermute`` while
every device accumulates its queries' attention with an online (flash-style)
softmax. P devices => P× longer context at the same per-device HBM, with
compute/communication overlap on ICI.

Causality: query chunk q on device i covers absolute positions
[i·C, i·C + C); after s ring steps a device holds the KV chunk of device
(i - s) mod P. Blocks wholly in the future are masked out; the diagonal
block applies the usual triangular mask.

Causal skip (VERDICT r3 item 4): the KV rotation is always full-ring (the
ppermute is a collective — every device must participate every step), but
a device whose incoming block is WHOLLY in its future skips the
score/value compute for it via ``lax.cond`` (a runtime branch, per
device). Summed over the ring, causal prefill does P(P+1)/2 block
computes instead of P² — the step-work ratio (P+1)/2P → ~0.5 at large P.
This cuts total FLOPs/energy; single-ring LATENCY is still P-1 rotations
because the last device computes at every step (balancing that needs a
zigzag chunk layout — two half-chunks per device, one low one high —
which would change sp_stage's on-device sequence layout; measured and
deferred, see docs/PERFORMANCE.md).

Numerics: scores and the softmax accumulator run in float32 regardless of the
activation dtype (matching ops.attention's fp32-softmax contract); the output
returns to the input dtype.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def _block_scores(q, k, scale):
    # q: [B, Tq, Hkv, G, Dh]; k: [B, Tk, Hkv, Dh] -> [B, Hkv, G, Tq, Tk] f32
    return jnp.einsum(
        "bthgd,bshd->bhgts", q * scale, k, preferred_element_type=jnp.float32
    )


# ---------------------------------------------------------------------------
# Shared online-softmax primitives (used here conceptually and directly by
# parallel.sp_stage's decode): partials are (m, l, o) with o UN-normalized
# fp32. The NEG_INF/2 guards keep fully-masked blocks exactly zero instead
# of exp(-inf - -inf) = 1 garbage.
# ---------------------------------------------------------------------------

def online_partial(qg, k, v, mask, scale):
    """Partial over one KV block. qg: [B, 1, Hkv, G, Dh]; k/v: [B, S, Hkv,
    Dh]; mask: [B, S] (True = attendable). Returns (m, l, o), o [B,Hkv,G,Dh]."""
    scores = jnp.einsum("bthgd,bshd->bhgs", qg * scale, k,
                        preferred_element_type=jnp.float32)
    scores = jnp.where(mask[:, None, None, :], scores, NEG_INF)
    m = jnp.max(scores, axis=-1)
    safe_m = jnp.where(m <= NEG_INF / 2, 0.0, m)
    probs = jnp.exp(scores - safe_m[..., None])
    probs = jnp.where(scores <= NEG_INF / 2, 0.0, probs)
    l = probs.sum(axis=-1)
    o = jnp.einsum("bhgs,bshd->bhgd", probs.astype(jnp.float32),
                   v.astype(jnp.float32))
    return m, l, o


def online_combine(a, b):
    """Merge two online-softmax partials (m, l, o)."""
    ma, la, oa = a
    mb, lb, ob = b
    m = jnp.maximum(ma, mb)
    safe_m = jnp.where(m <= NEG_INF / 2, 0.0, m)
    ca = jnp.where(ma <= NEG_INF / 2, 0.0, jnp.exp(ma - safe_m))
    cb = jnp.where(mb <= NEG_INF / 2, 0.0, jnp.exp(mb - safe_m))
    return m, la * ca + lb * cb, oa * ca[..., None] + ob * cb[..., None]


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: str,
    *,
    q_offset: Optional[jnp.ndarray] = None,
    chunk_positions: Optional[jnp.ndarray] = None,
    causal: bool = True,
    skip_masked_blocks: bool = True,
) -> jnp.ndarray:
    """Exact attention with sequence sharded over `axis_name`.

    Must be called inside shard_map/pjit manual context. Per-device views:
      q: [B, C, H, Dh] — this device's query chunk;
      k, v: [B, C, Hkv, Dh] — this device's KV chunk (same C).
    q_offset: absolute position of this device's first query (defaults to
    axis_index · C). Returns [B, C, H, Dh] in q.dtype.
    """
    del chunk_positions  # reserved for ragged chunks
    b, c, h, dh = q.shape
    hkv = k.shape[2]
    groups = h // hkv
    p = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    scale = dh ** -0.5

    if q_offset is None:
        q_offset = idx * c
    q_pos = q_offset + jnp.arange(c, dtype=jnp.int32)          # [C]

    qg = q.reshape(b, c, hkv, groups, dh)
    perm = [(i, (i + 1) % p) for i in range(p)]

    def accumulate(s, k_blk, v_blk, m, l, o):
        src = (idx - s) % p                                     # owner of k_blk
        k_pos = src * c + jnp.arange(c, dtype=jnp.int32)        # [C]

        scores = _block_scores(qg, k_blk, scale)                # [B,Hkv,G,C,C]
        if causal:
            allowed = k_pos[None, :] <= q_pos[:, None]          # [C, C]
            scores = jnp.where(allowed[None, None, None], scores, NEG_INF)

        blk_max = jnp.max(scores, axis=-1)                      # [B,Hkv,G,C]
        m_new = jnp.maximum(m, blk_max)
        # Guard fully-masked rows: exp(NEG_INF - NEG_INF) would be 1.
        safe_m = jnp.where(m_new <= NEG_INF / 2, 0.0, m_new)
        corr = jnp.exp(m - safe_m)
        probs = jnp.exp(scores - safe_m[..., None])
        probs = jnp.where(scores <= NEG_INF / 2, 0.0, probs)
        l = l * corr + probs.sum(axis=-1)
        pv = jnp.einsum(
            "bhgts,bshd->bthgd", probs.astype(v_blk.dtype), v_blk,
            preferred_element_type=jnp.float32,
        )                                                        # [B,C,Hkv,G,Dh]
        o = o * corr.transpose(0, 3, 1, 2)[..., None] + pv
        return m_new, l, o

    def step(s, carry):
        # Rotate FIRST, then accumulate: with the local block (s=0) peeled
        # out of the loop, p-1 rotations cover all p blocks — rotating after
        # the final accumulation would ship one dead ring hop of KV traffic.
        k_blk, v_blk, m, l, o = carry
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        if causal and skip_masked_blocks:
            # Causal skip: if the incoming block is WHOLLY in this device's
            # future (its first key position is past our last query), every
            # score would be masked — skip the block's compute entirely.
            # The rotation above still ran (collective); only the local
            # einsum/softmax work is branched out.
            src = (idx - s) % p
            wholly_future = src * c > q_offset + (c - 1)
            m, l, o = jax.lax.cond(
                wholly_future,
                lambda m, l, o: (m, l, o),
                lambda m, l, o: accumulate(s, k_blk, v_blk, m, l, o),
                m, l, o)
        else:
            m, l, o = accumulate(s, k_blk, v_blk, m, l, o)
        return k_blk, v_blk, m, l, o

    def vary(x):
        return jax.lax.pcast(x, axis_name, to="varying")

    m0 = vary(jnp.full((b, hkv, groups, c), NEG_INF, jnp.float32))
    l0 = vary(jnp.zeros((b, hkv, groups, c), jnp.float32))
    o0 = vary(jnp.zeros((b, c, hkv, groups, dh), jnp.float32))
    m, l, o = accumulate(0, k, v, m0, l0, o0)                   # local block
    _, _, m, l, o = jax.lax.fori_loop(1, p, step, (k, v, m, l, o))

    denom = jnp.maximum(l, 1e-20).transpose(0, 3, 1, 2)[..., None]
    out = (o / denom).reshape(b, c, h, dh)
    return out.astype(q.dtype)


def zigzag_ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: str,
) -> jnp.ndarray:
    """Causal ring attention with the ZIGZAG chunk layout: device i holds
    half-chunks i (low) and 2P-1-i (high) of a sequence cut into 2P
    half-chunks, so every device owns one early and one late piece.

    Why: the contiguous layout's causal skip leaves a skewed LATENCY
    profile — device P-1's queries attend every block, so it computes at
    all P ring steps while device 0 computes only its own (VERDICT r4
    weak item 6). Under zigzag, for the incoming KV of source s a device
    computes exactly
        [s <= i] qLow x kLow  +  qHigh x kLow (always)  +  [s >= i] qHigh x kHigh
    = 2 half-pairs per step (3 when s == i; qLow x kHigh is NEVER causal
    and is omitted statically) — per-device per-step work is uniform, so
    the slowest-device critical path drops from P block-computes to
    ~(2P+1)/4 block-equivalents while TOTAL work stays the causal ~half:
    P*(2P+1) half-pairs vs 4P^2 full-ring = (2P+1)/4P -> 0.5.

    Per-device views (inside shard_map): q [B, 2*C2, H, Dh], k/v
    [B, 2*C2, Hkv, Dh] in zigzag order (low half first). Use
    `make_zigzag_ring_attention_fn` for the full-array wrapper that
    applies the layout permutation.
    """
    b, c2x2, h, dh = q.shape
    c2 = c2x2 // 2
    hkv = k.shape[2]
    groups = h // hkv
    p = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    scale = dh ** -0.5

    ar = jnp.arange(c2, dtype=jnp.int32)
    q_pos_lo = idx * c2 + ar
    q_pos_hi = (2 * p - 1 - idx) * c2 + ar
    qg = q.reshape(b, 2 * c2, hkv, groups, dh)
    qlo, qhi = qg[:, :c2], qg[:, c2:]
    perm = [(i, (i + 1) % p) for i in range(p)]

    def pair(qh, q_pos, k_blk, v_blk, k_pos, m, l, o):
        """Accumulate one (query-half x key-half) pair into (m, l, o)."""
        scores = _block_scores(qh, k_blk, scale)        # [B,Hkv,G,C2,C2]
        allowed = k_pos[None, :] <= q_pos[:, None]
        scores = jnp.where(allowed[None, None, None], scores, NEG_INF)
        blk_max = jnp.max(scores, axis=-1)
        m_new = jnp.maximum(m, blk_max)
        safe_m = jnp.where(m_new <= NEG_INF / 2, 0.0, m_new)
        corr = jnp.exp(m - safe_m)
        probs = jnp.exp(scores - safe_m[..., None])
        probs = jnp.where(scores <= NEG_INF / 2, 0.0, probs)
        l = l * corr + probs.sum(axis=-1)
        pv = jnp.einsum("bhgts,bshd->bthgd", probs.astype(v_blk.dtype),
                        v_blk, preferred_element_type=jnp.float32)
        o = o * corr.transpose(0, 3, 1, 2)[..., None] + pv
        return m_new, l, o

    def accumulate(src, k_blk, v_blk, st_lo, st_hi):
        kl, vl = k_blk[:, :c2], v_blk[:, :c2]           # src's low chunk
        kh, vh = k_blk[:, c2:], v_blk[:, c2:]           # src's high chunk
        k_pos_lo = src * c2 + ar
        k_pos_hi = (2 * p - 1 - src) * c2 + ar
        # qLow x kLow: only when src <= i (past or diagonal).
        st_lo = jax.lax.cond(
            src <= idx,
            lambda st: pair(qlo, q_pos_lo, kl, vl, k_pos_lo, *st),
            lambda st: st, st_lo)
        # qHigh x kLow: always causal (every low chunk precedes any high).
        st_hi = pair(qhi, q_pos_hi, kl, vl, k_pos_lo, *st_hi)
        # qHigh x kHigh: only when src >= i (high chunks order-reverse).
        st_hi = jax.lax.cond(
            src >= idx,
            lambda st: pair(qhi, q_pos_hi, kh, vh, k_pos_hi, *st),
            lambda st: st, st_hi)
        # qLow x kHigh: statically never causal (2P-1-src > i for every
        # src < P <= 2P-1-i) — omitted.
        return st_lo, st_hi

    def step(s, carry):
        k_blk, v_blk, st_lo, st_hi = carry
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        st_lo, st_hi = accumulate((idx - s) % p, k_blk, v_blk, st_lo, st_hi)
        return k_blk, v_blk, st_lo, st_hi

    def vary(x):
        return jax.lax.pcast(x, axis_name, to="varying")

    def init():
        return (vary(jnp.full((b, hkv, groups, c2), NEG_INF, jnp.float32)),
                vary(jnp.zeros((b, hkv, groups, c2), jnp.float32)),
                vary(jnp.zeros((b, c2, hkv, groups, dh), jnp.float32)))

    st_lo, st_hi = accumulate(idx, k, v, init(), init())   # local block
    _, _, st_lo, st_hi = jax.lax.fori_loop(
        1, p, step, (k, v, st_lo, st_hi))

    def finish(st):
        m, l, o = st
        denom = jnp.maximum(l, 1e-20).transpose(0, 3, 1, 2)[..., None]
        return o / denom

    out = jnp.concatenate([finish(st_lo), finish(st_hi)], axis=1)
    return out.reshape(b, 2 * c2, h, dh).astype(q.dtype)


def zigzag_order(t: int, p: int) -> "jnp.ndarray":
    """Permutation taking natural sequence order to zigzag-sharded order:
    device i's shard_map slice holds half-chunks [i, 2P-1-i]."""
    if t % (2 * p):
        raise ValueError(
            f"zigzag layout needs T divisible by 2*P: T={t}, P={p} "
            "(pad the sequence; a truncating take would silently drop "
            "tokens)")
    c2 = t // (2 * p)
    idx = []
    for i in range(p):
        idx.extend(range(i * c2, (i + 1) * c2))
        idx.extend(range((2 * p - 1 - i) * c2, (2 * p - i) * c2))
    return jnp.asarray(idx, jnp.int32)


def make_zigzag_ring_attention_fn(mesh, axis_name: str = "sp"):
    """shard_map-wrapped zigzag ring attention over full natural-order
    arrays: applies the zigzag layout permutation, runs the balanced ring,
    and inverse-permutes the output. T must divide by 2*P. (A production
    sp serving path would keep the whole session IN zigzag layout and pay
    the permutation never — this wrapper prices it per call, which is fine
    for the structural comparison and parity tests.)"""
    from jax.sharding import PartitionSpec as P

    spec = P(None, axis_name)
    p = mesh.shape[axis_name]

    @jax.jit
    def fn(q, k, v):
        t = q.shape[1]
        order = zigzag_order(t, p)
        inv = jnp.argsort(order)
        sharded = jax.shard_map(
            lambda q_, k_, v_: zigzag_ring_attention(q_, k_, v_, axis_name),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        )
        out = sharded(jnp.take(q, order, axis=1),
                      jnp.take(k, order, axis=1),
                      jnp.take(v, order, axis=1))
        return jnp.take(out, inv, axis=1)

    return fn


def make_ring_attention_fn(mesh, axis_name: str = "sp",
                           skip_masked_blocks: bool = True):
    """shard_map-wrapped ring attention over full arrays.

    q: [B, T, H, Dh]; k/v: [B, T, Hkv, Dh]; T must divide by the axis size.
    Returns the full [B, T, H, Dh] output (sequence re-assembled).
    ``skip_masked_blocks=False`` forces the full-ring compute (the bench's
    comparison baseline for the causal-skip work ratio).
    """
    from jax.sharding import PartitionSpec as P

    spec = P(None, axis_name)

    @jax.jit
    @partial(
        jax.shard_map, mesh=mesh,
        in_specs=(spec, spec, spec), out_specs=spec,
    )
    def fn(q, k, v):
        return ring_attention(q, k, v, axis_name,
                              skip_masked_blocks=skip_masked_blocks)

    return fn
