"""Per-stage executor: the server-side compute path.

TPU-native counterpart of the reference's ``StageConnectionHandler._run_forward``
(``src/rpc_handler.py:149-325``): manage per-session KV, run the stage's layer
span, and either return the next hidden states (intermediate stage) or sample a
token (final stage — sampling happens ON the final server, with the sampling
params and recent-token window taken from request metadata each step).

Replay semantics preserved exactly (``src/rpc_handler.py:176-202``):
  * prefill clears any existing session cache;
  * decode with no cached session and ``is_replay=True`` is treated as a
    prefill chunk (a replacement server rebuilding its KV from the journal);
  * decode with no cached session and no replay flag is a hard error.

XLA-specific design (no reference counterpart — it re-traces per request):
  * the stage step is one jitted function per (cache_bucket, seq_bucket) pair;
    real sequence lengths are padded up to a small set of buckets so an elastic
    server sees a handful of compiles, then pure replay;
  * right-padded prefill is safe end-to-end: padded queries only produce
    garbage OUTPUT rows (discarded here before returning), and padded cache
    rows sit at positions the causal mask hides until a later real token
    overwrites them;
  * KV buffers live in a fixed-budget `KVArena` (admission control before
    dispatch — inside jit the cache write clamps rather than raises).
"""

from __future__ import annotations

import logging
from functools import partial
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..models.config import ModelConfig
from ..models.partition import (
    ROLE_FULL,
    ROLE_LAST,
    ROLE_SEGMENT,
    ROLE_STAGE0,
    StageSpec,
    stage_forward,
)
from ..ops.sampling import RECENT_WINDOW, sample_token
from ..models.transformer import stack_forward_train
from ..telemetry import events as _ev
from ..utils.platform import engine_donation
from .errors import register as _catalog
from .kv_cache import AllocationFailed, KVArena, KVHandle, round_to_bucket
from .messages import (
    BackwardRequest,
    BackwardResponse,
    StageRequest,
    StageResponse,
)

logger = logging.getLogger(__name__)

SEQ_BUCKETS = (1, 8, 16, 32, 64, 128, 256, 512, 1024, 2048, 4096, 8192)


@_catalog
class StageExecutionError(RuntimeError):
    """Server-side hard error (maps to the RuntimeError raised at
    ``src/rpc_handler.py:198-202`` for decode-without-cache)."""


_PREFIX_CHAIN_JIT = None


def _apply_prefix_chain(k, v, segs_k, segs_v):
    """Write a prefix-cache chain's KV segments (each [L, B, G, H, Dh])
    into the leading rows of the session caches in ONE program. Lists are
    pytrees, so jit re-specializes per chain length — stable per shared
    prompt. The fresh arena lease is donated (platform-gated like the
    engines — utils.platform.engine_donation) so a hit updates the
    bucket-sized buffers in place instead of duplicating them.

    Built LAZILY on first use: evaluating engine_donation at module import
    would initialize the JAX backend as an import side effect — breaking
    dcn.initialize's must-run-first contract and freezing the donation
    decision before a CPU fallback could flip it."""
    global _PREFIX_CHAIN_JIT
    if _PREFIX_CHAIN_JIT is None:
        @partial(jax.jit, donate_argnums=engine_donation(0, 1))
        def fn(k, v, segs_k, segs_v):
            zeros = (0,) * k.ndim
            kc = (segs_k[0] if len(segs_k) == 1
                  else jnp.concatenate(segs_k, axis=2))
            vc = (segs_v[0] if len(segs_v) == 1
                  else jnp.concatenate(segs_v, axis=2))
            return (jax.lax.dynamic_update_slice(k, kc, zeros),
                    jax.lax.dynamic_update_slice(v, vc, zeros))

        _PREFIX_CHAIN_JIT = fn
    return _PREFIX_CHAIN_JIT(k, v, segs_k, segs_v)


def verify_drafts_from_logits(
    logits2d: jnp.ndarray, req: StageRequest
) -> "tuple[tuple[int, ...], int]":
    """Final-stage speculative verification over one session's logits.

    logits2d: [T, V] for the T = K+1 positions [last_accepted, d_1..d_K];
    logits2d[i] predicts the token AFTER consuming position i. Returns
    (tokens, n_accepted) with len(tokens) == n_accepted + 1 (accepted run
    plus one correction/bonus token). Shared by the per-session executor
    and the batched adapter so both engines verify identically.

    Greedy (temperature<=0): accept while d_{i+1} == argmax(logits[i]) —
    token-identical to non-speculative greedy decoding
    (``src/rpc_handler.py:334-335`` applies greedy before penalties).
    Sampled (temperature>0): rejection-sampling verification
    (ops.sampling.speculative_verify) — accept draft i with probability
    p_i(d_i), resample the residual on reject — which preserves the
    sampling distribution exactly."""
    drafts = np.asarray(req.draft_tokens, np.int64)
    k = int(drafts.shape[0])
    if not req.sampling.greedy:
        from ..ops.sampling import speculative_verify

        recent = np.zeros((RECENT_WINDOW,), np.int32)
        n = min(len(req.generated_tokens), RECENT_WINDOW)
        if n:
            recent[:n] = np.asarray(req.generated_tokens[-n:], np.int32)
        sp = req.sampling
        toks, n_acc = speculative_verify(
            jax.random.PRNGKey(req.step_seed),
            logits2d.astype(jnp.float32),
            [int(d) for d in drafts], recent, n,
            sp.temperature, sp.top_p, sp.top_k, sp.repetition_penalty)
        return tuple(int(t) for t in toks), int(n_acc)
    preds = np.asarray(jnp.argmax(logits2d, axis=-1))  # [T]
    n_acc = 0
    while n_acc < k and int(preds[n_acc]) == int(drafts[n_acc]):
        n_acc += 1
    return tuple(int(t) for t in preds[: n_acc + 1]), n_acc


def _sample_rows(logits: jnp.ndarray, t_real: int, req: StageRequest) -> np.ndarray:
    """Final-stage sampling from the last REAL token's logits, PER BATCH ROW,
    using the metadata-shipped params + recent window
    (``src/rpc_handler.py:268-307``). logits: [B, T, V] -> int32 [B].

    Each row samples from its own logits with a row-decorrelated fold of the
    step seed (row 0 keeps the unfolded key, so batch-1 output is bit-
    identical to the historical single-row path). The recent-token window is
    session-scoped metadata and therefore shared across rows — matching the
    reference, whose generated-token window is likewise per-session
    (``src/rpc_transport.py:788-798``)."""
    last = logits[:, t_real - 1]  # [B, V] fp32 (lm_head upcasts)
    b = last.shape[0]
    recent = np.zeros((RECENT_WINDOW,), np.int32)
    n = min(len(req.generated_tokens), RECENT_WINDOW)
    if n:
        recent[:n] = np.asarray(req.generated_tokens[-n:], np.int32)
    sp = req.sampling
    base = jax.random.PRNGKey(req.step_seed)
    args = (
        jnp.asarray(recent),
        jnp.asarray(n, jnp.int32),
        jnp.asarray(sp.temperature, jnp.float32),
        jnp.asarray(sp.top_p, jnp.float32),
        jnp.asarray(sp.top_k, jnp.int32),
        jnp.asarray(sp.repetition_penalty, jnp.float32),
    )
    if b == 1:
        # Hot path (every decode step in every serving mode): skip the vmap
        # wrapper + key stack — row 0's key is the unfolded base by contract.
        return np.asarray(sample_token(base, last[0], *args))[None]
    rngs = jnp.stack([base if i == 0 else jax.random.fold_in(base, i)
                      for i in range(b)])
    tokens = jax.vmap(
        sample_token, in_axes=(0, 0, None, None, None, None, None, None)
    )(rngs, last, *args)
    return np.asarray(tokens)


def _sample_last(logits: jnp.ndarray, t_real: int, req: StageRequest) -> int:
    """Batch-1 convenience wrapper over `_sample_rows` (the batched adapter's
    per-slot rows are [1, T, V])."""
    return int(_sample_rows(logits, t_real, req)[0])


class StageExecutor:
    """One pipeline stage's compute engine (one 'server' in reference terms)."""

    def __init__(
        self,
        cfg: ModelConfig,
        spec: StageSpec,
        params: Dict[str, Any],
        arena: Optional[KVArena] = None,
        *,
        max_cache_bytes: int = 1 << 30,
        cache_dtype=jnp.float32,
        peer_id: str = "local",
        debug_activation_checks: bool = False,
        max_chunk_bytes: int = 256 * 1024 * 1024,
        offload: bool = False,
        keep_layers_resident: int = 0,
        tp_mesh: Optional["jax.sharding.Mesh"] = None,
        tp_axis: str = "tp",
        prefix_cache_bytes: int = 0,
    ):
        self.cfg = cfg
        self.spec = spec
        self.params = params
        self.peer_id = peer_id
        # Tensor parallelism INSIDE the serving path (the reference wraps
        # every serving block in TP, petals/server/backend.py:43): params are
        # megatron-sharded over the local ('tp',) mesh, the step runs through
        # parallel.tensor_parallel's shard_map, and the session KV shards
        # over kv heads. Protocol-invisible: requests/responses are
        # replicated at the boundary.
        self.tp_mesh = tp_mesh
        self.tp_axis = tp_axis
        if tp_mesh is not None:
            from ..parallel.tensor_parallel import (
                shard_stage_params,
                validate_tp,
            )

            if offload:
                raise ValueError(
                    "tensor parallelism and host offload are mutually "
                    "exclusive on one executor (a TP span is HBM-resident "
                    "by design)")
            validate_tp(cfg, tp_mesh.shape[tp_axis])
            self.params = params = shard_stage_params(
                cfg, params, tp_mesh, tp_axis)
        # Prefill chunk budget (petals ``backend.py:129-143``
        # max_chunk_size_bytes): long prefills run as several bounded chunks
        # over the same session cache instead of one huge activation.
        self.max_chunk_bytes = max_chunk_bytes
        # Host-offload layer streaming (the reference's --use_cpu_offload /
        # --keep_layers_on_gpu, component 6): span weights live in host
        # memory and stream through HBM one layer at a time.
        self.offload = offload
        self.keep_layers_resident = max(keep_layers_resident, 0)
        if offload:
            # Pin the executor's own copy to HOST first, so the runner's
            # streamed layers alias host arrays and the only device-resident
            # weights are the pinned prefix + embed/norm/head. Without this,
            # self.params (and each cached sub_params slice) would keep the
            # full span alive in HBM — defeating the offload entirely.
            host = jax.devices("cpu")[0]
            self.params = jax.tree.map(
                lambda a: jax.device_put(a, host), params)
            params = self.params
        if tp_mesh is None and not offload:
            # Engine-side fused-QKV layout (one projection matmul per
            # layer; bitwise-identical — models/transformer.fuse_qkv_params).
            # TP keeps the canonical split (its shard boundaries must align
            # per-projection); offload keeps it (host-streaming layer trees
            # are keyed to the stored layout).
            from ..models.transformer import fuse_qkv_params

            self.params = params = fuse_qkv_params(params)
        self.cache_dtype = jnp.dtype(cache_dtype)
        kv_sharding = None
        tp_degree = 1
        if tp_mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            kv_sharding = NamedSharding(tp_mesh, P(None, None, None, tp_axis))
            tp_degree = tp_mesh.shape[tp_axis]
        self.arena = arena or KVArena(
            num_layers=max(spec.num_layers, 1),
            num_kv_heads=cfg.num_kv_heads,
            head_dim=cfg.head_dim,
            max_bytes=max_cache_bytes,
            dtype=cache_dtype,
            sharding=kv_sharding,
            bytes_divisor=tp_degree,
        )
        self.debug_activation_checks = debug_activation_checks
        self.requests_served = 0
        # Prompt-prefix KV reuse (runtime.prefix_cache): > 0 enables a
        # bounded content-addressed store; repeat prefills copy cached KV
        # rows instead of recomputing the span for the shared prefix.
        self.prefix_store = None
        if prefix_cache_bytes > 0:
            from .prefix_cache import PrefixStore

            self.prefix_store = PrefixStore(prefix_cache_bytes)

        # Sub-span execution units, keyed by relative layer range (a, b). A
        # request may cover only part of the loaded span (the uid-chain of
        # petals/server/handler.py:522-530): elastic placement yields
        # OVERLAPPING server spans, and running the full span on a hidden
        # state that already passed some of its blocks silently corrupts the
        # output. The route assigns each hop an exact range; we execute
        # exactly that. Each entry holds (sub_spec, sub_params, jitted step);
        # jax.jit then caches one executable per (seq_bucket, cache_bucket)
        # input-shape pair — the bucket padding below bounds how many shapes
        # it ever sees.
        self._subspans: Dict[tuple, tuple] = {}
        # (a, b) -> prompt-injecting step callable (deep-prompt requests
        # only; kept separate so every _subspans entry stays a 3-tuple).
        self._prompt_steps: Dict[tuple, Any] = {}
        self._get_subspan(0, spec.num_layers)

    def _get_subspan(self, a: int, b: int):
        key = (a, b)
        entry = self._subspans.get(key)
        if entry is not None:
            return entry
        spec = self.spec
        if a == 0 and b == spec.num_layers:
            sub_spec, sub_params = spec, self.params
        else:
            first = spec.is_first and a == 0
            last = spec.is_last and b == spec.num_layers
            role = (ROLE_FULL if first and last else ROLE_STAGE0 if first
                    else ROLE_LAST if last else ROLE_SEGMENT)
            sub_spec = StageSpec(spec.index, role, spec.start + a, spec.start + b)
            sub_params = {}
            if "layers" in self.params:
                sub_params["layers"] = jax.tree.map(
                    lambda x: x[a:b], self.params["layers"]
                )
            if first and "embed" in self.params:
                sub_params["embed"] = self.params["embed"]
            if last:
                for k in ("final_norm", "lm_head"):
                    if k in self.params:
                        sub_params[k] = self.params[k]
                if self.cfg.tie_word_embeddings and "embed" in self.params:
                    sub_params.setdefault("embed", {})
                    sub_params["embed"] = {**sub_params["embed"],
                                           "wte": self.params["embed"]["wte"]}

        cfg = self.cfg

        if self.offload:
            from .offload import OffloadedSpanRunner

            step = OffloadedSpanRunner(
                cfg, sub_spec, sub_params,
                keep_resident=self.keep_layers_resident,
            )
        elif self.tp_mesh is not None:
            from ..parallel.tensor_parallel import make_tp_stage_fn

            step = make_tp_stage_fn(
                cfg, sub_spec, self.tp_mesh, self.tp_axis,
                donate_cache=bool(engine_donation(0)),
            )(sub_params)
        else:
            @partial(jax.jit, donate_argnums=engine_donation(2, 3))
            def step(params, x, k_cache, v_cache, cache_len):
                return stage_forward(cfg, sub_spec, params, x, k_cache,
                                     v_cache, cache_len)

        entry = (sub_spec, sub_params, step)
        self._subspans[key] = entry
        return entry

    def _get_prompt_step(self, a: int, b: int):
        """Step for inference requests carrying DEEP PROMPTS
        (``petals/server/block_functions.py:57-65,171-226``): same math as
        the plain subspan step plus a per-layer prompt injection at each
        block's entry, on EVERY engine (plain jit, offload, tp). Cached
        separately — the plain hot path keeps its prompt-free signature
        (and donation) untouched; jit re-specializes per prompts shape."""
        key = (a, b)
        entry = self._prompt_steps.get(key)
        if entry is not None:
            return entry
        sub_spec, sub_params, plain_step = self._get_subspan(a, b)
        cfg = self.cfg

        if self.offload:
            # OffloadedSpanRunner takes prompts as a trailing optional arg.
            step = plain_step
        elif self.tp_mesh is not None:
            from ..parallel.tensor_parallel import make_tp_stage_fn

            step = make_tp_stage_fn(
                cfg, sub_spec, self.tp_mesh, self.tp_axis,
                donate_cache=bool(engine_donation(0)), with_prompts=True,
            )(sub_params)
        else:
            @partial(jax.jit, donate_argnums=engine_donation(2, 3))
            def step(params, x, k_cache, v_cache, cache_len, prompts):
                return stage_forward(cfg, sub_spec, params, x, k_cache,
                                     v_cache, cache_len, prompts=prompts)

        self._prompt_steps[key] = step
        return step

    def _resolve_range(self, req: StageRequest) -> tuple:
        """Absolute request block range -> relative (a, b) within the span."""
        a = 0 if req.start_block is None else req.start_block - self.spec.start
        b = (self.spec.num_layers if req.end_block is None
             else req.end_block - self.spec.start)
        if not (0 <= a < b <= max(self.spec.num_layers, 1)):
            raise StageExecutionError(
                f"requested blocks [{req.start_block},{req.end_block}) outside "
                f"served span [{self.spec.start},{self.spec.end})"
            )
        return a, b

    # ------------------------------------------------------------------
    # Session / cache management (mirrors rpc_handler session semantics)
    # ------------------------------------------------------------------

    def _allocate(self, req: StageRequest, num_layers: int, batch: int) -> KVHandle:
        """Arena lease as a STAGE error: a full arena is peer-local state —
        surfacing it as StageExecutionError puts it in the client's retryable
        taxonomy, so the session fails over to a replica with free memory
        instead of crashing the generation."""
        try:
            handle = self.arena.allocate(req.session_id, req.max_length,
                                         num_layers=num_layers, batch=batch)
        except AllocationFailed as exc:
            raise StageExecutionError(str(exc)) from exc
        _ev.emit("server_session_open", session_id=req.session_id,
                 peer=self.peer_id, max_length=req.max_length,
                 replay=req.is_replay)
        return handle

    def _session_cache(self, req: StageRequest, num_layers: int,
                       batch: int = 1) -> KVHandle:
        handle = self.arena.get(req.session_id)
        if req.is_prefill:
            # Prefill (re)starts the session: clear existing cache
            # (src/rpc_handler.py:180-182).
            if handle is not None:
                self.arena.free(req.session_id)
            handle = self._allocate(req, num_layers, batch)
        elif handle is None:
            if req.is_replay:
                # Replacement server rebuilding KV from the client's journal:
                # treat the first replayed decode as a prefill
                # (src/rpc_handler.py:187-196).
                handle = self._allocate(req, num_layers, batch)
            else:
                raise StageExecutionError(
                    f"session {req.session_id}: decode step without KV cache "
                    "and not a replay (src/rpc_handler.py:198-202 semantics)"
                )
        if (not req.is_prefill and handle.cache_len != req.cur_len
                and not req.is_replay and req.start_from_position is None):
            # The reference logs and proceeds with the server's own count
            # (src/rpc_handler.py:206-225). A rewinding step (cur_len ==
            # start_from_position < cache_len) is NOT a mismatch — forward()
            # adopts the client's position via handle.rewind.
            logger.warning(
                "session %s: past-len mismatch client=%d server=%d; "
                "trusting server", req.session_id, req.cur_len, handle.cache_len,
            )
        return handle

    # ------------------------------------------------------------------
    # Forward
    # ------------------------------------------------------------------

    def forward(self, req: StageRequest) -> StageResponse:
        """Run one step of this stage for one session."""
        a, b = self._resolve_range(req)
        sub_spec, sub_params, step = self._get_subspan(a, b)

        prompts = None
        if req.prompts is not None:
            # Inference-time deep prompt tuning (petals
            # block_functions.py:171-226): inject the client's learned
            # per-block prompts at every block entry, every step.
            prompts = jnp.asarray(req.prompts)
            if prompts.ndim != 3 or prompts.shape[0] != b - a:
                raise StageExecutionError(
                    f"prompts shape {tuple(prompts.shape)} does not cover "
                    f"the requested {b - a} blocks (want [span, pre, D])"
                )
            step = self._get_prompt_step(a, b)

        x = jnp.asarray(req.hidden)
        # stage0 consumes int token ids [B, T]; later stages float hidden
        # [B, T, D] (uniform signature, src/llama_partition.py:99-137).
        want_ndim = 2 if sub_spec.is_first else 3
        if x.ndim != want_ndim:
            raise StageExecutionError(
                f"stage {self.spec.index} expects ndim={want_ndim}, got {x.shape}"
            )
        handle = self._session_cache(req, num_layers=max(b - a, 1),
                                     batch=x.shape[0])
        if handle.k is not None and handle.k.shape[0] != max(b - a, 1):
            raise StageExecutionError(
                f"session {req.session_id} was allocated for "
                f"{handle.k.shape[0]} layers but the request covers {b - a} "
                "(a route must use a stable block range per hop)"
            )
        if req.start_from_position is not None and not req.is_prefill:
            # Session rewind (petals handler.py:163-168): shrink the valid KV
            # prefix before this step — the client restarts generation from an
            # earlier position.
            try:
                handle.rewind(req.start_from_position)
            except ValueError as exc:
                raise StageExecutionError(str(exc)) from exc
        if req.hypo_ids is not None and not req.is_prefill:
            # Beam reorder BEFORE the step (petals backend.py:154-158):
            # hypothesis i continues from old KV row hypo_ids[i]. May also
            # GROW the batch (e.g. hypo_ids=(0,)*nb expands a batch-1 prefill
            # into nb beam rows) — re-lease the arena bytes first.
            ids_np = np.asarray(req.hypo_ids, np.int64)
            if ids_np.shape[0] != x.shape[0]:
                raise StageExecutionError(
                    f"hypo_ids has {ids_np.shape[0]} rows, batch is {x.shape[0]}"
                )
            old_batch = handle.k.shape[1]
            # jnp.take clamps out-of-range indices — that would silently
            # continue a hypothesis from the wrong KV row, so check here.
            if ids_np.size and (ids_np.min() < 0 or ids_np.max() >= old_batch):
                raise StageExecutionError(
                    f"hypo_ids {tuple(req.hypo_ids)} out of range for KV "
                    f"batch {old_batch}"
                )
            if x.shape[0] != old_batch:
                try:
                    self.arena.resize_batch(req.session_id, x.shape[0])
                except AllocationFailed as exc:
                    # Same taxonomy as _allocate: let the client fail over to
                    # a replica whose arena can hold the expanded batch.
                    raise StageExecutionError(str(exc)) from exc
            ids = jnp.asarray(ids_np, jnp.int32)
            handle.k = jnp.take(handle.k, ids, axis=1)
            handle.v = jnp.take(handle.v, ids, axis=1)
        if handle.k is not None and handle.k.shape[1] != x.shape[0]:
            raise StageExecutionError(
                f"session {req.session_id} holds KV for batch "
                f"{handle.k.shape[1]}, request batch is {x.shape[0]}"
            )
        t_real = req.seq_len
        handle.admit(t_real)

        t = x.shape[1]
        if t != t_real:
            raise StageExecutionError(f"seq_len {t_real} != tensor T {t}")

        # Prompt-prefix reuse (runtime.prefix_cache): on a prefill whose
        # leading grains were served before THROUGH THESE BLOCKS, copy the
        # cached KV segments into the fresh arena lease and compute only the
        # remainder. The rolling chain digest gives longest-shared-prefix
        # matching at grain granularity — two prompts sharing a system
        # preamble reuse its grains with no annotation of where it ends.
        # The shareable region is clamped to t_real - 1 so the final stage
        # always has a computed row to sample from. Exotic shapes (deep
        # prompts, beam reorder, drafts) skip the path — their step
        # semantics aren't a pure function of the prefix.
        pfx_skip = 0
        pfx_outs: list = []
        pfx_register: list = []  # (key, grain_start, grain_end) to register
        if (self.prefix_store is not None and req.is_prefill
                and req.prefix_len > 0 and prompts is None
                and req.hypo_ids is None and req.draft_tokens is None
                and handle.k is not None):
            from .prefix_cache import chain_digests

            grain = self.prefix_store.grain
            n_grains = min(req.prefix_len, t_real - 1) // grain
            if n_grains > 0:
                coords = (self.spec.start + a, self.spec.start + b,
                          x.shape[0], str(x.dtype), str(self.cache_dtype),
                          req.model)
                # Digest from the HOST-side request buffer when the wire
                # already delivered one — hashing the device copy would pay
                # a D2H transfer + sync on every store-enabled prefill,
                # misses included.
                src = (req.hidden if isinstance(req.hidden, np.ndarray)
                       else x)
                xp = np.asarray(src[:, :n_grains * grain])
                blocks = [
                    np.ascontiguousarray(xp[:, g * grain:(g + 1) * grain])
                    .tobytes() for g in range(n_grains)]
                keys = chain_digests(blocks, coords)
                chain = self.prefix_store.lookup_chain(
                    keys, need_out=not sub_spec.is_last)
                if chain:
                    # ONE dispatch applies the whole chain (concat + both
                    # cache writes inside one jitted program — jit
                    # specializes per chain length, which is stable for a
                    # given shared prompt). Eager per-grain updates would
                    # cost a device round trip each.
                    handle.k, handle.v = _apply_prefix_chain(
                        handle.k, handle.v,
                        [e.k for e in chain], [e.v for e in chain])
                    pfx_outs = [e.out for e in chain if e.out is not None]
                    pfx_skip = len(chain) * grain
                    handle.advance(pfx_skip)
                pfx_register = [
                    (keys[g], g * grain, (g + 1) * grain)
                    for g in range(len(chain), n_grains)]

        # Chunked prefill (petals backend.py:129-143): split an oversized
        # request into byte-bounded chunks over the same session cache. The
        # numerics are identical (each chunk attends causally to everything
        # already written); what the bound buys is peak activation memory —
        # and prefills longer than the largest jit seq bucket become possible
        # at all. Intermediate stages concatenate chunk outputs (the next
        # stage needs every token's hidden state); the final stage samples
        # from the LAST chunk's logits only.
        chunk = self._max_chunk_tokens(x.shape[0])
        outs = []
        off = pfx_skip
        while off < t_real:
            n = min(chunk, t_real - off)
            xc = jax.lax.slice_in_dim(x, off, off + n, axis=1)
            outs.append(self._dispatch_chunk(step, sub_params, xc, handle, n,
                                             prompts=prompts))
            off += n
        self.requests_served += 1

        if pfx_register:
            # Register the grains the chain lookup didn't cover. KV rows
            # come from the arena lease (already written by the chunk
            # loop); intermediate stages also keep the output rows they'd
            # need to forward on a future hit. Slicing copies — entries
            # must outlive this session's arena buffers.
            full = None
            if not sub_spec.is_last:
                full = (outs[0] if len(outs) == 1
                        else jnp.concatenate(outs, axis=1))
                outs = [full]
            for key, g0, g1 in pfx_register:
                out_rows = (None if full is None
                            else full[:, g0 - pfx_skip:g1 - pfx_skip])
                self.prefix_store.put(key, handle.k[:, :, g0:g1],
                                      handle.v[:, :, g0:g1], out_rows)

        if sub_spec.is_last:
            if req.draft_tokens is not None:
                return self._verify_drafts(req, outs, handle)
            out = outs[-1]  # chunk outputs are trimmed; sample from its tail
            if req.num_logprobs > 0:
                # Beam mode: per-row top-N candidates, raw log-softmax (beam
                # search scores, no sampling).
                last = out[:, -1].astype(jnp.float32)  # [B, V]
                logp = jax.nn.log_softmax(last, axis=-1)
                vals, idx = jax.lax.top_k(logp, req.num_logprobs)
                return StageResponse(
                    session_id=req.session_id, cache_len=handle.cache_len,
                    top_tokens=tuple(tuple(int(t) for t in row)
                                     for row in np.asarray(idx)),
                    top_logprobs=tuple(tuple(float(v) for v in row)
                                       for row in np.asarray(vals)),
                )
            row_tokens = _sample_rows(out, out.shape[1], req)
            return StageResponse(
                session_id=req.session_id, token_id=int(row_tokens[0]),
                token_ids=(tuple(int(t) for t in row_tokens)
                           if row_tokens.shape[0] > 1 else None),
                cache_len=handle.cache_len,
            )
        out = outs[0] if len(outs) == 1 else jnp.concatenate(outs, axis=1)
        if pfx_outs:
            # Hit: the next hop needs every token's hidden state — prepend
            # the stored prefix segments' outputs to the computed suffix.
            out = jnp.concatenate([*pfx_outs, out], axis=1)
        if self.debug_activation_checks:
            # Activation-explosion guard (src/rpc_handler.py:316-319). Opt-in:
            # the float() forces a host sync per hop per token, which would
            # serialize the decode hot path if always on.
            max_abs = float(jnp.max(jnp.abs(out)))
            if max_abs > 100.0:
                logger.warning(
                    "session %s stage %d: activation explosion |x|=%.1f",
                    req.session_id, self.spec.index, max_abs,
                )
        return StageResponse(
            session_id=req.session_id, hidden=out, cache_len=handle.cache_len
        )

    def _max_chunk_tokens(self, batch: int) -> int:
        """Tokens per prefill chunk: the byte budget over the per-token
        activation footprint (batch x hidden x fp32 x span layers — the
        attention-memory estimate of petals ``backend.py:146-152``), capped
        at the largest jit seq bucket and floored at one bucket."""
        per_token = batch * self.cfg.hidden_size * 4 * max(self.spec.num_layers, 1)
        est = self.max_chunk_bytes // max(per_token, 1)
        est = max(16, min(int(est), SEQ_BUCKETS[-1]))
        # Align DOWN to a jit seq bucket: a chunk size strictly between
        # buckets would pad every full chunk up to the next bucket — up to
        # ~2x wasted attention/MLP work per chunk.
        return max(b for b in SEQ_BUCKETS if b <= est)

    def _dispatch_chunk(self, step, sub_params, x: jnp.ndarray,
                        handle: KVHandle, n: int,
                        prompts: Optional[jnp.ndarray] = None) -> jnp.ndarray:
        """Run ONE bucket-padded jitted step of n real tokens against the
        session cache; advances the cache and returns the TRIMMED output.
        Bucket-padded tail positions may receive a deep-prompt injection
        too (absolute index < pre_seq); harmless — their output rows are
        trimmed here and their KV rows sit past cache_len until a real
        token overwrites them."""
        tb = round_to_bucket(n, SEQ_BUCKETS)
        if handle.cache_len + tb > handle.bucket_len:
            # Padding would make the jitted dynamic_update_slice clamp its
            # start index (writing garbage over the newest real rows). Fall
            # back to the exact length — one extra compile at the tail of a
            # session beats silent cache corruption.
            tb = n
        if tb != n:
            pad = ((0, 0), (0, tb - n)) + (((0, 0),) if x.ndim == 3 else ())
            x = jnp.pad(x, pad)
        cache_len = jnp.asarray(handle.cache_len, jnp.int32)
        if prompts is None:
            out, handle.k, handle.v = step(
                sub_params, x, handle.k, handle.v, cache_len
            )
        else:
            out, handle.k, handle.v = step(
                sub_params, x, handle.k, handle.v, cache_len, prompts
            )
        handle.advance(n)
        return out[:, :n]

    def _verify_drafts(self, req: StageRequest, outs, handle: KVHandle) -> StageResponse:
        """Speculative verification on the final stage.

        The request's T = 1 + K positions are [last_accepted, d_1..d_K];
        logits[i] predict the token AFTER consuming position i. Returns the
        accepted run plus one correction/bonus token, and REWINDS this
        stage's own KV past the rejected tail so the session is immediately
        consistent here; upstream stages drop their overhang via the next
        request's ``start_from_position`` (rewind semantics of petals
        handler.py:163-168, reused as speculative rollback).

        Greedy (temperature<=0): accept while d_{i+1} == argmax(logits[i]) —
        token-identical to non-speculative greedy decoding
        (``src/rpc_handler.py:334-335`` applies greedy before penalties).
        Sampled (temperature>0): rejection-sampling verification
        (ops.sampling.speculative_verify) — accept draft i with probability
        p_i(d_i), resample the residual on reject — which preserves the
        sampling distribution exactly, so temperature>0 gets the same
        round-trip amortization.
        """
        k = len(req.draft_tokens)
        t_real = req.seq_len
        if t_real != k + 1:
            raise StageExecutionError(
                f"speculative step carries {t_real} positions for {k} drafts "
                "(want K+1)"
            )
        logits = outs[0] if len(outs) == 1 else jnp.concatenate(outs, axis=1)
        tokens, n_acc = verify_drafts_from_logits(logits[0], req)
        # Rewind our own cache: positions for rejected drafts are garbage.
        valid = req.cur_len + n_acc + 1
        try:
            handle.rewind(valid)
        except ValueError as exc:  # pragma: no cover - defensive
            raise StageExecutionError(str(exc)) from exc
        return StageResponse(
            session_id=req.session_id,
            tokens=tokens,
            n_accepted=n_acc,
            cache_len=handle.cache_len,
        )

    # ------------------------------------------------------------------
    # Fine-tuning path (vendored rpc_forward/rpc_backward training surface,
    # petals/server/handler.py:352-488, block_functions.py:32-141)
    # ------------------------------------------------------------------

    def _train_fns(self, a: int, b: int):
        """Jitted (forward, backward) for blocks [a, b) of the loaded span.
        Stateless: no KV, no session; frozen span weights; grads flow to
        inputs (+ prompts and LoRA adapters — jit re-specializes per
        prompts/lora shape/None; lora_scale is static per compile)."""
        key = ("train", a, b)
        entry = self._subspans.get(key)
        if entry is not None:
            return entry
        cfg = self.cfg
        if a == 0 and b == self.spec.num_layers:
            layers = self.params["layers"]  # no duplicate HBM copy
        else:
            layers = jax.tree.map(lambda x: x[a:b], self.params["layers"])

        def f(x, prompts, lora, lora_scale):
            from ..models.lora import merge_lora

            bsz, t, _ = x.shape
            positions = jnp.broadcast_to(
                jnp.arange(t, dtype=jnp.int32)[None, :], (bsz, t)
            )
            return stack_forward_train(
                cfg, merge_lora(cfg, layers, lora, lora_scale), x, positions,
                prompts=prompts)

        fwd = jax.jit(f, static_argnums=3)

        @partial(jax.jit, static_argnums=3)
        def bwd(x, prompts, lora, lora_scale, grad_out):
            _, vjp = jax.vjp(
                lambda x_, p_, l_: f(x_, p_, l_, lora_scale),
                x, prompts, lora)
            return vjp(grad_out.astype(x.dtype))

        entry = (fwd, bwd)
        self._subspans[key] = entry
        return entry

    def _train_args(self, req) -> tuple:
        """Shared validation/padding for train_forward and backward."""
        a, b = self._resolve_range(req)
        x = jnp.asarray(req.hidden)
        if x.ndim != 3:
            raise StageExecutionError(
                f"training forward expects hidden [B, T, D], got {x.shape}"
            )
        if x.shape[1] != req.seq_len:
            raise StageExecutionError(
                f"seq_len {req.seq_len} != tensor T {x.shape[1]}"
            )
        prompts = None if req.prompts is None else jnp.asarray(req.prompts)
        if prompts is not None and prompts.shape[0] != b - a:
            raise StageExecutionError(
                f"prompts cover {prompts.shape[0]} layers, request spans {b - a}"
            )
        lora = req.lora
        if lora:
            attn = self.params["layers"].get("attn", {})
            from ..models.quant import is_quantized

            if is_quantized(attn):
                # merge_lora adds deltas to the stored weights, which for a
                # --quant span are packed QuantizedTensors (dequantized only
                # inside the layer scan) — fail as a clean stage error, not
                # a TypeError the client misreads as a dead peer.
                raise StageExecutionError(
                    "LoRA training is unsupported on a quantized span "
                    "(serve this span unquantized to fine-tune against it)")
            for t, ab in lora.items():
                if t not in attn and not (
                        "wqkv" in attn and t in ("wq", "wk", "wv")):
                    raise StageExecutionError(
                        f"LoRA target {t!r} not in this span's attn params")
                for leaf in ("a", "b"):
                    arr = ab.get(leaf)
                    if arr is None or arr.shape[0] != b - a:
                        raise StageExecutionError(
                            f"LoRA {t}/{leaf} covers "
                            f"{None if arr is None else arr.shape[0]} layers, "
                            f"request spans {b - a}")
        else:
            lora = None
        return a, b, x, prompts, lora

    def train_forward(self, req: StageRequest) -> StageResponse:
        """Cache-free span forward of the BLOCKS only (no head/sampling) —
        the training rpc_forward. Sequence padded to the shared buckets so an
        epoch of varying lengths stays within a handful of compiles."""
        a, b, x, prompts, lora = self._train_args(req)
        fwd, _ = self._train_fns(a, b)
        t_real = req.seq_len
        tb = round_to_bucket(t_real, SEQ_BUCKETS)
        if tb != t_real:
            x = jnp.pad(x, ((0, 0), (0, tb - t_real), (0, 0)))
        out = fwd(x, prompts, lora, float(req.lora_scale))
        self.requests_served += 1
        return StageResponse(
            session_id=req.session_id, hidden=out[:, :t_real], cache_len=0
        )

    def backward(self, req: BackwardRequest) -> BackwardResponse:
        """Re-forward blocks [a, b) from the supplied input and return
        (grad_input, grad_prompts). Activations are recomputed, never stored
        between training RPCs — same contract as the reference's
        ``run_rpc_backward`` re-forward (block_functions.py:106-124)."""
        a, b, x, prompts, lora = self._train_args(req)
        g = jnp.asarray(req.grad_output)
        if g.shape != x.shape:
            raise StageExecutionError(
                f"grad_output shape {g.shape} != hidden shape {x.shape}"
            )
        _, bwd = self._train_fns(a, b)
        t_real = req.seq_len
        tb = round_to_bucket(t_real, SEQ_BUCKETS)
        if tb != t_real:
            pad = ((0, 0), (0, tb - t_real), (0, 0))
            x = jnp.pad(x, pad)
            g = jnp.pad(g, pad)  # zero cotangents on padding
        gx, gp, gl = bwd(x, prompts, lora, float(req.lora_scale), g)
        self.requests_served += 1
        return BackwardResponse(
            session_id=req.session_id,
            grad_input=gx[:, :t_real],
            grad_prompts=gp,
            grad_lora=gl,
        )

    # ------------------------------------------------------------------
    # Lifecycle
    # ------------------------------------------------------------------

    def warmup(self, seq_buckets=(16, 8, 1), max_length: int = 128) -> None:
        """Pre-compile the common (seq bucket, cache bucket) step shapes so
        the first real request doesn't pay 30-120s of XLA compile inside the
        client's RPC deadline (a fresh server's first prefill would
        otherwise read as a dead peer and trigger spurious failover)."""
        b = 1
        cur = 0
        for i, t in enumerate(seq_buckets):
            if self.spec.is_first:
                x = jnp.zeros((b, t), jnp.int32)
            else:
                x = jnp.zeros((b, t, self.cfg.hidden_size), jnp.float32)
            try:
                self.forward(StageRequest(
                    session_id="__warmup__", hidden=x, seq_len=t,
                    cur_len=cur, is_prefill=(i == 0),
                    max_length=max_length))
                cur += t
            except Exception as exc:  # warmup must never kill a server
                logger.warning("warmup step (T=%d) failed: %s", t, exc)
        self.drop_session("__warmup__")

    def drop_session(self, session_id: str) -> None:
        if self.arena.get(session_id) is not None:
            _ev.emit("server_session_closed", session_id=session_id,
                     peer=self.peer_id)
        self.arena.free(session_id)

    def session_len(self, session_id: str) -> Optional[int]:
        h = self.arena.get(session_id)
        return None if h is None else h.cache_len
