"""Cache-aware multi-head attention (MHA/GQA/MQA) with static shapes.

TPU-first counterpart of the reference's manual sdpa + legacy-tuple KV concat
(``petals/llama/block.py:123-141``): instead of concatenating growing
per-session tuples, keys/values live in a preallocated fixed-size cache and new
tokens are written with ``dynamic_update_slice`` — shapes never change, so the
prefill and decode step functions each compile exactly once.

Softmax accumulates in float32 (matches reference ``block.py:138``: fp32
softmax), outputs return to the activation dtype (bfloat16 on TPU).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

NEG_INF = -1e30

# Flash-attention dispatch: "auto" uses the Pallas kernel on TPU whenever the
# shape qualifies (bucketed cache >= _MIN_CACHE_LEN), pure XLA elsewhere;
# "on" forces it (interpret-mode on CPU — for tests); "off" forces the
# pure-XLA path. DEFAULT IS OFF: measured honestly (hard host-fetch sync,
# fused-scan decode, v5e) XLA's fused attention beat the kernel at every
# cache length tried (e.g. 3.5 vs 6.7 ms/step at S=8192 on a 0.5B model) —
# the kernel's unfused custom-call boundary costs more than its streaming
# saves on this generation. Revisit per hardware with set_flash_attention.
_FLASH_MODE = "off"


def set_flash_attention(mode: str) -> None:
    global _FLASH_MODE
    if mode not in ("auto", "on", "off"):
        raise ValueError(f"flash mode {mode!r} not in auto/on/off")
    _FLASH_MODE = mode


def _flash_dispatch(s: int, t: int, groups: int, hkv: int, dh: int,
                    itemsize: int = 2) -> bool:
    if _FLASH_MODE == "off":
        return False
    from .flash_attention import supports_flash

    if _FLASH_MODE == "on":
        # Forced mode ignores the perf threshold (min cache length) but still
        # requires the kernel to be ABLE to run the shape.
        if not supports_flash(s, t, groups, hkv, dh, itemsize,
                              min_cache_len=0):
            raise ValueError(
                f"flash attention forced on but shape (S={s}, T={t}, "
                f"G={groups}, Hkv={hkv}, Dh={dh}) is unsupported"
            )
        return True
    return (supports_flash(s, t, groups, hkv, dh, itemsize)
            and jax.default_backend() == "tpu")


def update_kv_cache(
    k_cache: jnp.ndarray,
    v_cache: jnp.ndarray,
    k_new: jnp.ndarray,
    v_new: jnp.ndarray,
    cache_len: jnp.ndarray,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Write T new tokens at positions [cache_len, cache_len+T).

    k_cache/v_cache: [B, S, Hkv, Dh]; k_new/v_new: [B, T, Hkv, Dh];
    cache_len: scalar int32.

    CONTRACT: cache_len + T <= S. Under jit, ``dynamic_update_slice`` CLAMPS an
    out-of-range start index instead of raising, which would silently overwrite
    the newest cache rows. Callers must enforce max-length admission control
    BEFORE dispatching the step — the runtime does this at session level
    (`runtime.kv_cache`), mirroring the reference's ``inference_max_length``
    guard (``petals/server/block_functions.py:193-197``).
    """
    start = (0, cache_len.astype(jnp.int32), 0, 0)
    k_cache = jax.lax.dynamic_update_slice(k_cache, k_new.astype(k_cache.dtype), start)
    v_cache = jax.lax.dynamic_update_slice(v_cache, v_new.astype(v_cache.dtype), start)
    return k_cache, v_cache


def cached_attention(
    q: jnp.ndarray,
    k_cache: jnp.ndarray,
    v_cache: jnp.ndarray,
    cache_len: jnp.ndarray,
    *,
    sliding_window: Optional[int] = None,
) -> jnp.ndarray:
    """Causal attention of T query tokens over a cache holding cache_len+T keys.

    q: [B, T, H, Dh] — query i has absolute position cache_len + i.
    k_cache/v_cache: [B, S, Hkv, Dh] with the new keys already written.
    Returns [B, T, H, Dh].

    Right-padded prefill is safe: a real query at position i only attends to
    keys j <= cache_len + i, all of which are real tokens; padded queries
    produce garbage rows that the caller discards.
    """
    b, t, h, dh = q.shape
    s = k_cache.shape[1]
    hkv = k_cache.shape[2]
    groups = h // hkv

    if _flash_dispatch(s, t, groups, hkv, dh, q.dtype.itemsize):
        return _flash_diffable(sliding_window, q, k_cache, v_cache, cache_len)

    return _xla_cached_attention(q, k_cache, v_cache, cache_len,
                                 sliding_window)


def _xla_cached_attention(q, k_cache, v_cache, cache_len, sliding_window):
    b, t, h, dh = q.shape
    s = k_cache.shape[1]
    hkv = k_cache.shape[2]
    groups = h // hkv
    # Keep cache operands in their storage dtype (bf16 on TPU) — converting the
    # whole [B,S,Hkv,Dh] cache to fp32 would double HBM traffic per decode
    # step. fp32 accumulation comes from preferred_element_type instead.
    q = q * (dh ** -0.5)

    # [B, T, Hkv, G, Dh] x [B, S, Hkv, Dh] -> [B, Hkv, G, T, S]
    qg = q.reshape(b, t, hkv, groups, dh)
    scores = jnp.einsum(
        "bthgd,bshd->bhgts", qg, k_cache, preferred_element_type=jnp.float32
    )

    q_pos = cache_len + jnp.arange(t, dtype=jnp.int32)  # [T]
    k_pos = jnp.arange(s, dtype=jnp.int32)  # [S]
    allowed = k_pos[None, :] <= q_pos[:, None]  # causal
    if sliding_window is not None:
        allowed &= k_pos[None, :] > (q_pos[:, None] - sliding_window)
    scores = jnp.where(allowed[None, None, None, :, :], scores, NEG_INF)

    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum(
        "bhgts,bshd->bthgd",
        probs.astype(v_cache.dtype),
        v_cache,
        preferred_element_type=jnp.float32,
    )
    return out.reshape(b, t, h, dh).astype(q.dtype)


# ---------------------------------------------------------------------------
# Differentiable flash wrapper. The Pallas kernel has no VJP rule, but the
# cache-free TRAINING forward (models/transformer.py stack_forward_train →
# cached_attention with s == t) can route through it — so the flash path
# carries a custom_vjp whose backward differentiates the mathematically
# identical XLA implementation from recomputed residuals (same recompute-
# don't-store contract as the training RPCs, petals block_functions.py:
# 106-124). Forward stays kernel-fast; gradients stay exact.
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _flash_diffable(sliding_window, q, k_cache, v_cache, cache_len):
    from .flash_attention import flash_cached_attention

    return flash_cached_attention(
        q, k_cache, v_cache, cache_len,
        sliding_window=sliding_window,
        interpret=jax.default_backend() != "tpu",
    )


def _flash_diffable_fwd(sliding_window, q, k_cache, v_cache, cache_len):
    out = _flash_diffable(sliding_window, q, k_cache, v_cache, cache_len)
    return out, (q, k_cache, v_cache, cache_len)


def _flash_diffable_bwd(sliding_window, residuals, g):
    q, k_cache, v_cache, cache_len = residuals
    _, vjp = jax.vjp(
        lambda q_, k_, v_: _xla_cached_attention(q_, k_, v_, cache_len,
                                                 sliding_window),
        q, k_cache, v_cache,
    )
    dq, dk, dv = vjp(g)
    # cache_len is integral — its cotangent is the symbolic float0 zero.
    dlen = np.zeros(jnp.shape(cache_len), jax.dtypes.float0)
    return dq, dk, dv, dlen


_flash_diffable.defvjp(_flash_diffable_fwd, _flash_diffable_bwd)
