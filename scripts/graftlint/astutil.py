"""Shared AST plumbing for the graftlint analyzers.

Pure stdlib ``ast`` — analyzers must never import the package under
analysis (importing pulls in jax; the lint has to stay cheap enough for
tier-1 and robust against modules that only import on-TPU).
"""

from __future__ import annotations

import ast
import dataclasses
import pathlib
from typing import Dict, Iterator, List, Optional, Tuple


@dataclasses.dataclass
class Module:
    """One parsed source file."""

    path: pathlib.Path          # absolute
    rel: str                    # repo-relative, posix separators
    tree: ast.Module
    source: str


def parse_tree(root: pathlib.Path, repo: pathlib.Path) -> List[Module]:
    """Parse every ``*.py`` under `root` (skipping caches). A syntax error
    is reported as a crash, not swallowed — unparsable code means the lint
    is blind, which must fail loudly."""
    mods = []
    for path in sorted(root.rglob("*.py")):
        if "__pycache__" in path.parts:
            continue
        src = path.read_text(encoding="utf-8")
        tree = ast.parse(src, filename=str(path))
        mods.append(Module(path=path,
                           rel=path.relative_to(repo).as_posix(),
                           tree=tree, source=src))
    return mods


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for Name/Attribute chains; None for anything dynamic."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    if isinstance(node, ast.Call):
        # e.g. partial(jax.jit, ...)(f) — caller unwraps; no stable name.
        return None
    return None


def call_name(call: ast.Call) -> Optional[str]:
    return dotted_name(call.func)


def terminal_attr(call: ast.Call) -> Optional[str]:
    """The last attribute of a call target: ``x.y.item()`` -> ``item``."""
    if isinstance(call.func, ast.Attribute):
        return call.func.attr
    if isinstance(call.func, ast.Name):
        return call.func.id
    return None


def is_self_attr(node: ast.AST, names: Optional[set] = None) -> Optional[str]:
    """Return the attribute name when `node` is ``self.X`` (optionally only
    for X in `names`)."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        if names is None or node.attr in names:
            return node.attr
    return None


def str_const(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def walk_functions(tree: ast.Module
                   ) -> Iterator[Tuple[str, Optional[str], ast.AST]]:
    """Yield ``(qualname, class_name, funcdef)`` for every (async) function
    in the module, including nested ones (qualname uses dots)."""

    def rec(node: ast.AST, stack: List[str], cls: Optional[str]):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qn = ".".join(stack + [child.name])
                yield qn, cls, child
                yield from rec(child, stack + [child.name], cls)
            elif isinstance(child, ast.ClassDef):
                yield from rec(child, stack + [child.name], child.name)
            else:
                yield from rec(child, stack, cls)

    yield from rec(tree, [], None)


def scope_walk(node: ast.AST) -> Iterator[ast.AST]:
    """Walk a function body WITHOUT descending into nested (async) function
    defs — nested defs are separate call-graph nodes and get their own
    walk. Lambdas ARE descended into: they share the enclosing scope."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        child = stack.pop()
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        yield child
        stack.extend(ast.iter_child_nodes(child))


def enclosing_map(func: ast.AST) -> Dict[ast.AST, ast.AST]:
    """child -> parent map for ancestor walks within one function body."""
    parents: Dict[ast.AST, ast.AST] = {}
    for node in ast.walk(func):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    return parents


# ---------------------------------------------------------------------------
# Name-based call graph with unique-target discipline
# ---------------------------------------------------------------------------
#
# Shared by the lock analyzer (only-called-from-locked fixpoint), the
# failures analyzer (recovery-root reachability), and the spmd analyzer
# (shard_map axis-scope reachability). One resolution policy, so the
# families' reachability semantics cannot drift apart:
#
#   * ``self.m()`` resolves within the receiver's class;
#   * bare names resolve to module-level defs, same module first;
#   * a generic ``obj.m()`` resolves only when exactly ONE class
#     package-wide defines ``m`` — common method names would otherwise
#     weave phantom edges through every registry.

class CallGraph:
    """Function index + name-resolved call edges over a module set.

    Nodes are ``(rel, qualname)`` keys; ``funcs`` maps each to its
    ``(funcdef, class_name)``. ``edges`` resolves one function's outgoing
    calls; ``reachable`` runs BFS from a root set.
    """

    def __init__(self, mods: List[Module]):
        # (rel, qual) -> (funcdef, class_name)
        self.funcs: Dict[Tuple[str, str],
                         Tuple[ast.AST, Optional[str]]] = {}
        # bare function name -> [(rel, qual)] (module-level defs only)
        self.module_level: Dict[str, List[Tuple[str, str]]] = {}
        # method name -> [(rel, qual, class)]
        self.methods: Dict[str, List[Tuple[str, str, str]]] = {}
        for mod in mods:
            for qual, cls, fn in walk_functions(mod.tree):
                self.funcs[(mod.rel, qual)] = (fn, cls)
                name = qual.split(".")[-1]
                if cls is None and "." not in qual:
                    self.module_level.setdefault(name, []).append(
                        (mod.rel, qual))
                elif cls is not None and qual == f"{cls}.{name}":
                    self.methods.setdefault(name, []).append(
                        (mod.rel, qual, cls))

    def _resolve_bare(self, rel: str, name: str,
                      qual: Optional[str]) -> Optional[Tuple[str, str]]:
        """A bare-name reference: same-module module-level def first, then
        the caller's own nested def (lexical child — the `tick` loop-body
        idiom where several factories each nest one), then any unique
        same-module nested def, then a unique global module-level def."""
        cands = [c for c in self.module_level.get(name, ())
                 if c[0] == rel]
        if not cands and qual is not None:
            child = (rel, f"{qual}.{name}")
            if child in self.funcs:
                return child
        if not cands:
            cands = [k for k in self.funcs
                     if k[0] == rel and "." in k[1]
                     and k[1].split(".")[-1] == name]
        cands = cands or self.module_level.get(name, [])
        return cands[0] if len(cands) == 1 else None

    def edges(self, rel: str, fn: ast.AST, cls: Optional[str],
              qual: Optional[str] = None) -> List[Tuple[str, str]]:
        out: List[Tuple[str, str]] = []
        for node in ast.walk(fn):
            # Bare-name LOADS, not just calls: a function passed by
            # reference (`lax.fori_loop(0, n, tick, c)`, a callback wired
            # into a constructor) is reachable the moment the reference
            # escapes — the unique-target discipline keeps this precise.
            if isinstance(node, ast.Name) and isinstance(node.ctx,
                                                         ast.Load):
                hit = self._resolve_bare(rel, node.id, qual)
                if hit is not None:
                    out.append(hit)
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute):
                f = node.func
                owners = self.methods.get(f.attr, [])
                if (isinstance(f.value, ast.Name) and f.value.id == "self"
                        and cls is not None):
                    same = [o[:2] for o in owners if o[2] == cls]
                    if len(same) == 1:
                        out.append(same[0])
                    continue
                # Generic receiver: resolve only on a unique target.
                if len(owners) == 1:
                    out.append(owners[0][:2])
        return out

    def reachable(self, roots) -> set:
        """BFS closure of ``roots`` (an iterable of (rel, qual) keys)."""
        queue = [k for k in roots if k in self.funcs]
        seen = set(queue)
        while queue:
            rel, qual = queue.pop()
            fn, cls = self.funcs[(rel, qual)]
            for nxt in self.edges(rel, fn, cls, qual):
                if nxt not in seen and nxt in self.funcs:
                    seen.add(nxt)
                    queue.append(nxt)
        return seen


def only_called_from_fixpoint(members, seeds, calls, skip=frozenset()):
    """Grow ``seeds`` over a (caller, callee, flagged) call-site list until
    fixpoint: a member joins when it HAS call sites and every one of them
    is flagged — either the site itself (``flagged``) or its caller is
    already in the set. The lock analyzer's only-called-from-locked-context
    closure, shared so other families can reuse the discipline."""
    grown = set(seeds)
    changed = True
    while changed:
        changed = False
        sites: Dict[str, List[bool]] = {}
        for caller, callee, flagged in calls:
            sites.setdefault(callee, []).append(flagged or caller in grown)
        for m in members:
            if m in grown or m in skip:
                continue
            if sites.get(m) and all(sites[m]):
                grown.add(m)
                changed = True
    return grown


def import_aliases(tree: ast.Module) -> Dict[str, str]:
    """local alias -> imported dotted source for ``import a.b as c`` and
    ``from .mod import name`` (relative imports keep just the tail module
    name — good enough for the name-based resolution the analyzers do)."""
    out: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                out[a.asname or a.name.split(".")[0]] = a.name
        elif isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            for a in node.names:
                out[a.asname or a.name] = (mod + "." if mod else "") + a.name
    return out
