"""Determinism taint checking of the token path (graftlint phase 2,
family 2).

Every chaos/relay/overload soak demands TOKEN-IDENTICAL output across a
clean run and a faulted run. That invariant dies the moment wall-clock,
process-unique, or iteration-order nondeterminism leaks into anything
that picks a token: sampling parameters, PRNGKey seed derivation, journal
and digest inputs, decode-path wire frame fields.

Rules:

- ``det-unseeded-rng`` — ``random.Random()`` / ``np.random.default_rng()``
  constructed with no seed. Every RNG in this codebase is injectable and
  seeded (the soaks depend on it); an unseeded fallback is a latent
  nondeterminism bomb that only fires when a caller forgets to inject.
- ``det-taint`` — intraprocedural forward taint from nondeterminism
  sources (``time.time``/``monotonic``/``perf_counter`` families,
  ``os.urandom``, ``uuid``, module-level ``random.*`` draws, builtin
  ``hash()``, iteration over a ``set``) into token-affecting sinks:
  ``seed=``/``step_seed=``/``session_id=`` keyword arguments, the seed
  argument of ``PRNGKey``/``fold_in``, hashlib digest construction and
  ``.update()`` on a digest object, ``SamplingParams(...)`` and journal
  entry arguments, and the return value of a function whose name says it
  produces a seed/session/digest.
- ``det-key-reuse`` — PRNGKey discipline: a key consumed by two
  ``jax.random.*`` draws without an intervening ``split``/``fold_in``
  rebinding, or a draw inside a loop/comprehension from a key that the
  loop never rebinds. The sanctioned idioms — ``PRNGKey(seed + i)``
  bursts and ``fold_in(base, i)`` — construct the key inline or derive
  per-index and never trip this.

Deliberately out of scope (documented in docs/STATIC_ANALYSIS.md): taint
across function boundaries (a tainted value passed as an argument is the
callee's parameter, judged clean there), dict iteration (insertion-
ordered since 3.7), and keys smuggled through containers or non-jax
helper calls. The analyzer is lexical per function — cheap and quiet, in
exchange for catching only same-function flows; the fixture proves each
rule fires and the soaks still backstop the rest dynamically.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set

from . import astutil
from .core import Context, Finding

CLOCK_CALLS = {
    "time.time", "time.time_ns", "time.monotonic", "time.monotonic_ns",
    "time.perf_counter", "time.perf_counter_ns",
}
UUID_CALLS = {"uuid.uuid1", "uuid.uuid4", "os.urandom"}
# Module-level draws from the GLOBAL (unseeded, process-shared) random
# module. Instance draws (self._rng.choice) are fine: instances are
# seeded/injected, which det-unseeded-rng separately enforces.
GLOBAL_RANDOM = re.compile(
    r"^(random|np\.random|numpy\.random)\."
    r"(random|randint|randrange|getrandbits|choice|choices|shuffle|sample|"
    r"uniform|gauss|normal|permutation|rand|randn)$")

SEED_KWARGS = {"seed", "step_seed", "seed_base", "seeds", "session_id"}
SEEDY_NAME = re.compile(r"seed|session_id|digest")
HASHLIB_CTORS = {"blake2b", "blake2s", "sha256", "sha1", "md5"}
JOURNAL_SINKS = {"journal_append", "_journal_append", "JournalEntry",
                 "SamplingParams"}

KEY_PARAM = re.compile(r"^(rng|key|prng(_key)?|.*_key|key_.*)$")
KEY_MAKERS = {"PRNGKey", "split", "fold_in"}
KEY_CONSUMERS = {
    "categorical", "uniform", "normal", "bernoulli", "gumbel", "randint",
    "truncated_normal", "permutation", "choice", "exponential", "laplace",
    "bits", "beta", "gamma", "dirichlet", "poisson", "ball", "cauchy",
    "exponential", "loggamma", "multivariate_normal", "rademacher",
}


def _source_label(call: ast.Call) -> Optional[str]:
    dn = astutil.dotted_name(call.func)
    if dn is None:
        return None
    if dn in CLOCK_CALLS:
        return "clock"
    if dn in UUID_CALLS:
        return "uuid" if "uuid" in dn else "urandom"
    if GLOBAL_RANDOM.match(dn):
        return "global-random"
    if dn == "hash" and call.args:
        return "hash"
    return None


def _is_set_expr(node: ast.AST) -> bool:
    if isinstance(node, ast.Set):
        return True
    return (isinstance(node, ast.Call)
            and astutil.dotted_name(node.func) == "set")


class _FuncScan:
    """One function's taint + key-discipline pass, in statement order.

    Loop bodies are processed twice so loop-carried taint and the
    "key consumed every iteration but never rebound" hazard both
    surface on the second pass."""

    def __init__(self, mod: astutil.Module, qual: str, fn: ast.AST,
                 findings: List[Finding]):
        self.mod = mod
        self.qual = qual
        self.fn = fn
        self.findings = findings
        self.taint: Dict[str, str] = {}       # name -> source label
        self.digest_vars: Set[str] = set()    # names bound to hashlib objs
        self.key_fresh: Dict[str, bool] = {}  # key name -> unconsumed?
        self.reported: Set[str] = set()
        self.seedy_return = bool(
            SEEDY_NAME.search(qual.split(".")[-1].lower()))
        for a in getattr(fn, "args", None) and (
                fn.args.posonlyargs + fn.args.args + fn.args.kwonlyargs
        ) or ():
            if KEY_PARAM.match(a.arg) and a.arg != "self":
                self.key_fresh[a.arg] = True

    # -- reporting -----------------------------------------------------

    def _emit(self, rule: str, line: int, anchor: str, msg: str) -> None:
        if anchor in self.reported:
            return
        self.reported.add(anchor)
        self.findings.append(Finding(
            rule=rule, path=self.mod.rel, line=line, anchor=anchor,
            message=msg))

    # -- expression taint ----------------------------------------------

    def expr_taint(self, node: Optional[ast.AST]) -> Optional[str]:
        if node is None:
            return None
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                label = _source_label(sub)
                if label is not None:
                    return label
            elif isinstance(sub, ast.Name) and sub.id in self.taint:
                return self.taint[sub.id]
        return None

    # -- sinks ---------------------------------------------------------

    def _check_call_sinks(self, call: ast.Call) -> None:
        term = astutil.terminal_attr(call)
        dn = astutil.dotted_name(call.func) or term or ""
        for kw in call.keywords:
            if kw.arg in SEED_KWARGS:
                label = self.expr_taint(kw.value)
                if label is not None:
                    self._emit(
                        "det-taint", call.lineno,
                        f"{self.qual}:{kw.arg}",
                        f"{self.qual}: {label}-tainted value reaches "
                        f"token-affecting sink {kw.arg}= — soak reruns "
                        "would diverge")
        if term == "PRNGKey" and call.args:
            label = self.expr_taint(call.args[0])
            if label is not None:
                self._emit("det-taint", call.lineno,
                           f"{self.qual}:PRNGKey",
                           f"{self.qual}: {label}-tainted seed feeds "
                           "PRNGKey — the token stream becomes "
                           "run-unique")
        if term == "fold_in" and len(call.args) > 1:
            label = self.expr_taint(call.args[1])
            if label is not None:
                self._emit("det-taint", call.lineno,
                           f"{self.qual}:fold_in",
                           f"{self.qual}: {label}-tainted data folded "
                           "into a PRNG key")
        if term in HASHLIB_CTORS or term in JOURNAL_SINKS:
            for arg in list(call.args) + [k.value for k in call.keywords]:
                label = self.expr_taint(arg)
                if label is not None:
                    self._emit("det-taint", call.lineno,
                               f"{self.qual}:{term}",
                               f"{self.qual}: {label}-tainted value enters "
                               f"{term} — journal/digest inputs must be "
                               "replay-stable")
                    break
        if (term == "update" and isinstance(call.func, ast.Attribute)
                and isinstance(call.func.value, ast.Name)
                and call.func.value.id in self.digest_vars):
            for arg in call.args:
                label = self.expr_taint(arg)
                if label is not None:
                    self._emit("det-taint", call.lineno,
                               f"{self.qual}:{call.func.value.id}.update",
                               f"{self.qual}: {label}-tainted bytes enter "
                               "a digest — replay verification would "
                               "mismatch")
                    break
        # Unseeded RNG constructions (a rule of their own).
        if dn in ("random.Random",) and not call.args and not call.keywords:
            self._emit("det-unseeded-rng", call.lineno,
                       f"{self.qual}:random.Random",
                       f"{self.qual}: random.Random() with no seed — "
                       "inject or default a seeded RNG (the soaks pin "
                       "token-identical reruns)")
        if (dn in ("np.random.default_rng", "numpy.random.default_rng")
                and not call.args and not call.keywords):
            self._emit("det-unseeded-rng", call.lineno,
                       f"{self.qual}:default_rng",
                       f"{self.qual}: default_rng() with no seed — "
                       "inject or default a seeded generator")

    def _check_key_consumer(self, call: ast.Call, in_loop: bool) -> None:
        dn = astutil.dotted_name(call.func) or ""
        if not dn.startswith("jax.random."):
            return
        term = dn.rsplit(".", 1)[-1]
        if term not in KEY_CONSUMERS or not call.args:
            return
        arg0 = call.args[0]
        if not isinstance(arg0, ast.Name):
            return  # inline PRNGKey(seed + i) — the sanctioned burst idiom
        name = arg0.id
        if name not in self.key_fresh:
            return
        if not self.key_fresh[name]:
            self._emit("det-key-reuse", call.lineno,
                       f"{self.qual}:{name}",
                       f"{self.qual}: key {name!r} consumed by "
                       f"jax.random.{term} twice with no intervening "
                       "split/fold_in — identical draws, correlated "
                       "samples")
        self.key_fresh[name] = False
        del in_loop

    # -- statement walk ------------------------------------------------

    def _scan_calls(self, stmt: ast.AST, in_loop: bool) -> None:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Call):
                self._check_call_sinks(node)
                self._check_key_consumer(node, in_loop)
            elif isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                                   ast.GeneratorExp)):
                # A comprehension is a loop: a named key consumed inside it
                # is consumed once per element.
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Call):
                        dn = astutil.dotted_name(sub.func) or ""
                        term = dn.rsplit(".", 1)[-1]
                        if (dn.startswith("jax.random.")
                                and term in KEY_CONSUMERS and sub.args
                                and isinstance(sub.args[0], ast.Name)
                                and sub.args[0].id in self.key_fresh):
                            self._emit(
                                "det-key-reuse", sub.lineno,
                                f"{self.qual}:{sub.args[0].id}",
                                f"{self.qual}: key "
                                f"{sub.args[0].id!r} consumed inside a "
                                "comprehension without per-element "
                                "split/fold_in")

    def _assign_names(self, target: ast.AST) -> List[str]:
        if isinstance(target, ast.Name):
            return [target.id]
        if isinstance(target, (ast.Tuple, ast.List)):
            out = []
            for elt in target.elts:
                out.extend(self._assign_names(elt))
            return out
        return []

    def _handle_assign(self, names: List[str], value: ast.AST) -> None:
        label = self.expr_taint(value)
        is_key_src = (isinstance(value, ast.Call)
                      and astutil.terminal_attr(value) in KEY_MAKERS)
        is_digest = (isinstance(value, ast.Call)
                     and astutil.terminal_attr(value) in HASHLIB_CTORS)
        for n in names:
            if label is not None:
                self.taint[n] = label
            else:
                self.taint.pop(n, None)
            if is_key_src:
                self.key_fresh[n] = True
            else:
                self.key_fresh.pop(n, None)
            if is_digest:
                self.digest_vars.add(n)
            else:
                self.digest_vars.discard(n)

    def run_block(self, body, in_loop: bool = False) -> None:
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue  # nested defs get their own scan
            if isinstance(stmt, ast.Assign):
                self._scan_calls(stmt.value, in_loop)
                names = []
                for t in stmt.targets:
                    names.extend(self._assign_names(t))
                self._handle_assign(names, stmt.value)
            elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                self._scan_calls(stmt.value, in_loop)
                self._handle_assign(self._assign_names(stmt.target),
                                    stmt.value)
            elif isinstance(stmt, ast.AugAssign):
                self._scan_calls(stmt.value, in_loop)
                label = self.expr_taint(stmt.value)
                for n in self._assign_names(stmt.target):
                    if label is not None:
                        self.taint[n] = label
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                self._scan_calls(stmt.iter, in_loop)
                label = self.expr_taint(stmt.iter)
                if _is_set_expr(stmt.iter):
                    label = label or "set-iteration"
                for n in self._assign_names(stmt.target):
                    if label is not None:
                        self.taint[n] = label
                    else:
                        self.taint.pop(n, None)
                # Twice: loop-carried taint + unrebound-key detection.
                self.run_block(stmt.body, in_loop=True)
                self.run_block(stmt.body, in_loop=True)
                self.run_block(stmt.orelse, in_loop)
            elif isinstance(stmt, ast.While):
                self._scan_calls(stmt.test, True)
                self.run_block(stmt.body, in_loop=True)
                self.run_block(stmt.body, in_loop=True)
                self.run_block(stmt.orelse, in_loop)
            elif isinstance(stmt, ast.If):
                self._scan_calls(stmt.test, in_loop)
                self.run_block(stmt.body, in_loop)
                self.run_block(stmt.orelse, in_loop)
            elif isinstance(stmt, ast.Try):
                self.run_block(stmt.body, in_loop)
                for h in stmt.handlers:
                    self.run_block(h.body, in_loop)
                self.run_block(stmt.orelse, in_loop)
                self.run_block(stmt.finalbody, in_loop)
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                for item in stmt.items:
                    self._scan_calls(item.context_expr, in_loop)
                self.run_block(stmt.body, in_loop)
            elif isinstance(stmt, ast.Return):
                if stmt.value is not None:
                    self._scan_calls(stmt.value, in_loop)
                    if self.seedy_return:
                        label = self.expr_taint(stmt.value)
                        if label is not None:
                            self._emit(
                                "det-taint", stmt.lineno,
                                f"{self.qual}:return",
                                f"{self.qual}: returns a {label}-tainted "
                                "value from a seed/session/digest "
                                "factory — every caller inherits the "
                                "nondeterminism")
            else:
                self._scan_calls(stmt, in_loop)


def analyze(ctx: Context) -> List[Finding]:
    findings: List[Finding] = []
    for mod in ctx.modules:
        for qual, _cls, fn in astutil.walk_functions(mod.tree):
            scan = _FuncScan(mod, qual, fn, findings)
            scan.run_block(fn.body)
        # Module top level (constants computed at import): unseeded RNGs
        # and clock-derived module state are findings there too.
        top = _FuncScan(mod, "<module>", ast.parse(""), findings)
        top.run_block([s for s in mod.tree.body
                       if not isinstance(s, (ast.FunctionDef,
                                             ast.AsyncFunctionDef,
                                             ast.ClassDef))])
    return findings
