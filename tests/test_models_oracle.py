"""Golden-oracle tests: our JAX models vs transformers (torch, random weights).

The reference's only correctness check was a manual single-GPU comparison
script (``scripts/single_gpu_check.py``); here the same idea is an automated
assertion: identical weights -> logits allclose and greedy tokens identical,
including incremental decode through the KV cache.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest
import torch

from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.models import (
    config_from_hf,
    convert_state_dict,
    full_forward,
    init_kv_cache,
)

def tiny_gpt2():
    torch.manual_seed(0)
    from transformers import GPT2Config, GPT2LMHeadModel

    hf_cfg = GPT2Config(
        vocab_size=257, n_embd=64, n_layer=4, n_head=4, n_positions=128,
    )
    return GPT2LMHeadModel(hf_cfg).eval()


def tiny_llama():
    torch.manual_seed(0)
    from transformers import LlamaConfig, LlamaForCausalLM

    hf_cfg = LlamaConfig(
        vocab_size=320, hidden_size=64, num_hidden_layers=4,
        num_attention_heads=4, num_key_value_heads=2, intermediate_size=128,
        max_position_embeddings=128, rope_theta=10000.0, tie_word_embeddings=False,
    )
    return LlamaForCausalLM(hf_cfg).eval()


def tiny_mistral():
    torch.manual_seed(0)
    from transformers import MistralConfig, MistralForCausalLM

    hf_cfg = MistralConfig(
        vocab_size=320, hidden_size=64, num_hidden_layers=3,
        num_attention_heads=4, num_key_value_heads=2, intermediate_size=128,
        max_position_embeddings=128, sliding_window=8,
    )
    return MistralForCausalLM(hf_cfg).eval()


def tiny_mixtral():
    torch.manual_seed(0)
    from transformers import MixtralConfig, MixtralForCausalLM

    hf_cfg = MixtralConfig(
        vocab_size=320, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, intermediate_size=96,
        max_position_embeddings=128, num_local_experts=4, num_experts_per_tok=2,
    )
    return MixtralForCausalLM(hf_cfg).eval()


def tiny_qwen2():
    torch.manual_seed(0)
    from transformers import Qwen2Config, Qwen2ForCausalLM

    hf_cfg = Qwen2Config(
        vocab_size=320, hidden_size=64, num_hidden_layers=3,
        num_attention_heads=4, num_key_value_heads=2, intermediate_size=128,
        max_position_embeddings=128, rope_theta=10000.0,
        tie_word_embeddings=False,
    )
    return Qwen2ForCausalLM(hf_cfg).eval()


def tiny_gemma():
    torch.manual_seed(0)
    from transformers import GemmaConfig, GemmaForCausalLM

    # head_dim=32 != hidden/heads=16 exercises the decoupled-head-dim path
    # (gemma-7b ships 3072/16 heads with head_dim 256).
    hf_cfg = GemmaConfig(
        vocab_size=320, hidden_size=64, num_hidden_layers=3,
        num_attention_heads=4, num_key_value_heads=2, intermediate_size=128,
        head_dim=32, max_position_embeddings=128, rope_theta=10000.0,
        hidden_activation="gelu_pytorch_tanh", tie_word_embeddings=True,
    )
    return GemmaForCausalLM(hf_cfg).eval()


def tiny_gemma2():
    torch.manual_seed(0)
    from transformers import Gemma2Config, Gemma2ForCausalLM

    # sliding_window=8 with a 16+-token prompt exercises the alternating
    # local/global layers; softcaps + query_pre_attn_scalar != head_dim
    # exercise the scoring path.
    hf_cfg = Gemma2Config(
        vocab_size=320, hidden_size=64, num_hidden_layers=4,
        num_attention_heads=4, num_key_value_heads=2, intermediate_size=128,
        head_dim=32, max_position_embeddings=128, rope_theta=10000.0,
        hidden_activation="gelu_pytorch_tanh", tie_word_embeddings=True,
        sliding_window=8, query_pre_attn_scalar=16.0,
        attn_logit_softcapping=50.0, final_logit_softcapping=30.0,
        attn_implementation="eager",  # softcapping needs the eager path
    )
    return Gemma2ForCausalLM(hf_cfg).eval()


FACTORIES = {
    "gpt2": tiny_gpt2,
    "llama": tiny_llama,
    "mistral": tiny_mistral,
    "mixtral": tiny_mixtral,
    "qwen2": tiny_qwen2,
    "gemma": tiny_gemma,
    "gemma2": tiny_gemma2,
}


@pytest.mark.parametrize("family", list(FACTORIES))
def test_prefill_logits_match_hf(family):
    hf_model = FACTORIES[family]()
    cfg = config_from_hf(hf_model.config)
    params = convert_state_dict(cfg, hf_model.state_dict())

    ids = np.array([[5, 9, 23, 7, 81, 2, 14, 3]], dtype=np.int32)
    with torch.no_grad():
        ref_logits = hf_model(torch.tensor(ids, dtype=torch.long)).logits.numpy()

    kc, vc = init_kv_cache(cfg, cfg.num_layers, batch=1, max_len=32)
    logits, _, _ = full_forward(
        cfg, params, jnp.asarray(ids), kc, vc, jnp.int32(0)
    )
    if family == "mixtral":
        # Random-weight routers produce near-tied top-k gaps (observed 5e-4);
        # fp noise then flips expert choice for a token, shifting its logits
        # by ~2e-2. Accept that while still requiring argmax agreement.
        atol, rtol = 5e-2, 5e-2
    else:
        atol, rtol = 8e-3, 1e-2
    np.testing.assert_allclose(np.asarray(logits), ref_logits, atol=atol, rtol=rtol)
    assert (np.asarray(logits).argmax(-1) == ref_logits.argmax(-1)).all()


@pytest.mark.parametrize("family",
                         ["gpt2", "llama", "qwen2", "gemma", "gemma2"])
def test_incremental_decode_matches_full_recompute(family):
    """Prefill + per-token decode through the KV cache must equal one full
    forward over the whole sequence (the cache is exact, not approximate)."""
    hf_model = FACTORIES[family]()
    cfg = config_from_hf(hf_model.config)
    params = convert_state_dict(cfg, hf_model.state_dict())

    full_ids = np.array([[5, 9, 23, 7, 81, 2, 14, 3, 19, 44]], dtype=np.int32)
    prompt_len = 6

    kc, vc = init_kv_cache(cfg, cfg.num_layers, batch=1, max_len=16)
    logits, kc, vc = full_forward(
        cfg, params, jnp.asarray(full_ids[:, :prompt_len]), kc, vc, jnp.int32(0)
    )
    step_logits = [np.asarray(logits[:, -1])]
    for t in range(prompt_len, full_ids.shape[1]):
        logits, kc, vc = full_forward(
            cfg, params, jnp.asarray(full_ids[:, t : t + 1]), kc, vc, jnp.int32(t)
        )
        step_logits.append(np.asarray(logits[:, -1]))

    kc2, vc2 = init_kv_cache(cfg, cfg.num_layers, batch=1, max_len=16)
    ref_logits, _, _ = full_forward(
        cfg, params, jnp.asarray(full_ids), kc2, vc2, jnp.int32(0)
    )
    for i, sl in enumerate(step_logits):
        pos = prompt_len - 1 + i
        np.testing.assert_allclose(
            sl, np.asarray(ref_logits[:, pos]), atol=5e-3, rtol=5e-3,
            err_msg=f"mismatch at position {pos}",
        )


@pytest.mark.parametrize("family", ["gpt2", "llama", "mistral", "qwen2"])
def test_greedy_generation_token_identical(family):
    """End-to-end greedy decode vs transformers .generate — token identical."""
    hf_model = FACTORIES[family]()
    cfg = config_from_hf(hf_model.config)
    params = convert_state_dict(cfg, hf_model.state_dict())

    prompt = np.array([[5, 9, 23, 7]], dtype=np.int32)
    n_new = 12
    with torch.no_grad():
        ref = hf_model.generate(
            torch.tensor(prompt, dtype=torch.long),
            max_new_tokens=n_new, do_sample=False, use_cache=True,
            pad_token_id=0,
        ).numpy()[0, prompt.shape[1]:]

    kc, vc = init_kv_cache(cfg, cfg.num_layers, batch=1, max_len=32)
    logits, kc, vc = full_forward(
        cfg, params, jnp.asarray(prompt), kc, vc, jnp.int32(0)
    )
    out = []
    cur = int(jnp.argmax(logits[0, -1]))
    out.append(cur)
    cache_len = prompt.shape[1]
    for _ in range(n_new - 1):
        logits, kc, vc = full_forward(
            cfg, params, jnp.asarray([[cur]], dtype=jnp.int32), kc, vc,
            jnp.int32(cache_len),
        )
        cur = int(jnp.argmax(logits[0, -1]))
        out.append(cur)
        cache_len += 1

    assert out == list(ref), f"ours={out} ref={list(ref)}"


def test_llama31_rope_scaling_matches_hf():
    """Llama-3.1-style rope_scaling (type "llama3"): logits must match the
    HF implementation of the frequency remap — the reference's LB test
    model is Llama-3.1-8B (BASELINE.md)."""
    torch.manual_seed(0)
    from transformers import LlamaConfig, LlamaForCausalLM

    hf_cfg = LlamaConfig(
        vocab_size=320, hidden_size=64, num_hidden_layers=3,
        num_attention_heads=4, num_key_value_heads=2, intermediate_size=128,
        max_position_embeddings=256, rope_theta=10000.0,
        tie_word_embeddings=False,
        rope_scaling={"rope_type": "llama3", "factor": 8.0,
                      "low_freq_factor": 1.0, "high_freq_factor": 4.0,
                      "original_max_position_embeddings": 32},
    )
    hf_model = LlamaForCausalLM(hf_cfg).eval()
    cfg = config_from_hf(hf_model.config)
    assert cfg.rope_scaling == (8.0, 1.0, 4.0, 32)
    params = convert_state_dict(cfg, hf_model.state_dict())

    # Long enough that scaled wavelengths actually matter (> orig_max/2).
    ids = np.arange(48, dtype=np.int32)[None, :] % 320
    with torch.no_grad():
        ref = hf_model(torch.tensor(ids, dtype=torch.long)).logits.numpy()
    kc, vc = init_kv_cache(cfg, cfg.num_layers, batch=1, max_len=64)
    logits, _, _ = full_forward(cfg, params, jnp.asarray(ids), kc, vc,
                                jnp.int32(0))
    np.testing.assert_allclose(np.asarray(logits), ref, atol=8e-3, rtol=1e-2)
    assert (np.asarray(logits).argmax(-1) == ref.argmax(-1)).all()


def test_unsupported_rope_scaling_rejected():
    import pytest as _pytest

    torch.manual_seed(0)
    from transformers import LlamaConfig

    hf_cfg = LlamaConfig(
        vocab_size=64, hidden_size=32, num_hidden_layers=1,
        num_attention_heads=2, num_key_value_heads=2, intermediate_size=64,
        rope_scaling={"rope_type": "linear", "factor": 2.0},
    )
    with _pytest.raises(ValueError, match="rope_scaling"):
        config_from_hf(hf_cfg)


def test_non_llama_rope_scaling_rejected():
    import pytest as _pytest

    torch.manual_seed(0)
    from transformers import Qwen2Config

    hf_cfg = Qwen2Config(
        vocab_size=64, hidden_size=32, num_hidden_layers=1,
        num_attention_heads=2, num_key_value_heads=2, intermediate_size=64,
        rope_scaling={"rope_type": "yarn", "factor": 4.0})
    with _pytest.raises(ValueError, match="rope_scaling"):
        config_from_hf(hf_cfg)


def test_fused_qkv_layers_bitwise_matches_canonical():
    """Engine-side fused-QKV layout (models/transformer.fuse_qkv_layers):
    one [D, (H+2Hkv)*Dh] projection must be BITWISE identical to the three
    canonical matmuls — fusing along the output axis never changes a
    column's K-reduction — so every engine-vs-oracle parity test stays
    exact with engines fused and oracles canonical."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.models import (
        full_forward,
        init_kv_cache,
        init_params,
        llama_config,
    )
    from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.models.transformer import (
        fuse_qkv_layers,
    )

    cfg = llama_config(vocab_size=211, hidden_size=64, num_layers=4,
                       num_heads=4, num_kv_heads=2, intermediate_size=128,
                       max_position_embeddings=64)
    params = init_params(jax.random.PRNGKey(0), cfg)
    fused = dict(params, layers=fuse_qkv_layers(params["layers"]))
    assert "wqkv" in fused["layers"]["attn"]
    assert "wq" not in fused["layers"]["attn"]
    # idempotent / guard behavior
    assert fuse_qkv_layers(fused["layers"]) is fused["layers"]

    ids = jnp.asarray(
        np.random.default_rng(0).integers(0, cfg.vocab_size, (2, 9)),
        jnp.int32)
    kc, vc = init_kv_cache(cfg, cfg.num_layers, 2, 32)
    ref, kr, vr = full_forward(cfg, params, ids, kc, vc, jnp.int32(0))
    kc2, vc2 = init_kv_cache(cfg, cfg.num_layers, 2, 32)
    got, kg, vg = full_forward(cfg, fused, ids, kc2, vc2, jnp.int32(0))
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(got))
    np.testing.assert_array_equal(np.asarray(kr), np.asarray(kg))


def test_fuse_gate_up_stacked_bitwise():
    """fuse_gate_up_layers must FIRE on vmap-stacked dense trees (wg 3-D
    [L, d, i] — the layout every engine passes; an ndim guard once made
    it a silent no-op) and produce bitwise-identical logits; MoE expert
    trees keep canonical."""
    import jax.numpy as jnp

    from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.models import (
        full_forward,
        init_kv_cache,
        init_params,
        llama_config,
        mixtral_config,
    )
    from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.models.transformer import (
        fuse_qkv_params,
    )

    cfg = llama_config(vocab_size=131, hidden_size=64, num_layers=3,
                       num_heads=4, num_kv_heads=2, intermediate_size=96,
                       max_position_embeddings=32)
    params = init_params(jax.random.PRNGKey(0), cfg)
    fused = fuse_qkv_params(params)
    assert "wgu" in fused["layers"]["mlp"], "gate+up fusion did not fire"
    assert fused["layers"]["mlp"]["wgu"].shape == (3, 64, 192)
    ids = jnp.asarray([[5, 9, 23, 7]], jnp.int32)
    kc, vc = init_kv_cache(cfg, cfg.num_layers, 1, 16)
    a, _, _ = full_forward(cfg, params, ids, kc, vc, jnp.int32(0))
    kc, vc = init_kv_cache(cfg, cfg.num_layers, 1, 16)
    b, _, _ = full_forward(cfg, fused, ids, kc, vc, jnp.int32(0))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    moe = mixtral_config(vocab_size=131, hidden_size=32, num_layers=2,
                         num_heads=4, num_kv_heads=2, intermediate_size=64,
                         num_experts=2, num_experts_per_tok=1,
                         max_position_embeddings=32)
    mp = fuse_qkv_params(init_params(jax.random.PRNGKey(1), moe))
    assert "wgu" not in mp["layers"]["mlp"]      # experts stay canonical
    assert "wg" in mp["layers"]["mlp"]
