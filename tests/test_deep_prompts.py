"""Inference-time deep prompt tuning (ptune serving).

The vendored reference injects learned per-block prompts into hidden states
during ``rpc_forward`` AND during every per-step inference call
(``petals/server/block_functions.py:57-65,171-226``,
``backend.py:226-233``). Parity contract here: the distributed pipeline
with ``deep_prompts`` must generate token-for-token what a MONOLITHIC
forward with the same prompts generates — across chained spans, chunked
prefill, failover replay, and the TCP wire.
"""

import random

import jax
import jax.numpy as jnp
import numpy as np

from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.models import (
    full_forward,
    init_kv_cache,
    init_params,
)
from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.models.partition import (
    ROLE_FULL,
    StagePlan,
    StageSpec,
    parse_splits,
    slice_stage_params,
)
from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.ops.sampling import (
    SamplingParams,
)
from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.runtime.client import (
    PipelineClient,
    make_server_record,
)
from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.runtime.executor import (
    StageExecutor,
)
from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.runtime.messages import (
    StageRequest,
)

from test_runtime_pipeline import build_cluster, tiny_cfg


def make_prompts(cfg, pre_seq, seed=3, scale=0.5):
    """[num_layers, pre_seq, D] learned-prompt stand-in. Scale matters: the
    injection must be large enough to CHANGE the generated tokens, or the
    parity assertions would pass vacuously."""
    return scale * jax.random.normal(
        jax.random.PRNGKey(seed),
        (cfg.num_layers, pre_seq, cfg.hidden_size), jnp.float32)


def oracle_with_prompts(cfg, params, prompt_ids, max_new_tokens, prompts,
                        max_len=256):
    """Greedy monolithic loop with per-layer prompts on EVERY forward."""
    kc, vc = init_kv_cache(cfg, cfg.num_layers, 1, max_len)
    ids = jnp.asarray(np.asarray(prompt_ids, np.int32)[None, :])
    logits, kc, vc = full_forward(cfg, params, ids, kc, vc, jnp.int32(0),
                                  prompts=prompts)
    generated = [int(jnp.argmax(logits[0, len(prompt_ids) - 1]))]
    cur_len = len(prompt_ids)
    for _ in range(1, max_new_tokens):
        if len(generated) >= 5 and len(set(generated[-5:])) == 1:
            break
        nxt = jnp.asarray([[generated[-1]]], jnp.int32)
        logits, kc, vc = full_forward(cfg, params, nxt, kc, vc,
                                      jnp.int32(cur_len), prompts=prompts)
        generated.append(int(jnp.argmax(logits[0, 0])))
        cur_len += 1
    return generated


def test_pipeline_deep_prompts_match_monolithic_oracle():
    """Chained spans + client-side slicing == monolithic injection. pre_seq
    EXCEEDS the prompt length, so the first decode steps fall inside the
    prompt region and exercise the per-step (not just prefill) injection."""
    cfg = tiny_cfg()
    client, _, _, params, _ = build_cluster(cfg, splits="2,4,6")
    sampling = SamplingParams(temperature=0.0)
    prompt = [5, 9, 23, 7]
    prompts = make_prompts(cfg, pre_seq=7)  # > len(prompt): decode injection

    res = client.generate(prompt, max_new_tokens=8, sampling=sampling,
                          deep_prompts=prompts)
    ref = oracle_with_prompts(cfg, params, prompt, 8, prompts)
    assert res.tokens == ref
    # Not vacuous: the prompts must actually steer generation.
    base = client.generate(prompt, max_new_tokens=8, sampling=sampling)
    assert base.tokens != ref
    # Session state cleaned up.
    assert not client._session_prompts


def test_deep_prompts_chunked_prefill_absolute_positions():
    """A prefill long enough to split into several chunks must inject at
    ABSOLUTE positions: chunk 2 (positions >= chunk_len) gets prompt rows
    [chunk_len:...], not a restarted slice. (Chunk-relative injection —
    what a naive port of petals' slicing would do — fails this test.)"""
    cfg = tiny_cfg()
    params = init_params(jax.random.PRNGKey(0), cfg)
    spec = StageSpec(index=0, role=ROLE_FULL, start=0, end=cfg.num_layers)
    # Chunk budget sized to force multi-chunk prefill: per-token footprint is
    # batch * hidden * 4 * layers = 64*4*8 = 2048 bytes; 32 KiB -> 16-token
    # chunks for a 40-token prompt (floored at 16, the smallest bucket).
    ex = StageExecutor(cfg, spec, params, peer_id="chunky",
                       max_chunk_bytes=32 * 1024)
    rng = np.random.default_rng(0)
    prompt = rng.integers(0, cfg.vocab_size, 40).astype(np.int32)
    pre = 24  # prompt region spans chunk 1 AND chunk 2
    prompts = make_prompts(cfg, pre_seq=pre)

    resp = ex.forward(StageRequest(
        session_id="s", hidden=jnp.asarray(prompt[None, :]),
        seq_len=len(prompt), cur_len=0, is_prefill=True, max_length=64,
        sampling=SamplingParams(temperature=0.0), prompts=prompts,
    ))

    kc, vc = init_kv_cache(cfg, cfg.num_layers, 1, 64)
    logits, _, _ = full_forward(cfg, params, jnp.asarray(prompt[None, :]),
                                kc, vc, jnp.int32(0), prompts=prompts)
    assert resp.token_id == int(jnp.argmax(logits[0, -1]))


def test_deep_prompts_survive_failover_replay():
    """A replacement peer must rebuild its KV with the SAME injection —
    journal replay ships the hop's prompt slice too."""
    cfg = tiny_cfg()
    client, transport, _, params, _ = build_cluster(cfg, splits="4",
                                                    replicas=2)
    sampling = SamplingParams(temperature=0.0)
    prompt = [11, 3, 77]
    prompts = make_prompts(cfg, pre_seq=6)
    ref = oracle_with_prompts(cfg, params, prompt, 8, prompts)

    killed = {"done": False}
    orig_call = transport.call

    def flaky_call(peer_id, req, timeout=None):
        if not killed["done"] and not req.is_prefill and req.cur_len >= 5:
            killed["done"] = True
            transport.kill(peer_id)
        return orig_call(peer_id, req, timeout=timeout)

    transport.call = flaky_call
    res = client.generate(prompt, max_new_tokens=8, sampling=sampling,
                          deep_prompts=prompts)
    assert killed["done"], "fault was never injected"
    assert client.recoveries >= 1
    assert res.tokens == ref


def test_deep_prompts_over_tcp_round_trip():
    """Prompts ride the wire as a second payload tensor (classic frame) and
    the TCP pipeline matches the monolithic oracle."""
    from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.runtime.net import (
        RegistryServer,
        RemoteRegistry,
        TcpStageServer,
        TcpTransport,
    )

    cfg = tiny_cfg()
    params = init_params(jax.random.PRNGKey(0), cfg)
    plan = StagePlan.from_splits(cfg.num_layers, parse_splits("3,6"))
    reg_server = RegistryServer()
    reg_server.start()
    servers = []
    try:
        for spec in plan.stages[1:]:
            peer = f"dp-s{spec.index}"
            ex = StageExecutor(cfg, spec,
                               slice_stage_params(cfg, params, spec),
                               peer_id=peer)
            srv = TcpStageServer(ex, wire_dtype="f32")
            srv.start()
            rec = make_server_record(peer, spec)
            rec.address = srv.address
            reg_server.registry.register(rec)
            servers.append(srv)
        registry = RemoteRegistry(reg_server.address)
        transport = TcpTransport(registry, wire_dtype="f32")
        stage0 = StageExecutor(cfg, plan.stages[0],
                               slice_stage_params(cfg, params, plan.stages[0]),
                               peer_id="client-local")
        client = PipelineClient(cfg, plan, stage0, transport, registry,
                                settle_seconds=0.0)
        prompt = [5, 9, 23]
        prompts = make_prompts(cfg, pre_seq=5)
        res = client.generate(prompt, max_new_tokens=6,
                              sampling=SamplingParams(temperature=0.0),
                              deep_prompts=prompts)
        ref = oracle_with_prompts(cfg, params, prompt, 6, prompts)
        assert res.tokens == ref
        # Steps past the prompt region drop the tensor and ride the
        # persistent-stream fast path again (steady-state decode must not
        # pay the classic frame re-shipping [span, pre, D] per hop).
        assert sum(s.stream_steps for s in servers) > 0
        transport.close()
    finally:
        for s in servers:
            s.stop()
        reg_server.stop()


def _span_executor_parity(ex, cfg, params, spec):
    """Run prefill + 3 decode steps with prompts on `ex` (covering span
    [spec.start, spec.end)) and assert every hidden matches the prompt-
    injected monolithic stack for those layers."""
    from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.models.partition import (
        init_stage_kv,
        stage_forward,
    )

    pre = 6
    prompts = make_prompts(cfg, pre_seq=pre)[spec.start:spec.end]
    rng = np.random.default_rng(1)
    x0 = rng.standard_normal((1, 4, cfg.hidden_size)).astype(np.float32)
    kc, vc = init_stage_kv(cfg, spec, 1, 64)
    cur = 0
    full = slice_stage_params(cfg, params, spec)
    for step in range(4):
        t = 4 if step == 0 else 1
        x = (x0 if step == 0
             else rng.standard_normal((1, 1, cfg.hidden_size)).astype(
                 np.float32))
        resp = ex.forward(StageRequest(
            session_id="s", hidden=jnp.asarray(x), seq_len=t, cur_len=cur,
            is_prefill=(step == 0), max_length=32, prompts=prompts,
        ))
        want, kc, vc = stage_forward(cfg, spec, full, jnp.asarray(x), kc, vc,
                                     jnp.int32(cur), prompts=prompts)
        np.testing.assert_allclose(np.asarray(resp.hidden),
                                   np.asarray(want), atol=2e-4, rtol=2e-4)
        cur += t


def test_deep_prompts_on_tp_engine():
    """TP executors must inject identically (prompts replicated across the
    tp mesh; the router may legally place deep-prompt sessions here)."""
    from jax.sharding import Mesh

    cfg = tiny_cfg()
    params = init_params(jax.random.PRNGKey(0), cfg)
    spec = StageSpec(index=1, role="segment", start=2, end=6)
    mesh = Mesh(np.asarray(jax.devices()[:2]), ("tp",))
    ex = StageExecutor(cfg, spec, slice_stage_params(cfg, params, spec),
                       peer_id="tp", tp_mesh=mesh)
    _span_executor_parity(ex, cfg, params, spec)


def test_deep_prompts_on_offload_engine():
    """Host-offloaded spans inject per streamed layer."""
    cfg = tiny_cfg()
    params = init_params(jax.random.PRNGKey(0), cfg)
    spec = StageSpec(index=1, role="segment", start=2, end=6)
    ex = StageExecutor(cfg, spec, slice_stage_params(cfg, params, spec),
                       peer_id="off", offload=True, keep_layers_resident=1)
    _span_executor_parity(ex, cfg, params, spec)


def test_batched_and_sp_engines_refuse_prompts():
    """Single-session engines must reject deep prompts loudly (silently
    ignoring them would generate un-tuned tokens that LOOK valid)."""
    import pytest

    from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.runtime.batching import (
        BatchedStageExecutor,
        BatchingStageAdapter,
    )
    from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.runtime.executor import (
        StageExecutionError,
    )

    cfg = tiny_cfg()
    params = init_params(jax.random.PRNGKey(0), cfg)
    spec = StageSpec(index=0, role=ROLE_FULL, start=0, end=cfg.num_layers)
    inner = BatchedStageExecutor(cfg, spec, params, slots=2, max_len=64)
    ad = BatchingStageAdapter(inner, window_s=0.0)
    req = StageRequest(
        session_id="s", hidden=jnp.asarray([[1, 2, 3]], jnp.int32),
        seq_len=3, cur_len=0, is_prefill=True, max_length=32,
        prompts=make_prompts(cfg, pre_seq=4),
    )
    with pytest.raises(StageExecutionError, match="deep-prompt"):
        ad.forward(req)
