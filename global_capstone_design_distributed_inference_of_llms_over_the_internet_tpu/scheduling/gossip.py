"""Gossip-replicated placement registry: epidemic anti-entropy over the
stage servers themselves, so the dedicated ``--mode registry`` processes
degrade from a hard dependency into a mere bootstrap seed.

The reference's control plane is a Kademlia DHT with no distinguished node
(``src/dht_utils.py:34-242``): any peer can bootstrap any other, and killing
every "well-known" node leaves the swarm discoverable through whoever is
still up. Our registry service replaced that DHT with primary+standby
processes — a coordinated failure domain the reference does not have
(VERDICT rec #5). This module restores the DHT's survivability WITHOUT
building a DHT, in the style of Demers et al., *Epidemic Algorithms for
Replicated Database Maintenance*: every serve process embeds a
`GossipNode` — a versioned mirror of the placement records — and
periodically runs a digest-then-delta anti-entropy exchange with a few
random live peers (piggybacked on its heartbeat cadence, over the same
framed TCP the data plane uses — `runtime.net.gossip_exchange`).

Versioning rules (the whole correctness story):

  * **Per-origin sequence numbers.** Each record is owned by exactly one
    origin peer, which stamps every refresh with a monotonically increasing
    ``seq``. Merge is newest-seq-wins per origin — order- and
    duplication-independent, so randomized delivery converges (the
    property test feeds the same churn in shuffled orders and asserts
    identical live sets).
  * **Relative-TTL encoding.** ``time.monotonic()`` values NEVER cross
    hosts (the registry's ``age_s`` precedent): a wire entry carries the
    seconds of liveness it has left, and the receiver re-anchors that
    against its own clock. Equal-seq merges keep the later local expiry,
    so a refresh seen twice via different paths never shortens a record's
    life.
  * **Grace-period tombstones.** ``unregister`` becomes a tombstone with
    the next seq, retained for ``tombstone_grace_s`` (default 2x TTL): an
    older live version still circulating cannot resurrect a deliberately
    removed record, while a genuine re-register (which takes a NEWER seq)
    beats the tombstone immediately. At equal seq the tombstone wins —
    deletion must dominate a concurrent refresh for the merge to be a
    semilattice join.

The mirror itself is a real `PlacementRegistry`, kept in lockstep with the
versioned entry table, so a stage server answers the registry service's
``register``/``heartbeat``/``list`` verbs (see `TcpStageServer`) with the
exact response shapes of `RegistryServer` — a client that lost every seed
can point `RemoteRegistry` at ANY live stage server and keep discovering.
"""

from __future__ import annotations

import dataclasses
import random
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

from ..telemetry import catalog as _tm
from ..telemetry import events as _ev
from .registry import (
    DEFAULT_TTL,
    PlacementRegistry,
    ServerRecord,
    dict_to_rec,
    rec_to_dict,
)

# How many random peers one anti-entropy tick exchanges with. Epidemic
# dissemination reaches the whole swarm in O(log N) rounds at any fanout
# >= 1; 2 keeps per-beat traffic trivial while halving the propagation
# constant vs. 1.
GOSSIP_FANOUT = 2


@dataclasses.dataclass
class _Entry:
    """One origin's latest known version (live record or tombstone)."""

    origin: str
    seq: int
    rec: Optional[dict]          # wire-form record; may be None on a tombstone
    dead: bool
    expires_at: float            # LOCAL monotonic deadline (ttl or grace)
    window: float                # full liveness window (ttl, or grace if dead)


class GossipNode:
    """Versioned, tombstoned mirror of the placement records. Thread-safe.

    Pure state machine: the wire work (framing, peer dialing, fault hooks)
    lives in ``runtime.net``; this class only versions, merges, and projects
    the entry table into its embedded `PlacementRegistry` mirror.
    """

    def __init__(self, peer_id: str, ttl: float = DEFAULT_TTL,
                 fanout: int = GOSSIP_FANOUT,
                 tombstone_grace_s: Optional[float] = None,
                 rng: Optional[random.Random] = None):
        self.peer_id = peer_id
        self.ttl = float(ttl)
        self.fanout = int(fanout)
        self.tombstone_grace_s = (2.0 * self.ttl if tombstone_grace_s is None
                                  else float(tombstone_grace_s))
        # This process's own data-plane address: excluded from peer
        # selection (gossiping with yourself is a no-op round). Stamped by
        # the serve wiring once the listener is bound.
        self.self_address: Optional[str] = None
        # Seeded default keeps peer-selection order reproducible when the
        # caller does not inject an RNG (soaks pin token-identical reruns).
        self._rng = rng or random.Random(0)
        self._lock = threading.Lock()
        self._entries: Dict[str, _Entry] = {}
        # The query mirror: discovery-shaped reads (list verb, peer
        # selection) go through a real PlacementRegistry so TTL purge and
        # freshness ordering behave exactly like the dedicated registry.
        self.registry = PlacementRegistry(ttl=self.ttl, rng=random.Random(0))

    # -- local write surface (origin authority / mirror proxy) --------------

    def publish(self, rec: dict) -> int:
        """Register or refresh a record with the NEXT per-origin seq. Used
        by a serve process for its own record each heartbeat, and by the
        mirror when a peer writes ``register`` to us while the seeds are
        down (we become the introducing authority for that version)."""
        origin = rec["peer_id"]
        now = time.monotonic()
        with self._lock:
            e = self._entries.get(origin)
            seq = (e.seq if e is not None else 0) + 1
            self._apply_locked(origin, seq, dict(rec), False,
                               self.ttl, self.ttl, now)
        return seq

    def apply_heartbeat(self, peer_id: str, throughput=None,
                        cache_tokens_left=None,
                        next_server_rtts=None) -> bool:
        """Mirror-side heartbeat: refresh a known live record under a new
        seq so the refresh propagates. Returns False for unknown (or
        tombstoned) peers — the caller's re-register repairs it, exactly
        the RegistryServer contract."""
        now = time.monotonic()
        with self._lock:
            e = self._entries.get(peer_id)
            if e is None or e.dead or e.expires_at <= now or e.rec is None:
                return False
            rec = dict(e.rec)
            if throughput is not None:
                rec["throughput"] = throughput
            if cache_tokens_left is not None:
                rec["cache_tokens_left"] = cache_tokens_left
            if next_server_rtts is not None:
                rec["next_server_rtts"] = dict(next_server_rtts)
            self._apply_locked(peer_id, e.seq + 1, rec, False,
                               self.ttl, self.ttl, now)
            return True

    def apply_unregister(self, peer_id: str) -> None:
        """Tombstone a record under the next seq; the tombstone circulates
        for the grace window so older live versions cannot resurrect it."""
        now = time.monotonic()
        with self._lock:
            e = self._entries.get(peer_id)
            seq = (e.seq if e is not None else 0) + 1
            self._apply_locked(peer_id, seq, e.rec if e is not None else None,
                               True, self.tombstone_grace_s,
                               self.tombstone_grace_s, now)
        _ev.emit("gossip_tombstone", peer=peer_id, seq=seq)

    # -- merge (the semilattice join) ---------------------------------------

    def _apply_locked(self, origin: str, seq: int, rec: Optional[dict],
                      dead: bool, ttl_left: float, window: float,
                      now: float) -> bool:
        """Apply one version; True if it changed the entry table. The order
        of application never matters: higher seq always wins, equal seq
        resolves tombstone-over-live then max-expiry — a deterministic join,
        which is what the convergence property test pins."""
        window = max(0.0, float(window))
        expires_at = now + max(0.0, min(float(ttl_left), window))
        e = self._entries.get(origin)
        if e is not None:
            if seq < e.seq:
                return False
            if seq == e.seq:
                if dead != e.dead:
                    if e.dead:          # tombstone wins the tie
                        return False
                elif expires_at > e.expires_at:
                    # Same version seen via a fresher path: extend liveness.
                    e.expires_at = expires_at
                    if not e.dead:
                        self._mirror_locked(e)
                    return False
                else:
                    return False
        self._entries[origin] = e = _Entry(origin, seq, rec, dead,
                                           expires_at, window)
        if dead:
            self.registry.unregister(origin)
        else:
            self._mirror_locked(e)
        return True

    def _mirror_locked(self, e: _Entry) -> None:
        """Project one live entry into the PlacementRegistry mirror with its
        true (relative) freshness restored — discovery's newest-first
        ordering and TTL purge then behave exactly like the seed registry."""
        rec = dict_to_rec(e.rec or {})
        self.registry.register(rec)
        rec.expires_at = e.expires_at
        rec.timestamp = e.expires_at - e.window

    def merge(self, entries: Sequence[dict]) -> int:
        """Apply a gossip delta; returns how many entries changed state."""
        now = time.monotonic()
        applied = 0
        with self._lock:
            for w in entries or ():
                origin = w.get("origin")
                if not origin:
                    continue
                dead = bool(w.get("dead"))
                window = float(w.get("window")
                               or (self.tombstone_grace_s if dead
                                   else self.ttl))
                applied += self._apply_locked(
                    origin, int(w.get("seq", 0)), w.get("rec"), dead,
                    float(w.get("ttl_s", window)), window, now)
        if applied:
            _tm.get("gossip_entries_merged_total").inc(applied)
        return applied

    # -- anti-entropy wire forms --------------------------------------------

    def digest(self) -> Dict[str, int]:
        """origin -> seq for every entry still circulating (tombstones
        included: a peer must learn the deletion, not just stop hearing
        refreshes)."""
        now = time.monotonic()
        with self._lock:
            self._gc_locked(now)
            return {o: e.seq for o, e in self._entries.items()}

    def delta_for(self, remote_digest: Dict[str, int]) -> List[dict]:
        """Entries the remote lacks (its digest shows no/older seq),
        relative-TTL encoded for transport."""
        remote_digest = remote_digest or {}
        now = time.monotonic()
        out = []
        with self._lock:
            self._gc_locked(now)
            for origin, e in self._entries.items():
                if int(remote_digest.get(origin, -1)) < e.seq:
                    out.append({"origin": origin, "seq": e.seq,
                                "dead": e.dead, "rec": e.rec,
                                "window": e.window,
                                "ttl_s": max(0.0, e.expires_at - now)})
        return out

    def _gc_locked(self, now: float) -> None:
        """Drop fully expired entries: a live record past its TTL (origin
        stopped heartbeating) and a tombstone past its grace. Keeping them
        longer would only re-announce dead state forever."""
        gone = [o for o, e in self._entries.items() if e.expires_at <= now]
        for o in gone:
            del self._entries[o]

    # -- queries -------------------------------------------------------------

    def live_servers(self, model: Optional[str] = None) -> List[ServerRecord]:
        return self.registry.live_servers(model=model)

    def live_count(self) -> int:
        now = time.monotonic()
        with self._lock:
            return sum(1 for e in self._entries.values()
                       if not e.dead and e.expires_at > now)

    def live_records(self) -> List[dict]:
        """Verbatim wire-form record dicts of every live entry. Unlike
        ``live_servers()`` (which projects through ServerRecord and drops
        unknown keys), this keeps extras like the piggybacked ``stats``
        digest — the swarm-top view reads those."""
        now = time.monotonic()
        with self._lock:
            return [dict(e.rec) for e in self._entries.values()
                    if not e.dead and e.rec is not None
                    and e.expires_at > now]

    def select_peers(self, extra: Sequence[str] = ()) -> List[str]:
        """Up to `fanout` random peer addresses to exchange with this tick:
        the mirror's live records plus any `extra` addresses the caller
        knows (e.g. the seed registry's view during bootstrap, before the
        mirror has heard of anyone)."""
        cands = set(a for a in extra if a)
        for r in self.live_servers():
            if r.address and r.peer_id != self.peer_id:
                cands.add(r.address)
        cands.discard(self.self_address)
        if not cands:
            return []
        pool = sorted(cands)
        if len(pool) <= self.fanout:
            return pool
        return self._rng.sample(pool, self.fanout)


class GossipLoop(threading.Thread):
    """Anti-entropy driver: every `interval_s` (default TTL/3 — the same
    cadence as registry heartbeats, per the tentpole's piggyback contract)
    republish this server's own record into its node and run one exchange
    with each of a few random peers. `exchange` is injected from
    ``runtime.net`` (keeps this package wire-free): callable
    ``(node, address) -> (sent, merged)`` raising OSError-family on failure.
    """

    def __init__(self, node: GossipNode,
                 exchange: Callable[[GossipNode, str], tuple],
                 record_fn: Optional[Callable[[], Optional[dict]]] = None,
                 extra_peers_fn: Optional[Callable[[], Sequence[str]]] = None,
                 interval_s: Optional[float] = None):
        super().__init__(daemon=True, name=f"gossip-{node.peer_id}")
        self.node = node
        self.exchange = exchange
        self.record_fn = record_fn
        self.extra_peers_fn = extra_peers_fn
        self.interval_s = (node.ttl / 3.0 if interval_s is None
                           else float(interval_s))
        self._stop = threading.Event()

    def tick(self) -> int:
        """One anti-entropy round; returns entries merged (all peers)."""
        if self.record_fn is not None:
            rec = self.record_fn()
            if rec is not None:
                self.node.publish(rec)
        extra: Sequence[str] = ()
        if self.extra_peers_fn is not None:
            try:
                extra = self.extra_peers_fn() or ()
            except Exception:       # seed registry down — gossip continues
                extra = ()
        merged_total = 0
        for addr in self.node.select_peers(extra):
            try:
                _sent, merged = self.exchange(self.node, addr)
                merged_total += merged
            except (ConnectionError, OSError, TimeoutError):
                # A dead/faulted peer costs this round nothing but the
                # failed dial; its record ages out of selection via TTL.
                continue
        _tm.get("gossip_mirror_records").set(self.node.live_count())
        return merged_total

    def run(self) -> None:
        # First round runs IMMEDIATELY: a just-started server must seed its
        # mirror (and its RemoteRegistry's peers cache, via extra_peers_fn's
        # list read) before the seeds can die, not one interval later.
        while True:
            try:
                self.tick()
            except Exception:
                # The loop must outlive any single bad round: gossip is the
                # survivability layer, it cannot itself be fragile.
                import logging
                logging.getLogger(__name__).exception("gossip tick failed")
            if self._stop.wait(self.interval_s):
                return

    def stop(self) -> None:
        self._stop.set()
