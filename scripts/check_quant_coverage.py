#!/usr/bin/env python
"""Thin shim over the graftlint driver (analyzer: ``quant_coverage``).

The check itself lives in scripts/graftlint/legacy.py — one driver, one
finding format, one baseline. This entry point survives so existing
tier-1 wrappers (tests/test_quant_coverage.py) keep working; it exits
non-zero when a quant format in models/quant.py::QUANT_BITS lacks a bench
row, a parity test, or an MoE-path parity test.
"""

import pathlib
import sys

REPO = pathlib.Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

from scripts.graftlint.__main__ import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main(["--analyzer", "quant_coverage"]))
