"""Fused multi-step greedy decode: the single-chip serving hot path.

The TPU-idiomatic analogue of the reference's CUDA-graph decode
(``petals/llama/cuda_graphs.py``): N decode steps run as ONE compiled XLA
program (``lax.scan`` over steps), so steady state pays zero per-step host
round trips — on a tunneled chip each dispatch costs ~100 ms, which
otherwise dwarfs the ~0.5-2 ms of real per-step compute.

Two measured structural choices (slope-timed on a v5e, gpt2-124M b8 and a
1.1B flagship — see bench.py):

  * **Caches as loop CARRY with per-layer in-place updates**, not as the
    layer scan's xs/ys. The xs/ys structure rewrites every layer's whole
    cache each step (5.6 ms/step at gpt2 b8 S=1024); carrying the stack
    and dynamic-indexing one layer at a time measured 3.7 ms — 1.5x. (An
    L-times-unrolled body over separate per-layer buffers measured another
    ~1.6x at long caches, but its giant HLO wedged the shared compile
    service; the scan body is traced once and compiles in seconds.)
  * **Head fused with argmax, transposed.** The tied/untied head matmul is
    emitted as ``[V, B]`` (weights-stationary orientation) and consumed
    directly by the argmax, in the weight dtype with an fp32 upcast for the
    reduction — measured ~1.5x over the fp32-matmul + row-major argmax
    pair at gpt2's vocab.

Donation stays ungated here (cf. utils.platform.engine_donation): both
fused engines are single-controller programs — the bench/oracle caller
owns every dispatch, so the CPU async-dispatch/free race the threaded
serving engines gate against has no second thread to race.

`make_fused_decode` is the greedy throughput engine (bench + oracle fast
path); `make_fused_sample_decode` folds the FULL reference sampler into
the scan for batch-1 sampled generation, bit-identical to the per-token
oracle loop. Distributed serving still samples per step on the final hop
(the sampler needs the request's live metadata there).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, List, Tuple

import jax
import jax.numpy as jnp

from ..models.config import ModelConfig
from ..models.transformer import _norm, embed_tokens, lm_head, stack_forward
from ..ops.sampling import RECENT_WINDOW, push_recent, sample_token

Params = Dict[str, Any]


def _decode_step(cfg: ModelConfig, params: Params, tok: jnp.ndarray,
                 kc: jnp.ndarray, vc: jnp.ndarray, cl: jnp.ndarray):
    """ONE decode step shared by the greedy and sampled fused engines:
    embed (+ learned positions), cache-carrying stack_forward (T == 1 fast
    path). tok: [B] int32 -> (h [B, T=1, D], kc, vc)."""
    batch = tok.shape[0]
    pos = cl + jnp.zeros((batch, 1), jnp.int32)
    # The SHARED embed (models.transformer.embed_tokens): a hand-rolled
    # wte gather here once dropped gemma's sqrt(hidden) embed scale.
    x = embed_tokens(cfg, params["embed"], tok[:, None], pos)
    return stack_forward(cfg, params["layers"], x, pos, kc, vc, cl)


def make_fused_decode(cfg: ModelConfig, max_steps: int, batch: int,
                      exact_head: bool = False):
    """Build a jitted fused decode program with a DYNAMIC step count.

    Returns ``fn(params, tok, kc, vc, start, n) -> (toks, kc, vc)``:
    ``tok``: [B] int32 last sampled token; ``kc``/``vc``: stacked caches
    [L, B, S, Hkv, Dh] (donated); ``start``: scalar int32 cache length;
    ``n``: scalar int32 number of steps (<= max_steps, traced — one compile
    serves every step count, which is what makes slope timing affordable).
    ``toks``: [max_steps, B]; rows >= n are zero.

    ``exact_head=True`` runs the head matmul in fp32 like ``lm_head`` does —
    bit-matching the per-token sampler's greedy argmax on reduced-precision
    checkpoints (near-tied logits can otherwise flip under the bf16 one-pass
    head). The oracle baseline uses it; the bench keeps the fast weight-dtype
    head (the measured ~1.5x).
    """
    L = cfg.num_layers

    def head_argmax(params, h):
        # h: [B, D] -> greedy token [B] via the transposed head matmul.
        if cfg.tie_word_embeddings:
            w = params["embed"]["wte"]                    # [V, D]
        else:
            w = params["lm_head"]["w"].T                  # [V, D] (folded)
        dt = jnp.float32 if exact_head else w.dtype
        logits_t = w.astype(dt) @ h.T.astype(dt)          # [V, B]
        return jnp.argmax(logits_t.astype(jnp.float32), axis=0).astype(
            jnp.int32)

    @partial(jax.jit, donate_argnums=(2, 3))
    def fn(params, tok, kc, vc, start, n):
        # The layer scan carries the stacked caches and updates each layer's
        # rows in place via dynamic indexing (measured 1.5x over the
        # stacked-xs/ys structure, whose ys outputs rewrite every cache row
        # every step; the layer body is traced ONCE, keeping the HLO small —
        # an L-times-unrolled body was another ~1.6x at long caches but
        # produced compile jobs that wedged the shared compiler service).
        toks0 = jnp.zeros((max_steps, batch), jnp.int32)

        def body(i, carry):
            tok, kc, vc, cl, toks = carry
            h, kc, vc = _decode_step(cfg, params, tok, kc, vc, cl)
            h = _norm(cfg, params["final_norm"], h)[:, 0]
            tok = head_argmax(params, h)
            toks = jax.lax.dynamic_update_index_in_dim(toks, tok, i, 0)
            return (tok, kc, vc, cl + 1, toks)

        tok, kc, vc, _, toks = jax.lax.fori_loop(
            0, n, body, (tok, kc, vc, start, toks0))
        return toks, kc, vc

    return fn


def make_fused_sample_decode(cfg: ModelConfig, max_steps: int):
    """Fused multi-step SAMPLED decode (batch 1): the full reference sampler
    — count-scaled repetition penalty over the recent-50 window, triple-
    repeat guard, temperature, top-k, top-p (ops.sampling) — folded into the
    step scan, with the window carried as a ring buffer.

    The per-step key is ``PRNGKey(seed0 + i)`` (PRNGKey is traceable), the
    EXACT schedule of the per-token oracle loop (main.run_oracle /
    tests' oracle_generate) — so output is bit-identical to per-token
    sampled decoding while running as ONE compiled program.

    Returns ``fn(params, tok, kc, vc, start, n, seed0, recent, nvalid,
    temperature, top_p, top_k, repetition_penalty) ->
    (toks, kc, vc, recent, nvalid)`` — recent/nvalid thread across chunked
    calls so stop-condition checks between chunks don't reset the window.
    """

    @partial(jax.jit, donate_argnums=(2, 3))
    def fn(params, tok, kc, vc, start, n, seed0, recent, nvalid,
           temperature, top_p, top_k, repetition_penalty):
        toks0 = jnp.zeros((max_steps,), jnp.int32)

        def body(i, carry):
            tok, kc, vc, cl, recent, nvalid, toks = carry
            h, kc, vc = _decode_step(cfg, params, tok[None], kc, vc, cl)
            logits = lm_head(cfg, params, h)[0, 0]  # applies final_norm
            tok = sample_token(
                jax.random.PRNGKey(seed0 + i), logits, recent, nvalid,
                temperature, top_p, top_k, repetition_penalty)
            recent, nvalid = push_recent(recent, nvalid, tok)
            toks = jax.lax.dynamic_update_index_in_dim(toks, tok, i, 0)
            return (tok, kc, vc, cl + 1, recent, nvalid, toks)

        tok, kc, vc, _, recent, nvalid, toks = jax.lax.fori_loop(
            0, n, body, (tok, kc, vc, start, recent, nvalid, toks0))
        return toks, kc, vc, recent, nvalid

    return fn
