"""Weighted fairness: deficit round-robin across tenants, EDF within.

DRR (Shreedhar & Varghese) is the right primitive here because the unit of
service is cheap and uniform — one queued request to start, or one decode
step to run — and we need O(1) scheduling decisions that converge to the
configured weight ratios over any window a few rotations long. Quanta are
normalized by the SMALLEST weight so every tenant earns at least one unit
of credit per rotation visit (no starvation even at extreme ratios), and
an idle tenant's deficit is zeroed — fairness is about contended moments,
not banked credit from quiet ones.

Within a tenant the order is earliest-deadline-first using the same
``deadline_budget_s`` machinery the rest of the stack enforces: among
requests a tenant is entitled to run, the one closest to its SLO goes
first; deadline-less requests sort last (infinity) in FIFO order.
"""

from __future__ import annotations

import heapq
import itertools
import math
import threading
from typing import Any, Dict, Iterable, Optional, Set, Tuple


class DeficitRoundRobin:
    """Serve-one-unit-per-call DRR over a fixed tenant set.

    ``pick(active)`` returns the tenant entitled to the next unit of
    service among ``active`` (tenants with work), or None when idle. The
    rotation pointer and deficits persist across calls, so consecutive
    picks realize the weight ratios; service within one tenant's quantum
    is consecutive (burst-per-visit, as in classic DRR)."""

    def __init__(self, weights: Dict[str, float]):
        if not weights:
            raise ValueError("DRR needs at least one tenant")
        if any(w <= 0 for w in weights.values()):
            raise ValueError("DRR weights must be > 0")
        self._order = sorted(weights)
        wmin = min(weights.values())
        self._quantum = {t: weights[t] / wmin for t in self._order}
        self._deficit = {t: 0.0 for t in self._order}
        self._idx = 0

    def pick(self, active: Set[str]) -> Optional[str]:
        active = {t for t in active if t in self._deficit}
        if not active:
            return None
        for t in self._order:
            if t not in active:
                self._deficit[t] = 0.0
        n = len(self._order)
        # Bounded: one full rotation grants every active tenant a quantum
        # >= 1, so a serve happens within 2n iterations — unless a tenant
        # was burst-charged into debt (charge(); deficit << 0), in which
        # case it needs one extra rotation per unit of debt to re-earn
        # credit before its next serve.
        debt = max(0.0, -min(self._deficit[t] for t in active))
        for _ in range((2 * n) * (int(debt) + 1) + 1):
            t = self._order[self._idx]
            if t in active and self._deficit[t] >= 1.0:
                self._deficit[t] -= 1.0
                return t
            self._idx = (self._idx + 1) % n
            t = self._order[self._idx]
            if t in active:
                self._deficit[t] += self._quantum[t]
        raise AssertionError("DRR failed to converge")  # pragma: no cover

    def charge(self, tenant: str, units: float) -> None:
        """Debit service beyond the single unit ``pick()`` already took —
        burst serving charges one pick N tokens, not 1 (each scheduler
        pick runs an N-tick burst). The deficit may go negative; the
        tenant re-earns credit across subsequent rotations, which is
        exactly how classic DRR amortizes variable packet sizes, so
        served-TOKEN ratios still converge to the weights at burst
        granularity."""
        if tenant in self._deficit and units > 0:
            self._deficit[tenant] -= float(units)


class FairQueue:
    """Thread-safe tenant-fair queue: DRR picks the tenant, EDF picks the
    request. ``push`` never blocks (admission already bounded depth);
    ``pop`` blocks up to ``timeout`` for work."""

    def __init__(self, weights: Dict[str, float]):
        self._drr = DeficitRoundRobin(weights)
        # (deadline_at or +inf, submission seq, item): EDF with FIFO ties.
        self._heaps: Dict[str, list] = {t: [] for t in weights}
        self._seq = itertools.count()
        self._cond = threading.Condition()

    def push(self, tenant: str, item: Any,
             deadline_at: Optional[float] = None) -> int:
        """Enqueue; returns the total depth AFTER the push."""
        key = math.inf if deadline_at is None else float(deadline_at)
        with self._cond:
            if tenant not in self._heaps:
                raise KeyError(f"unknown tenant {tenant!r}")
            heapq.heappush(self._heaps[tenant], (key, next(self._seq), item))
            self._cond.notify()
            return sum(len(h) for h in self._heaps.values())

    def _pop_locked(self) -> Optional[Tuple[str, Any]]:
        active = {t for t, h in self._heaps.items() if h}
        tenant = self._drr.pick(active)
        if tenant is None:
            return None
        _, _, item = heapq.heappop(self._heaps[tenant])
        return tenant, item

    def try_pop(self) -> Optional[Tuple[str, Any]]:
        with self._cond:
            return self._pop_locked()

    def pop(self, timeout: Optional[float] = None
            ) -> Optional[Tuple[str, Any]]:
        with self._cond:
            if not self._cond.wait_for(
                    lambda: any(self._heaps.values()), timeout):
                return None
            return self._pop_locked()

    def depth(self) -> int:
        with self._cond:
            return sum(len(h) for h in self._heaps.values())

    def depths(self) -> Dict[str, int]:
        with self._cond:
            return {t: len(h) for t, h in self._heaps.items()}

    def drain(self) -> Iterable[Tuple[str, Any]]:
        """Remove and return everything queued (shutdown path)."""
        out = []
        with self._cond:
            for t, h in self._heaps.items():
                out.extend((t, item) for _, _, item in h)
                h.clear()
        return out
