"""CLI entry point — the reference's flag surface on the TPU-native runtime.

Mirrors ``src/main.py:775-838`` (argparse, role dispatch) with the stages
re-homed: on a TPU host the whole pipeline lives in one process, so
``--stage N`` processes become execution MODES:

  * ``--mode local``  — in-process cluster: fixed-split or load-balancing
    stage servers + the pipeline client, one generation end-to-end. This is
    also the ``scripts/run_all.py`` role (component 17): the reference
    spawned 4 subprocesses and scraped their logs; here the same topology is
    constructed directly.
  * ``--mode fused``  — the ICI hot path: all stages in one jitted program
    on a ("stage"[, "tp"]) device mesh (microbatched pipelined decode).
  * ``--mode oracle`` — unpartitioned single-device generation
    (``scripts/single_gpu_check.py``, component 19): the correctness/speed
    baseline with identical sampling.

Model weights: ``--checkpoint`` loads a local HF checkpoint directory via
transformers (offline; no downloads — zero-egress environments). Without a
checkpoint, weights are random-initialized from the ``--model`` preset, which
still exercises every runtime path. Tokenization uses the checkpoint's
tokenizer when available, else a UTF-8 byte fallback so the CLI always runs.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import random
import re
import sys
import time
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .models import full_forward, get_config, init_kv_cache, init_params
from .models.config import ModelConfig
from .models.partition import StagePlan, parse_splits, slice_stage_params
from .ops.sampling import SamplingParams
from .runtime.client import PipelineClient, make_server_record
from .runtime.executor import StageExecutor
from .runtime.server import ElasticStageServer
from .runtime.transport import LocalTransport
from .scheduling.registry import PlacementRegistry

logger = logging.getLogger("mini_petals_tpu")


def _emit(*parts, **kwargs) -> None:
    """CLI output boundary: every user-facing stdout line in this module
    goes through here (scripts/check_no_bare_print.py enforces it).
    Diagnostics belong on a logger; _emit is for the REPORT a mode exists
    to print — generation text, status tables, scrape output."""
    print(*parts, **kwargs)  # noqa: T201 — the one sanctioned print

# float16 runs as bfloat16: TPUs have no fp16 compute path (load_model warns).
_DTYPE_MAP = {"float32": jnp.float32, "bfloat16": jnp.bfloat16,
              "float16": jnp.bfloat16}


# ---------------------------------------------------------------------------
# Tokenizer (checkpoint tokenizer, else byte-level fallback)
# ---------------------------------------------------------------------------

class ByteTokenizer:
    """UTF-8 byte fallback: token id = byte value. Keeps the CLI runnable
    with random-init models in zero-egress environments."""

    eos_token_id: Optional[int] = None

    def encode(self, text: str) -> List[int]:
        return list(text.encode("utf-8"))

    def decode(self, ids: Sequence[int]) -> str:
        return bytes(int(i) % 256 for i in ids).decode("utf-8", errors="replace")


def load_tokenizer(checkpoint: Optional[str]):
    if checkpoint:
        try:
            from transformers import AutoTokenizer

            return AutoTokenizer.from_pretrained(checkpoint, local_files_only=True)
        except Exception as exc:
            logger.warning("tokenizer load failed (%s); using byte fallback", exc)
    return ByteTokenizer()


_STORES: dict = {}


def _remote_store(args):
    """Memoized RemoteShardStore for an http(s):// --checkpoint (one cache
    + one LRU state per process, shared by load_model and _stage_params)."""
    from .models.remote_store import RemoteShardStore

    key = (args.checkpoint, args.weight_cache_dir)
    store = _STORES.get(key)
    if store is None:
        cache = args.weight_cache_dir or os.path.join(
            os.path.expanduser("~"), ".cache", "mini_petals_tpu",
            re.sub(r"[^A-Za-z0-9._-]+", "_", args.checkpoint))
        store = RemoteShardStore(
            args.checkpoint, cache,
            max_cache_bytes=args.weight_cache_bytes)
        _STORES[key] = store
    return store


def _is_remote(checkpoint) -> bool:
    return bool(checkpoint) and checkpoint.startswith(("http://", "https://"))


def load_model(args) -> Tuple[ModelConfig, dict]:
    if args.dtype == "float16":
        # TPUs have no fp16 compute path; bf16 differs numerically (8-bit
        # exponent / 7-bit mantissa vs 5/10) so an fp16 baseline will not
        # reproduce bit-for-bit.
        logger.warning("--dtype float16 runs as bfloat16 on TPU")
    dtype = _DTYPE_MAP[args.dtype]
    if _is_remote(args.checkpoint):
        from .models.hf_import import config_from_checkpoint

        store = _remote_store(args)
        cfg = config_from_checkpoint(store.fetch_config())
        if args.mode in ("local", "serve", "client", "gateway"):
            # Per-span streaming (petals from_pretrained.py:81-128): params
            # stay None; each serving role later fetches just the shards
            # covering ITS span (store.load_stage via _stage_params).
            return cfg, None
        # oracle/fused/etc. need the FULL tree up front: fetch every shard,
        # then stream-convert from the cache like a local checkpoint.
        from .models.partition import ROLE_FULL, StageSpec

        full = StageSpec(0, ROLE_FULL, 0, cfg.num_layers)
        return cfg, store.load_stage(cfg, full, dtype=dtype)
    if args.checkpoint:
        if args.mode in ("local", "serve", "client", "gateway"):
            from .models.hf_import import config_from_checkpoint

            has_st = (os.path.exists(os.path.join(
                args.checkpoint, "model.safetensors.index.json"))
                or os.path.exists(os.path.join(args.checkpoint,
                                               "model.safetensors")))
            if has_st:
                # Per-stage weight streaming (petals from_pretrained.py:
                # 81-128): stage servers read only their span's shards; the
                # full model is never materialized (run_local/run_serve/
                # run_client load per-stage when params is None).
                return config_from_checkpoint(args.checkpoint), None
        import torch
        from transformers import AutoModelForCausalLM

        from .models.hf_import import config_from_hf, convert_state_dict

        torch.manual_seed(0)
        hf = AutoModelForCausalLM.from_pretrained(
            args.checkpoint, local_files_only=True, torch_dtype=torch.float32
        )
        cfg = config_from_hf(hf.config)
        params = convert_state_dict(cfg, hf.state_dict(), dtype=np.float32)
        if dtype != jnp.float32:
            # Float leaves only: the gemma2 per-layer "window" leaf is
            # int32 position arithmetic (see convert_state_dict).
            params = jax.tree.map(
                lambda x: (x.astype(dtype)
                           if jnp.issubdtype(x.dtype, jnp.floating) else x),
                params)
        return cfg, params
    cfg = get_config(args.model)
    logger.info("no --checkpoint: random-initializing %s (%d layers)",
                args.model, cfg.num_layers)
    return cfg, init_params(jax.random.PRNGKey(args.seed), cfg, dtype=dtype)


# ---------------------------------------------------------------------------
# Modes
# ---------------------------------------------------------------------------

def _model_id(args) -> str:
    """Registry-scoping model id: --model_name, defaulting to the --model
    preset. The ONE place the fallback rule lives — every record publish and
    client query must agree or the swarm silently splits per model id."""
    return args.model_name or args.model


def _client_metrics(args):
    """Under ``--telemetry`` the client folds its series into the
    process-global registry (one ``--mode metrics`` scrape shows client +
    server families); otherwise it keeps its default private registry."""
    if getattr(args, "telemetry", False):
        from . import telemetry

        return telemetry.get_registry()
    return None


def run_local(args, cfg: ModelConfig, params) -> int:
    """In-process cluster: servers (fixed or LB) + client, one generation."""
    splits = parse_splits(args.splits) if args.splits else None
    if splits is None:
        plan = StagePlan.even(cfg.num_layers, 4)
    else:
        plan = StagePlan.from_splits(cfg.num_layers, splits)

    transport = LocalTransport()
    registry = PlacementRegistry(rng=random.Random(args.seed))
    provider = lambda spec: _stage_params(args, cfg, params, spec)  # noqa: E731

    if args.use_load_balancing:
        min_block = plan.stages[0].end
        num_blocks = args.num_blocks or max(
            1, (cfg.num_layers - min_block) // max(plan.num_stages - 1, 1))
        for i in range(args.num_servers):
            ElasticStageServer(
                f"server-{i}", cfg, provider, registry, transport,
                executor_kwargs={
                    "offload": args.use_cpu_offload,
                    "keep_layers_resident": args.keep_layers_on_gpu,
                    "prefix_cache_bytes": args.prefix_cache_mb << 20,
                },
                num_blocks=num_blocks,
                total_blocks=args.total_blocks or cfg.num_layers,
                min_block=min_block,
                balance_quality=args.balance_quality,
                mean_balance_check_period=args.mean_balance_check_period,
                bandwidth_mbps=args.network_bandwidth_mbps,
                rng=random.Random(args.seed + i),
                model=_model_id(args),
            ).start_serving()
    else:
        for spec in plan.stages[1:]:
            peer = f"server-stage{spec.index}"
            ex = StageExecutor(
                cfg, spec, provider(spec), peer_id=peer,
                offload=args.use_cpu_offload,
                keep_layers_resident=args.keep_layers_on_gpu,
                prefix_cache_bytes=args.prefix_cache_mb << 20,
            )
            transport.add_peer(peer, ex)
            registry.register(make_server_record(
                peer, spec, model=_model_id(args)))

    stage0 = StageExecutor(cfg, plan.stages[0], provider(plan.stages[0]),
                           peer_id="client-local",
                           prefix_cache_bytes=args.prefix_cache_mb << 20)
    client = PipelineClient(
        cfg, plan, stage0, transport, registry,
        use_module_routing=bool(args.use_load_balancing),
        route_by_latency=args.route_by_latency,
        total_blocks=args.total_blocks or cfg.num_layers,
        request_timeout=args.request_timeout,
        seed=args.seed,
        model=_model_id(args),
        metrics=_client_metrics(args),
    )
    return _generate_and_report(args, client.generate, cfg)


def _maybe_lora(args, cfg, params, start=None, end=None):
    """Apply ``--lora``: fold saved adapter deltas (a fine-tune's
    ``export_lora`` .npz) into the attention weights at LOAD time —
    serving a tuned model needs no runtime adapter support, and the merge
    runs BEFORE quantization so int8/nf4 weights include the deltas.
    start/end select the span's slice (stage serving); None = full model
    (oracle/fused)."""
    path = getattr(args, "lora", None)
    if not path or "layers" not in params:
        return params
    from .models.lora import load_lora, merge_lora, slice_lora

    cached = _maybe_lora._cache.get(path)
    if cached is None:
        # Load once per process: elastic re-spans and multi-stage local
        # mode call _stage_params repeatedly.
        cached = _maybe_lora._cache[path] = load_lora(path)
    tree, scale = cached
    # Validate the FULL adapter depth BEFORE slicing — a wrong-model
    # adapter could slice to exactly a span's width and silently merge
    # deltas from the wrong layers on every non-final stage.
    for t, ab in tree.items():
        if ab["a"].shape[0] != cfg.num_layers:
            raise SystemExit(
                f"--lora: adapter {t!r} covers {ab['a'].shape[0]} layers, "
                f"the model has {cfg.num_layers} (adapter trained for a "
                "different model?)")
    if start is not None:
        tree = slice_lora(tree, start, end)
    return {**params,
            "layers": merge_lora(cfg, params["layers"], tree, scale)}


_maybe_lora._cache = {}


def _maybe_quantize(args, params, tp: int = 1):
    """Apply ``--quant`` weight-only quantization (int8 measured +26%
    decode tokens/s on-chip — docs/PERFORMANCE.md): QuantizedTensor/
    NF4Tensor leaves ride the layer trees and dequantize per layer inside
    the scans; embed/head stay full precision. Rejected with tp > 1 on
    the fused path: the megatron sharding tables key on leaf names that
    quantized pytree nodes hide, so the q/s leaves would replicate over
    tp and the closing psum would scale every projection by tp — the same
    silent corruption the TP stage engine guards against
    (parallel/tensor_parallel.py shard tables)."""
    if getattr(args, "quant", "none") == "none":
        return params
    if tp > 1:
        raise SystemExit(
            "--quant is not supported with --tp > 1 on the fused/ring "
            "path (quantized leaves cannot be megatron-sharded; run "
            "tp=1, or serve full-precision TP)")
    from .models.quant import quantize_params

    return quantize_params(params, args.quant)


def run_fused(args, cfg: ModelConfig, params) -> int:
    """Fused ICI pipeline generation (microbatch=1 stream for the CLI), or
    — with ``--ring_sessions G`` — G concurrent generations on the
    multi-session ring-decode schedule (every stage advances a different
    session each tick; see parallel.ring_decode)."""
    from .parallel.pipeline import IciPipeline

    num_stages = args.num_stages or max(1, min(len(jax.devices()) // args.tp, 4))
    while cfg.num_layers % num_stages:
        num_stages -= 1
    params = _maybe_quantize(args, _maybe_lora(args, cfg, params),
                             tp=args.tp)
    if getattr(args, "ring_sessions", 0) > 1:
        return _run_fused_ring(args, cfg, params, num_stages)
    pipe = IciPipeline.build(cfg, params, num_stages=num_stages,
                             num_micro=1, tp=args.tp)
    logger.info("fused pipeline: %d stages x tp=%d on %s",
                num_stages, args.tp, pipe.mesh.devices.ravel())

    def generate(prompt_ids, max_new_tokens, sampling, eos_token_id=None,
                 **_kw):
        from .ops.sampling import (
            make_recent_buffer,
            push_recent,
            sample_token_jit,
            sampling_scalars,
        )
        from .runtime.client import GenerationResult

        sp_args = sampling_scalars(sampling.temperature, sampling.top_p,
                                   sampling.top_k,
                                   sampling.repetition_penalty)
        recent, nvalid = make_recent_buffer()

        def pick(logits_last, step):
            # Full reference sampler (jitted — one executable for every
            # knob config), oracle key schedule PRNGKey(seed + step) —
            # single-session fused output matches --mode oracle.
            nonlocal recent, nvalid
            if sampling.greedy:
                return int(jnp.argmax(logits_last))
            tok = sample_token_jit(jax.random.PRNGKey(args.seed + step),
                                   logits_last.astype(jnp.float32),
                                   recent, nvalid, *sp_args)
            recent, nvalid = push_recent(recent, nvalid, tok)
            return int(tok)

        max_len = len(prompt_ids) + max_new_tokens + 1
        kv_dtype = pipe.embed["wte"].dtype
        k, v = pipe.init_kv(1, max(128, max_len), dtype=kv_dtype)
        ids = jnp.asarray(np.asarray(prompt_ids, np.int32)[None, None, :])
        t0 = time.monotonic()
        logits, k, v = pipe.forward(ids, k, v, jnp.int32(0))
        tok = pick(logits[0, 0, -1], 0)
        ttft = time.monotonic() - t0
        tokens = [tok]
        cur = len(prompt_ids)
        decode_times = []
        stopped = "max_tokens"
        for step_i in range(1, max_new_tokens):
            if eos_token_id is not None and tokens[-1] == eos_token_id:
                stopped = "eos"
                break
            if len(tokens) >= 5 and len(set(tokens[-5:])) == 1:
                stopped = "repeat"
                break
            t0 = time.monotonic()
            step = jnp.asarray([[[tokens[-1]]]], jnp.int32)
            logits, k, v = pipe.forward(step, k, v, jnp.int32(cur))
            tokens.append(pick(logits[0, 0, -1], step_i))
            decode_times.append(time.monotonic() - t0)
            cur += 1
        return GenerationResult(tokens=tokens, ttft_s=ttft,
                                decode_times_s=decode_times, stopped_by=stopped)

    return _generate_and_report(args, generate, cfg,
                                supports_speculative=False)


def run_oracle(args, cfg: ModelConfig, params) -> int:
    """Single-device unpartitioned generation (scripts/single_gpu_check.py).

    Both greedy and sampled decoding ride the fused multi-step engine
    (runtime.fused_decode): whole chunks run as ONE compiled program with
    stop conditions checked between chunks — the CUDA-graph replay the
    reference's oracle lacks. The sampled path folds the full reference
    sampler into the scan with the SAME per-step key schedule as the old
    per-token loop, so outputs are bit-identical to it. ``--quant`` serves
    int8/nf4 weights, dequantized per layer inside the scan."""
    params = _maybe_quantize(args, _maybe_lora(args, cfg, params))

    def _drive_chunks(prompt_ids, max_new_tokens, eos_token_id, *,
                      prefill_first_token, run_chunk, chunk):
        """Shared chunked-generation driver for both fused engines.

        ``prefill_first_token(ids, kc, vc) -> (tok0, kc, vc)`` consumes the
        prompt and produces the first token (greedy argmax or key-schedule
        step 0 of the sampler); ``run_chunk(last_tok, cur, n, kc, vc, step)
        -> (got_tokens, kc, vc)`` runs n fused steps (``step`` = PRNG
        schedule index of the chunk's first token; the greedy engine ignores
        it). Stop conditions are re-checked PER TOKEN inside each chunk —
        the fused program may overshoot an EOS/repeat point and the trimmed
        output must match per-token decoding exactly — and each chunk's
        FULL wall time amortizes over the KEPT tokens so reported tokens/s
        doesn't inflate on overshoot."""
        from .runtime.client import GenerationResult

        max_len = max(128, len(prompt_ids) + max_new_tokens + 1)
        kc, vc = init_kv_cache(cfg, cfg.num_layers, 1, max_len,
                               dtype=params["embed"]["wte"].dtype)
        ids = jnp.asarray(np.asarray(prompt_ids, np.int32)[None, :])
        t0 = time.monotonic()
        tok0, kc, vc = prefill_first_token(ids, kc, vc)
        tokens = [int(tok0)]
        ttft = time.monotonic() - t0
        cur = len(prompt_ids)
        step = 1                      # PRNG schedule index: seed + step
        decode_times: List[float] = []
        stopped = "max_tokens"
        while len(tokens) < max_new_tokens and stopped == "max_tokens":
            if eos_token_id is not None and tokens[-1] == eos_token_id:
                stopped = "eos"
                break
            if len(tokens) >= 5 and len(set(tokens[-5:])) == 1:
                stopped = "repeat"
                break
            n = min(chunk, max_new_tokens - len(tokens))
            t0 = time.monotonic()
            got, kc, vc = run_chunk(tokens[-1], cur, n, kc, vc, step)
            dt = time.monotonic() - t0
            kept = 0
            for tok in got:
                tokens.append(int(tok))
                cur += 1
                step += 1
                kept += 1
                if eos_token_id is not None and int(tok) == eos_token_id:
                    stopped = "eos"
                    break
                if len(tokens) >= 5 and len(set(tokens[-5:])) == 1:
                    stopped = "repeat"
                    break
            decode_times.extend([dt / max(kept, 1)] * kept)
        return GenerationResult(
            tokens=tokens[:max_new_tokens], ttft_s=ttft,
            decode_times_s=decode_times[:max(len(tokens) - 1, 0)],
            stopped_by=stopped)

    def generate(prompt_ids, max_new_tokens, sampling, eos_token_id=None,
                 **_kw):
        chunk = min(max_new_tokens, 32)
        if sampling.greedy:
            from .runtime.fused_decode import make_fused_decode

            fn = make_fused_decode(cfg, chunk, 1, exact_head=True)

            def prefill_first(ids, kc, vc):
                logits, kc, vc = full_forward(cfg, params, ids, kc, vc,
                                              jnp.int32(0))
                return int(jnp.argmax(logits[0, -1])), kc, vc

            def run_chunk(last, cur, n, kc, vc, step):
                toks, kc, vc = fn(params, jnp.asarray([last], jnp.int32),
                                  kc, vc, jnp.int32(cur), jnp.int32(n))
                return [int(t) for t in np.asarray(toks[:n, 0])], kc, vc

            return _drive_chunks(prompt_ids, max_new_tokens, eos_token_id,
                                 prefill_first_token=prefill_first,
                                 run_chunk=run_chunk, chunk=chunk)

        from .ops.sampling import (
            make_recent_buffer,
            push_recent,
            sample_token_jit,
            sampling_scalars,
        )
        from .runtime.fused_decode import make_fused_sample_decode

        fn = make_fused_sample_decode(cfg, chunk)
        sp_args = sampling_scalars(sampling.temperature, sampling.top_p,
                                   sampling.top_k,
                                   sampling.repetition_penalty)
        state = {"recent": None, "nvalid": None}

        def prefill_first(ids, kc, vc):
            logits, kc, vc = full_forward(cfg, params, ids, kc, vc,
                                          jnp.int32(0))
            recent, nvalid = make_recent_buffer()
            # First token: key schedule step 0 (same as the per-token loop).
            tok = sample_token_jit(jax.random.PRNGKey(args.seed),
                                   logits[0, -1], recent, nvalid, *sp_args)
            state["recent"], state["nvalid"] = push_recent(recent, nvalid,
                                                           tok)
            return int(tok), kc, vc

        def run_chunk(last, cur, n, kc, vc, step):
            toks, kc, vc, state["recent"], state["nvalid"] = fn(
                params, jnp.asarray(last, jnp.int32), kc, vc,
                jnp.int32(cur), jnp.int32(n),
                jnp.int32(args.seed + step), state["recent"],
                state["nvalid"], *sp_args)
            return [int(t) for t in np.asarray(toks[:n])], kc, vc

        return _drive_chunks(prompt_ids, max_new_tokens, eos_token_id,
                             prefill_first_token=prefill_first,
                             run_chunk=run_chunk, chunk=chunk)

    return _generate_and_report(args, generate, cfg,
                                supports_speculative=False)


def _run_fused_ring(args, cfg: ModelConfig, params, num_stages: int) -> int:
    """`--mode fused --ring_sessions G`: serve G concurrent prompts
    ('||'-separated in --prompt; a single prompt is replicated) with the
    bubble-free rotation schedule. Each session prefills its own length
    via the masked single-group prefill, then all G decode together — one
    sampled token per tick in steady state instead of one per S ticks.
    temperature > 0 runs the FULL reference sampler inside the rotation
    (per-session recent windows, the oracle's PRNGKey(seed + i) schedule —
    each session's text matches --mode oracle for its prompt); greedy
    otherwise. --speculative_k composes with both: greedy output stays
    token-identical to the plain ring for any draft quality; sampled +
    speculative preserves the sampling DISTRIBUTION exactly (rejection
    sampling) but uses a per-round key schedule, so the text differs from
    the non-speculative run at the same seed (logged below)."""
    from .parallel.pipeline import IciPipeline
    from .parallel.ring_decode import RingDecoder, make_ring_prefill_group

    G = args.ring_sessions
    if G < num_stages:
        raise SystemExit(
            f"--ring_sessions {G} < pipeline stages {num_stages}: the "
            "rotation needs at least one session per stage "
            "(use --num_stages to shrink the pipeline)")
    tokenizer = load_tokenizer(_remote_store(args).cache_dir
                               if _is_remote(args.checkpoint)
                               else args.checkpoint)
    prompts = [p for p in args.prompt.split("||") if p.strip()] or ["hi"]
    orig = len(prompts)  # cycle over the USER's prompts, not the grown list
    while len(prompts) < G:
        prompts.append(prompts[len(prompts) % orig])
    prompts = prompts[:G]
    prompt_ids = [[i % cfg.vocab_size for i in tokenizer.encode(p)]
                  for p in prompts]
    eos = getattr(tokenizer, "eos_token_id", None)
    sampled = args.temperature > 0

    spec_k = getattr(args, "speculative_k", 0) or 0
    if spec_k and sampled:
        logger.warning(
            "sampled + speculative ring: rejection-sampling verification "
            "preserves the sampling distribution exactly, but the per-round "
            "key schedule differs from the per-token one — text will not "
            "bitwise-match the same seed without --speculative_k")
    pipe = IciPipeline.build(cfg, params, num_stages=num_stages,
                             num_micro=G, tp=args.tp)
    logger.info("ring decode: %d sessions over %d stages x tp=%d (%s%s)",
                G, num_stages, args.tp,
                "sampled" if sampled else "greedy",
                f", speculative_k={spec_k}" if spec_k else "")
    chunk = 16
    if spec_k:
        from .parallel.ring_decode import make_ring_spec_round

        round_fn = make_ring_spec_round(pipe, spec_k)
    else:
        rd = RingDecoder.build(pipe, max_steps=chunk, sampled=sampled)
    prefill_one = make_ring_prefill_group(pipe, return_logits=sampled)
    # chunk-1 (or one spec round) of overshoot headroom: a session finishing
    # mid-chunk still has its (discarded) extra steps' KV writes in-bounds.
    max_len = (max(len(p) for p in prompt_ids) + args.max_new_tokens
               + max(chunk, spec_k + 1))
    k, v = pipe.init_kv(1, max(128, max_len), dtype=pipe.embed["wte"].dtype)

    from .ops.sampling import (
        RECENT_WINDOW,
        push_recent,
        sample_token_jit,
        sampling_scalars,
    )

    sp_scalars = sampling_scalars(args.temperature, args.top_p, args.top_k,
                                  args.repetition_penalty)
    recent = jnp.zeros((G, 1, RECENT_WINDOW), jnp.int32)
    nvalid = jnp.zeros((G, 1), jnp.int32)

    t0 = time.monotonic()
    lens = np.zeros((G,), np.int32)
    tok0 = np.zeros((G, 1), np.int32)
    for g, ids_g in enumerate(prompt_ids):
        first, k, v = prefill_one(jnp.asarray([ids_g], jnp.int32), k, v, g)
        if sampled:
            # Key-schedule step 0 on the prefill logits (run_oracle parity).
            tok = sample_token_jit(jax.random.PRNGKey(args.seed),
                                   first[0], recent[g, 0], nvalid[g, 0],
                                   *sp_scalars)
            r2, n2 = push_recent(recent[g, 0], nvalid[g, 0], tok)
            recent = recent.at[g, 0].set(r2)
            nvalid = nvalid.at[g, 0].set(n2)
            tok0[g] = int(tok)
        else:
            tok0[g] = np.asarray(first)
        lens[g] = len(ids_g)
    ttft = time.monotonic() - t0

    sessions = [[int(tok0[g, 0])] for g in range(G)]
    done = [False] * G
    cur_tok = jnp.asarray(tok0)
    lens_j = jnp.asarray(lens)
    sp_vecs = dict(
        temps=jnp.full((G,), args.temperature, jnp.float32),
        top_ps=jnp.full((G,), args.top_p, jnp.float32),
        top_ks=jnp.full((G,), args.top_k, jnp.int32),
        reps=jnp.full((G,), args.repetition_penalty, jnp.float32))
    steps_done = 1      # PRNG schedule index: prefill token was step 0
    t0 = time.monotonic()
    # Count only tokens harvested INSIDE the decode loop: the first token
    # per session came from prefill (its cost sits in TTFT, not here).
    produced = 0
    rounds = accepted = 0

    def _harvest(g, run) -> None:
        """Append tokens to session g with per-token stop checks."""
        nonlocal produced
        for t in run:
            if done[g] or len(sessions[g]) >= args.max_new_tokens:
                done[g] = True
                return
            t = int(t)
            sessions[g].append(t)
            produced += 1
            if eos is not None and t == eos:
                done[g] = True
            elif (len(sessions[g]) >= 5
                  and len(set(sessions[g][-5:])) == 1):
                done[g] = True

    if spec_k:
        # Ring x speculative: each round every session consumes its last
        # token + K client-drafted tokens; the last stage verifies
        # in-program (greedy chain or rejection sampling), yielding 1..K+1
        # tokens per session per pipeline traversal. Greedy output is
        # token-identical to the plain ring regardless of draft quality.
        from .runtime.speculative import ngram_draft

        contexts = [list(prompt_ids[g]) + list(sessions[g])
                    for g in range(G)]
        lens_np = lens.copy()
        while True:
            act = [g for g in range(G)
                   if not done[g] and len(sessions[g]) < args.max_new_tokens]
            if not act:
                break
            tokens_in = np.zeros((G, 1, spec_k + 1), np.int32)
            for g in range(G):
                tokens_in[g, 0, 0] = sessions[g][-1]
                drafts = (list(ngram_draft(contexts[g], spec_k))
                          if not done[g] else [])
                for i in range(spec_k):   # short draft runs pad with 0 — a
                    # pad is just a (probably wrong) draft; verification
                    # keeps the output exact either way.
                    tokens_in[g, 0, 1 + i] = (drafts[i] if i < len(drafts)
                                              else 0)
            seed_base = np.asarray(
                [args.seed + len(sessions[g]) for g in range(G)], np.int32)
            toks, nacc, k, v, recent, nvalid = round_fn(
                tokens_in, k, v, lens_np, seed_base=seed_base,
                recent=recent, nvalid=nvalid, **sp_vecs)
            toks, nacc = np.asarray(toks), np.asarray(nacc)
            rounds += 1
            for g in act:
                na = int(nacc[g, 0])
                accepted += na
                run = toks[g, 0, : na + 1].tolist()
                lens_np[g] += na + 1
                _harvest(g, run)
                contexts[g] = list(prompt_ids[g]) + list(sessions[g])
    else:
        while True:
            act = [g for g in range(G)
                   if not done[g] and len(sessions[g]) < args.max_new_tokens]
            if not act:
                break
            n = max(1, min(chunk, max(args.max_new_tokens - len(sessions[g])
                                      for g in act)))
            if sampled:
                toks, k, v, recent, nvalid = rd.decode_sampled(
                    cur_tok, k, v, lens_j, n,
                    seed_base=jnp.full((G,), args.seed + steps_done,
                                       jnp.int32),
                    recent=recent, nvalid=nvalid, **sp_vecs)
            else:
                toks, k, v = rd.decode(cur_tok, k, v, lens_j, n)
            steps_done += n
            toks = np.asarray(toks[:n])
            for g in range(G):
                _harvest(g, toks[:, g, 0])
            cur_tok = jnp.asarray(toks[n - 1])
            lens_j = lens_j + n
    decode_s = time.monotonic() - t0

    for g, toks_g in enumerate(sessions):
        text = tokenizer.decode(toks_g[:args.max_new_tokens])
        _emit(f"\n=== Session {g} ({len(toks_g[:args.max_new_tokens])} "
              f"tokens) ===\n{text}")
    _emit(f"\nTTFT (all {G} prefills): {ttft:.3f}s")
    rate = produced / decode_s if decode_s > 0 else 0.0
    _emit(f"Decode: {decode_s:.3f}s total, {rate:.2f} tokens/s aggregate "
          f"across {G} sessions (decode-loop tokens only; each session's "
          f"first token comes from prefill)")
    if spec_k and rounds:
        _emit(f"Speculative: {rounds} rounds, "
              f"{accepted / (rounds * len(sessions)):.2f} drafts accepted "
              f"per session-round (of {spec_k})")
    return 0


def _generate_and_report(args, generate_fn, cfg: ModelConfig,
                         supports_speculative: bool = True) -> int:
    # Remote checkpoints: the tokenizer files were fetched into the local
    # cache by fetch_config — load from there, not the URL.
    tokenizer = load_tokenizer(_remote_store(args).cache_dir
                               if _is_remote(args.checkpoint)
                               else args.checkpoint)
    prompt_ids = tokenizer.encode(args.prompt)
    prompt_ids = [i % cfg.vocab_size for i in prompt_ids]
    sampling = SamplingParams(
        temperature=args.temperature, top_p=args.top_p, top_k=args.top_k,
        repetition_penalty=args.repetition_penalty,
    )
    eos = getattr(tokenizer, "eos_token_id", None)

    kw = {}
    if getattr(args, "speculative_k", 0):
        if supports_speculative:
            kw["speculative_k"] = args.speculative_k
        else:
            logger.warning("--speculative_k is ignored in --mode %s "
                           "(pipeline-client modes only)", args.mode)
    if getattr(args, "deadline_s", None):
        if supports_speculative:  # same gate: pipeline-client modes only
            kw["deadline_s"] = args.deadline_s
        else:
            logger.warning("--deadline_s is ignored in --mode %s "
                           "(pipeline-client modes only)", args.mode)
    if getattr(args, "burst", 0):
        if supports_speculative:  # same gate: pipeline-client modes only
            kw["burst"] = args.burst
        else:
            logger.warning("--burst is ignored in --mode %s "
                           "(pipeline-client modes only)", args.mode)
    res = generate_fn(prompt_ids, args.max_new_tokens, sampling=sampling,
                      eos_token_id=eos, **kw)
    text = tokenizer.decode(res.tokens)
    # The reference's closing report (src/main.py:213-225): TTFT, decode
    # time, tokens/s.
    _emit(f"\n=== Generation ({len(res.tokens)} tokens, "
          f"stopped by {res.stopped_by}) ===")
    _emit(text)
    _emit(f"\nTTFT: {res.ttft_s:.3f}s")
    total_decode = sum(res.decode_times_s)
    _emit(f"Decode: {total_decode:.3f}s total, "
          f"{res.decode_tokens_per_s:.2f} tokens/s")
    return 0


# ---------------------------------------------------------------------------
# Network modes: REAL multi-process swarm over TCP (reference --stage N
# servers + DHT, src/main.py:243-278,426-555 — registry service instead of
# Kademlia, framed TCP instead of libp2p). One process per role:
#   --mode registry : control-plane service (the DHT bootstrap node role)
#   --mode serve    : one stage server (--stage N picks the span)
#   --mode client   : stage-0 client driving the remote pipeline
# ---------------------------------------------------------------------------

def _stage_params(args, cfg: ModelConfig, params, spec):
    """Stage weights for a serving role: streamed from a safetensors
    checkpoint when possible, sliced from the loaded tree otherwise, then
    optionally block-quantized (--quant int8, V9 parity)."""
    if params is None:
        if _is_remote(args.checkpoint):
            sp = _remote_store(args).load_stage(
                cfg, spec, dtype=_DTYPE_MAP[args.dtype])
        else:
            from .models.hf_import import load_stage_checkpoint

            sp = load_stage_checkpoint(args.checkpoint, cfg, spec,
                                       dtype=_DTYPE_MAP[args.dtype])
    else:
        sp = slice_stage_params(cfg, params, spec)
    sp = _maybe_lora(args, cfg, sp, spec.start, spec.end)
    # Stage-server TP + quant is guarded downstream (the TP engine's shard
    # tables reject quantized leaves loudly), so no tp check here.
    return _maybe_quantize(args, sp)


def run_registry(args, cfg: ModelConfig, params) -> int:
    del cfg, params
    from .runtime.net import RegistryServer

    srv = RegistryServer(host=args.host, port=args.registry_port,
                         ttl=args.ttl,
                         allow_fault_injection=args.allow_fault_injection)
    srv.start()
    # Machine-readable handshake line (the reference printed the DHT maddr
    # for run_all.py to scrape, src/main.py:449-465).
    _emit(f"REGISTRY_ADDR={srv.address}", flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        pass
    finally:
        srv.stop()
    return 0


def _serve_tp_mesh(args):
    """Local ('tp',) mesh for --mode serve --tp N: one server process using
    N chips for its stage (the reference wraps every serving block in TP,
    petals/server/backend.py:43). None when tp <= 1."""
    if args.tp <= 1:
        return None
    from jax.sharding import Mesh

    devs = jax.devices()
    if len(devs) < args.tp:
        raise SystemExit(
            f"--tp {args.tp} needs {args.tp} local devices, found {len(devs)}")
    return Mesh(np.asarray(devs[:args.tp]), ("tp",))


def run_serve(args, cfg: ModelConfig, params) -> int:
    import os

    from .runtime.executor import StageExecutor as _SE
    from .runtime.net import RemoteRegistry, TcpStageServer

    if args.use_load_balancing:
        return _run_serve_elastic(args, cfg, params)
    splits = parse_splits(args.splits) if args.splits else None
    plan = (StagePlan.from_splits(cfg.num_layers, splits) if splits
            else StagePlan.even(cfg.num_layers, 4))
    if args.stage == 0:
        # Full-span server: the only shape that can run burst decode —
        # on-device sampling feeds each tick's token straight back into
        # the embedding, so the scan needs blocks 0..L plus the head in
        # one process. Classic stage 0 runs inside the client, so this
        # shape is --batched-only; --splits is ignored for the span.
        if not args.batched:
            raise SystemExit(
                "--stage 0 serves the FULL model span and requires "
                "--batched (the burst-capable continuous-batching engine); "
                "classic stage 0 runs inside the client")
        spec = StagePlan.even(cfg.num_layers, 1).stages[0]
    elif not 1 <= args.stage < plan.num_stages:
        raise SystemExit(
            f"--stage must be 1..{plan.num_stages - 1} for serve mode "
            "(stage 0 runs inside the client; --stage 0 --batched serves "
            "the full span for --burst)")
    else:
        spec = plan.stages[args.stage]

    registry = RemoteRegistry(args.registry_addr, peers_cache=args.peers_cache)
    peer_id = args.peer_id or f"stage{args.stage}-{os.getpid()}"
    if args.sp_zigzag and args.sp <= 1:
        raise SystemExit("--sp_zigzag requires --sp N > 1 (it is a layout "
                         "for the sequence-parallel engine)")
    if args.sp > 1 and (args.batched or args.tp > 1 or args.use_cpu_offload):
        raise SystemExit("--sp does not compose with --batched/--tp/"
                         "--use_cpu_offload on one server")
    if args.prefix_cache_mb and args.sp > 1:
        raise SystemExit(
            "--prefix_cache_mb does not compose with --sp (the sp engine "
            "shards one session's prefix KV across the mesh; a shared "
            "store would need per-device segment sharding) — serve "
            "session or batched replicas with it instead")
    if args.sp > 1:
        # Sequence-parallel long-context engine: ONE session at a time, its
        # prefix KV sharded along T over the local ('sp',) mesh.
        from jax.sharding import Mesh as _Mesh

        from .parallel.sp_stage import SpStageRunner
        from .runtime.sp_serve import SpStageAdapter

        devs = jax.devices()
        if len(devs) < args.sp:
            raise SystemExit(f"--sp {args.sp} needs {args.sp} local devices, "
                             f"found {len(devs)}")
        mesh = _Mesh(np.asarray(devs[:args.sp]), ("sp",))
        runner = SpStageRunner(cfg, spec,
                               _stage_params(args, cfg, params, spec), mesh,
                               dtype=_DTYPE_MAP[args.dtype],
                               zigzag=args.sp_zigzag)
        # max_context default (8192/chip + tail) is the ADAPTER's policy.
        ex = SpStageAdapter(runner, peer_id=peer_id,
                            max_context=args.max_context)
    elif args.batched:
        # Continuous-batching engine behind the same TCP protocol: plain
        # sessions coalesce into shared rounds; exotic verbs get a retryable
        # refusal and clients route them to per-session replicas. Compute
        # runs inline on handler threads (NOT through a single-threaded
        # StageRuntime) — the adapter's round window IS the scheduler.
        if args.use_cpu_offload or args.keep_layers_on_gpu:
            raise SystemExit(
                "--batched keeps its span resident in HBM (the batched step "
                "reads every layer every round); host offload is a "
                "per-session-executor feature — drop --use_cpu_offload/"
                "--keep_layers_on_gpu or serve without --batched")
        if args.tp > 1:
            raise SystemExit("--batched does not compose with --tp yet; "
                             "serve per-session (--tp N) or batched (--batched)")
        from .runtime.batching import BatchedStageExecutor, BatchingStageAdapter

        kv_dtype = (jnp.bfloat16 if args.dtype == "bfloat16" else jnp.float32)
        engine = BatchedStageExecutor(
            cfg, spec, _stage_params(args, cfg, params, spec),
            slots=args.slots, max_len=args.max_session_len, dtype=kv_dtype,
            prefix_cache_bytes=args.prefix_cache_mb << 20,
            model=_model_id(args))
        ex = BatchingStageAdapter(engine, peer_id=peer_id)
    else:
        ex = _SE(cfg, spec, _stage_params(args, cfg, params, spec),
                 peer_id=peer_id,
                 offload=args.use_cpu_offload,
                 keep_layers_resident=args.keep_layers_on_gpu,
                 tp_mesh=_serve_tp_mesh(args),
                 prefix_cache_bytes=args.prefix_cache_mb << 20)
    logger.info("warming up stage %d (pre-compiling step shapes)", args.stage)
    if args.batched:
        # Warm the K+1-wide batched decode step and/or the N-tick burst
        # program too, so neither compiles inside the round leader's lock.
        ex.warmup(speculative_k=getattr(args, "speculative_k", 0),
                  burst=getattr(args, "burst", 0))
    else:
        ex.warmup()
    # Per-session executors serialize compute through the prioritized
    # runtime (one compute thread owns the chip; N handler threads own the
    # sockets — the reference's handlers→Runtime split). The batched engine
    # must NOT be serialized: concurrent handler calls are how its round
    # window coalesces, and its own lock + round leadership guard the chip.
    from .runtime.task_pool import StageRuntime

    # The batched engine must NOT be serialized (concurrent handler calls
    # are how its round window coalesces); the sp adapter serializes itself
    # with its own lock (one session owns the mesh anyway).
    runtime = (None if (args.batched or args.sp > 1)
               else StageRuntime(high_water=args.queue_high_water,
                                 low_water=args.queue_low_water))
    # Decentralized control plane: every serve process embeds a gossip
    # mirror of the placement records, so the swarm survives losing EVERY
    # dedicated registry (seeds become bootstrap-only, like DHT initial
    # peers). The server answers register/heartbeat/list itself and runs
    # anti-entropy exchanges piggybacked on the heartbeat cadence.
    from .scheduling.gossip import GossipLoop, GossipNode
    from .scheduling.registry import rec_to_dict as _r2d

    gnode = GossipNode(peer_id, ttl=registry.ttl,
                       rng=random.Random(args.seed + os.getpid()))
    srv = TcpStageServer(ex, host=args.host, port=args.rpc_port,
                         wire_dtype=args.wire_dtype, model=_model_id(args),
                         runtime=runtime,
                         allow_fault_injection=args.allow_fault_injection,
                         gossip=gnode,
                         relay_capacity=args.relay_capacity)
    srv.start()
    # --public_ip overrides the advertised address (the reference's
    # public-maddr-only advertising, component 21 / src/main.py:492-509).
    advert = (f"{args.public_ip}:{srv.address.rsplit(':', 1)[1]}"
              if args.public_ip else srv.address)
    gnode.self_address = advert
    rec = make_server_record(ex.peer_id, spec,
                             model=_model_id(args),
                             engine=getattr(ex, "engine", "session"))
    rec.max_context = getattr(ex, "max_context", None)
    rec.address = advert
    if args.relay_capacity > 0:
        rec.relay_capacity = args.relay_capacity
    # Next-hop RTT probe + relay attach share one transport: a TcpTransport
    # resolves peers via the registry, so both hit the real data-plane wire.
    from .runtime.net import TcpTransport as _TT
    from .runtime.net import attach_via_relay as _attach_relay
    from .runtime.net import check_direct_reachability as _reach
    from .telemetry import events as _events

    ping_tx = _TT(registry, wire_dtype=args.wire_dtype)
    # Dial-back reachability vote (petals/server/reachability.py): ask live
    # peers to dial `advert` back. An explicit False verdict means we are
    # NAT'd — attach to a volunteer and advertise relay_via so clients
    # route through it; None (nobody answered / first server in the swarm)
    # is treated as reachable. The registration below then replicates
    # relay_via through gossip like any other record field.
    if _reach(ping_tx, registry, advert) is False:
        got = _attach_relay(ping_tx, registry, ex.peer_id, srv.address)
        if got is None:
            _emit("WARNING: dial-back vote says this server is unreachable "
                  "and no relay volunteer accepted an attach — clients "
                  "will not be able to reach it (start a peer with "
                  "--relay_capacity N or fix --public_ip)", flush=True)
        else:
            rec.relay_via = got["relay"]
            # Advertise the relayed throughput through the same model the
            # planner trusts: with step=None get_server_throughput returns
            # the network-only estimate, so the relayed/direct ratio is
            # exactly the RELAY_PENALTY discount (petals' use_relay wiring).
            from .scheduling.throughput import get_server_throughput as _gst
            nb = max(1, spec.end - spec.start)
            direct_rps = _gst(None, cfg.hidden_size, num_blocks=nb)
            relayed_rps = _gst(None, cfg.hidden_size, use_relay=True,
                               num_blocks=nb)
            rec.throughput = rec.throughput * (relayed_rps / direct_rps)
            _events.emit("relay_attach", peer=ex.peer_id,
                         relay=rec.relay_via, address=srv.address)
            _emit(f"RELAY: serving via volunteer {rec.relay_via} "
                  f"(dial-back vote failed for {advert})", flush=True)
    registry.register(rec)
    gnode.publish(_r2d(rec))

    from .runtime.net import gossip_exchange as _gx

    def _seed_peers():
        # Seed the gossip peer set from whatever discovery still works —
        # the seed registry while it's up, the mirror/stale snapshot after.
        return [r.address for r in registry.live_servers() if r.address]

    from .telemetry.profiling import stats_digest as _stats_digest

    def _own_rec_with_stats():
        # Piggyback this server's live stats digest on the gossip record:
        # dict_to_rec ignores unknown keys, so the "stats" extra propagates
        # swarm-wide verbatim and --mode top reads it from ANY live mirror.
        d = _r2d(rec)
        d["stats"] = _stats_digest()
        return d

    gloop = GossipLoop(gnode, _gx, record_fn=_own_rec_with_stats,
                       extra_peers_fn=_seed_peers)
    gloop.start()
    _emit(f"SERVING stage={args.stage} span=[{spec.start},{spec.end}) "
          f"addr={advert} peer={ex.peer_id}", flush=True)
    # Next-hop RTT probe (petals/server/server.py:760-767) reuses ping_tx.
    from .runtime.server import measure_next_server_rtts as _rtts

    try:
        # Heartbeat every TTL/3 (src/main.py:529-537); re-register if the
        # registry restarted and forgot us.
        rtts = None
        while True:
            time.sleep(registry.ttl / 3.0)
            try:
                # Refresh first with last beat's RTTs, then measure — a slow
                # ping sweep must not delay the TTL refresh past expiry.
                rec.next_server_rtts = rtts
                if not registry.heartbeat(
                        ex.peer_id,
                        cache_tokens_left=ex.arena.tokens_left(),
                        next_server_rtts=rtts):
                    registry.register(rec)
                if rec.relay_via is not None:
                    # Relay circuits are leases: re-attach every beat to
                    # refresh ours (idempotent on the volunteer). If the
                    # volunteer died, pick a replacement and re-advertise —
                    # clients meanwhile hit the failover/replay path.
                    from .runtime.net import PeerUnavailable as _PU
                    try:
                        ping_tx.relay_attach(rec.relay_via, ex.peer_id,
                                             srv.address)
                    except (_PU, TimeoutError, ConnectionError, OSError):
                        got = _attach_relay(ping_tx, registry, ex.peer_id,
                                            srv.address,
                                            exclude=(rec.relay_via,))
                        if got is not None:
                            rec.relay_via = got["relay"]
                            _events.emit("relay_attach", peer=ex.peer_id,
                                         relay=rec.relay_via,
                                         address=srv.address)
                            registry.register(rec)
                            gnode.publish(_r2d(rec))
                # {} is published as-is: it RETRACTS stale RTTs (None would
                # mean "no update" and pin dead-link measurements forever).
                rtts = (None if spec.is_last else _rtts(
                    registry, lambda r: ping_tx.ping(r.peer_id),
                    ex.peer_id, spec.end,
                    budget_s=registry.ttl / 6.0,
                    model=_model_id(args)))
            except (ConnectionError, OSError) as exc:
                logger.warning("heartbeat failed: %s", exc)
    except KeyboardInterrupt:
        pass
    finally:
        gloop.stop()
        try:
            registry.unregister(ex.peer_id)
        except Exception:
            pass
        gnode.apply_unregister(ex.peer_id)
        srv.stop()
    return 0


def _run_serve_elastic(args, cfg: ModelConfig, params) -> int:
    """Elastic (load-balancing) stage server over TCP: the span is CHOSEN
    from live swarm coverage (rule 1), re-chosen on imbalance (rule 2), and
    the executor is swapped in place on the listening socket — the
    reference's LB servers were network servers too
    (src/main.py:281-423,558-772)."""
    import os

    from .runtime.net import RemoteRegistry, TcpStageServer
    from .runtime.server import ElasticStageServer

    peer = args.peer_id or f"lb-{os.getpid()}"
    registry = RemoteRegistry(args.registry_addr, peers_cache=args.peers_cache)
    # Serialize compute through the prioritized runtime: elastic servers see
    # whatever concurrency the swarm sends them, and concurrent per-session
    # forwards on one executor are not a supported dispatch pattern.
    from .runtime.task_pool import StageRuntime
    from .scheduling.gossip import GossipLoop, GossipNode

    gnode = GossipNode(peer, ttl=registry.ttl,
                       rng=random.Random(args.seed + os.getpid()))
    srv = TcpStageServer(None, host=args.host, port=args.rpc_port,
                         wire_dtype=args.wire_dtype, peer_id=peer,
                         model=_model_id(args), runtime=StageRuntime(),
                         allow_fault_injection=args.allow_fault_injection,
                         gossip=gnode)
    srv.start()
    advert = (f"{args.public_ip}:{srv.address.rsplit(':', 1)[1]}"
              if args.public_ip else srv.address)
    gnode.self_address = advert

    class _Membership:
        """LocalTransport's membership surface, backed by the live TCP
        socket: add_peer swaps the served executor, remove_peer blanks it
        (requests during a re-span get a retryable stage error)."""

        def add_peer(self, peer_id, executor):
            srv.executor = executor

        def remove_peer(self, peer_id):
            srv.executor = None

    splits = parse_splits(args.splits) if args.splits else None
    min_block = splits[0] if splits else 0  # client-local prefix floor
    total = args.total_blocks or cfg.num_layers
    num_blocks = args.num_blocks
    if num_blocks is None:
        # No --num_blocks: derive capacity from the REAL device memory
        # (weights + KV arena + headroom, petals server.py:275-326), falling
        # back to the even-thirds topology heuristic when the backend
        # publishes no byte limit (host CPU).
        from .runtime.server import derive_num_blocks

        num_blocks = derive_num_blocks(
            cfg, dtype_bytes=jnp.dtype(_DTYPE_MAP[args.dtype]).itemsize,
            quant=args.quant, tp=args.tp)
        if num_blocks is not None:
            num_blocks = min(num_blocks, max(total - min_block, 1))
    num_blocks = num_blocks or max(1, (total - min_block) // 3)
    from .runtime.net import TcpTransport as _TT

    ping_tx = _TT(registry, wire_dtype=args.wire_dtype)
    es = ElasticStageServer(
        peer, cfg, lambda spec: _stage_params(args, cfg, params, spec),
        registry, _Membership(),
        pinger=lambda rec: ping_tx.ping(rec.peer_id),
        num_blocks=num_blocks, total_blocks=total, min_block=min_block,
        balance_quality=args.balance_quality,
        mean_balance_check_period=args.mean_balance_check_period,
        bandwidth_mbps=args.network_bandwidth_mbps,
        executor_kwargs={"offload": args.use_cpu_offload,
                         "keep_layers_resident": args.keep_layers_on_gpu,
                         "tp_mesh": _serve_tp_mesh(args),
                         "prefix_cache_bytes": args.prefix_cache_mb << 20},
        advertise_address=advert, warmup=True,
        rng=random.Random(args.seed + os.getpid()),
        model=_model_id(args),
    )
    es.start()
    _emit(f"SERVING elastic span=[{es.spec.start},{es.spec.end}) "
          f"addr={advert} peer={peer}", flush=True)

    from .runtime.net import gossip_exchange as _gx
    from .scheduling.registry import rec_to_dict as _r2d

    from .telemetry.profiling import stats_digest as _stats_digest

    def _own_record():
        # During a re-span the spec is momentarily unset; skip that beat.
        if es.spec is None:
            return None
        d = _r2d(es._record())
        d["stats"] = _stats_digest()
        return d

    gloop = GossipLoop(
        gnode, _gx, record_fn=_own_record,
        extra_peers_fn=lambda: [r.address for r in registry.live_servers()
                                if r.address])
    gloop.start()
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        pass
    finally:
        gloop.stop()
        es.stop()
        gnode.apply_unregister(peer)
        srv.stop()
    return 0


def run_client(args, cfg: ModelConfig, params) -> int:
    from .runtime.executor import StageExecutor as _SE
    from .runtime.net import RemoteRegistry, TcpTransport

    splits = parse_splits(args.splits) if args.splits else None
    plan = (StagePlan.from_splits(cfg.num_layers, splits) if splits
            else StagePlan.even(cfg.num_layers, 4))
    registry = RemoteRegistry(args.registry_addr, peers_cache=args.peers_cache)
    transport = TcpTransport(registry, wire_dtype=args.wire_dtype,
                             model=_model_id(args))
    stage0 = _SE(cfg, plan.stages[0],
                 _stage_params(args, cfg, params, plan.stages[0]),
                 peer_id="client-local")
    client = PipelineClient(
        cfg, plan, stage0, transport, registry,
        use_module_routing=bool(args.use_load_balancing),
        route_by_latency=args.route_by_latency,
        total_blocks=args.total_blocks or cfg.num_layers,
        request_timeout=args.request_timeout,
        seed=args.seed,
        model=_model_id(args),
        long_context_threshold=args.long_context_threshold,
        metrics=_client_metrics(args),
    )
    try:
        return _generate_and_report(args, client.generate, cfg)
    finally:
        transport.close()


def _load_tenants_config(raw: Optional[str]):
    """Parse --tenants: inline JSON (starts with '{') or a file path;
    omitted means one 'default' tenant with the library defaults."""
    from .serving import parse_tenants_config

    raw = raw or '{"default": {}}'
    if not raw.lstrip().startswith("{"):
        with open(raw) as f:
            raw = f.read()
    return parse_tenants_config(json.loads(raw))


def run_gateway(args, cfg: ModelConfig, params) -> int:
    """--mode gateway: the multi-tenant serving front door. Owns one or
    more PipelineClients against the swarm at --registry_addr and serves
    the framed-TCP `submit` verb (docs/SERVING.md)."""
    from .runtime.executor import StageExecutor as _SE
    from .runtime.net import RemoteRegistry, TcpTransport
    from .serving import GatewayServer

    tenants, max_queue_depth, max_active = _load_tenants_config(args.tenants)
    splits = parse_splits(args.splits) if args.splits else None
    plan = (StagePlan.from_splits(cfg.num_layers, splits) if splits
            else StagePlan.even(cfg.num_layers, 4))
    registry = RemoteRegistry(args.registry_addr,
                              peers_cache=args.peers_cache)
    transports = []
    clients = []
    for i in range(max(1, args.gateway_clients)):
        tx = TcpTransport(registry, wire_dtype=args.wire_dtype,
                          model=_model_id(args))
        transports.append(tx)
        stage0 = _SE(cfg, plan.stages[0],
                     _stage_params(args, cfg, params, plan.stages[0]),
                     peer_id=f"gateway-local-{i}")
        clients.append(PipelineClient(
            cfg, plan, stage0, tx, registry,
            use_module_routing=bool(args.use_load_balancing),
            route_by_latency=args.route_by_latency,
            total_blocks=args.total_blocks or cfg.num_layers,
            request_timeout=args.request_timeout,
            seed=args.seed,
            model=_model_id(args),
            long_context_threshold=args.long_context_threshold,
            metrics=_client_metrics(args),
        ))
    gw = GatewayServer(clients, tenants, host=args.host,
                       port=args.rpc_port,
                       max_queue_depth=max_queue_depth,
                       max_active=max_active,
                       allow_fault_injection=args.allow_fault_injection)
    gw.start()
    _emit(f"GATEWAY addr={gw.address} tenants={','.join(sorted(tenants))} "
          f"clients={len(clients)} max_queue_depth={max_queue_depth} "
          f"max_active={max_active}", flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        pass
    finally:
        gw.stop()
        for tx in transports:
            tx.close()
    return 0


def run_submit(args) -> int:
    """--mode submit: fire --submit_requests requests at --gateway_addr as
    --tenant. No model weights load here — the gateway's swarm owns the
    model; only the tokenizer (prompt encoding) is needed."""
    from .serving import GatewaySubmitClient, Overloaded

    cfg = get_config(args.model)
    tokenizer = load_tokenizer(_remote_store(args).cache_dir
                               if _is_remote(args.checkpoint)
                               else args.checkpoint)
    prompt_ids = [i % cfg.vocab_size for i in tokenizer.encode(args.prompt)]
    client = GatewaySubmitClient(args.gateway_addr)
    shed = 0
    for i in range(args.submit_requests):
        t0 = time.perf_counter()
        try:
            res = client.submit(
                args.tenant, prompt_ids, args.max_new_tokens,
                temperature=args.temperature, top_p=args.top_p,
                top_k=args.top_k,
                repetition_penalty=args.repetition_penalty,
                deadline_s=args.deadline_s,
                timeout=args.request_timeout)
        except Overloaded as exc:
            shed += 1
            _emit(f"[{i}] SHED ({exc.reason}): retry after "
                  f"{exc.retry_after_s:.3f}s -- {exc}")
            continue
        dt = time.perf_counter() - t0
        _emit(f"[{i}] {len(res['tokens'])} tokens in {dt:.2f}s "
              f"(ttft={res['ttft_s'] or 0:.3f}s "
              f"queue_wait={res['queue_wait_s'] or 0:.3f}s "
              f"stopped_by={res['stopped_by']}): "
              f"{tokenizer.decode(res['tokens'])!r}")
    # Shedding is the gateway doing its job; only all-shed is a failure.
    return 1 if shed == args.submit_requests else 0


# ---------------------------------------------------------------------------
# Chaos soak (--mode chaos): deterministic fault injection against the REAL
# TCP data plane. Two generations with the same seed and prompt — one clean,
# one under a seeded FaultPlan covering every side of the swarm — must emit
# IDENTICAL tokens (recovery is exactly-once), and the doctor must
# reconstruct every injected failure from the flight-recorder rings.
# ---------------------------------------------------------------------------

def chaos_soak(cfg, params, *, prompt_ids, max_new_tokens=10, seed=0,
               splits=None, wire_dtype="f32", request_timeout=30.0,
               registry_addr=None, sampling=None, deadline_probe=True,
               stage_params=None) -> dict:
    """Run the chaos soak and return a verdict dict (``ok``, ``problems``,
    ``kinds_fired``, token lists, chain stats).

    ``registry_addr=None`` boots a self-contained swarm in-process — real
    TCP sockets, every role fault-armable. Passing an address instead
    ATTACHES to an externally launched swarm (scripts/chaos_swarm.py: one
    OS process per role, all started with --allow_fault_injection
    --telemetry) and scrapes the servers' event rings over the wire."""
    import collections as _collections
    import os as _os

    from .runtime.client import DeadlineExceeded
    from .runtime.executor import StageExecutor as _SE
    from .runtime.faults import FaultPlan, default_chaos_rules
    from .runtime.net import (RegistryServer, RemoteRegistry, TcpStageServer,
                              TcpTransport)
    from .runtime.task_pool import StageRuntime
    from .telemetry import doctor as _doc
    from .telemetry import events as _events

    # The soak IS a diagnostic: record regardless of --telemetry so the
    # doctor cross-check below always has a local stream to read.
    _events.get_recorder().enable()
    if sampling is None:
        # Greedy keeps the token-equality oracle independent of sampling
        # RNG bookkeeping; seeded-sampling parity under failover is already
        # pinned by the recovery tests.
        sampling = SamplingParams(temperature=0.0)
    if stage_params is None:
        stage_params = lambda spec: slice_stage_params(cfg, params, spec)  # noqa: E731
    plan = (StagePlan.from_splits(cfg.num_layers, splits) if splits
            else StagePlan.even(cfg.num_layers, 4))

    attach = registry_addr is not None
    reg_server = None
    servers = []
    problems: List[str] = []
    result: dict = {"attach": attach, "seed": seed}
    try:
        if not attach:
            reg_server = RegistryServer(host="127.0.0.1", port=0,
                                        allow_fault_injection=True)
            reg_server.start()
            registry_addr = reg_server.address
        reg = RemoteRegistry(registry_addr)
        if not attach:
            for spec in plan.stages[1:]:
                ex = _SE(cfg, spec, stage_params(spec),
                         peer_id=f"chaos-s{spec.index}")
                srv = TcpStageServer(ex, host="127.0.0.1", port=0,
                                     wire_dtype=wire_dtype,
                                     runtime=StageRuntime(),
                                     allow_fault_injection=True)
                srv.start()
                rec = make_server_record(ex.peer_id, spec)
                rec.address = srv.address
                reg.register(rec)
                servers.append(srv)
        ex0 = _SE(cfg, plan.stages[0], stage_params(plan.stages[0]),
                  peer_id="chaos-client")

        def _client(tx):
            # settle_seconds=0: recovery sleeps would dominate a soak whose
            # faults are all deterministic one-shots.
            return PipelineClient(cfg, plan, ex0, tx, reg,
                                  request_timeout=request_timeout,
                                  settle_seconds=0.0, seed=seed)

        # --- clean reference run: nothing armed anywhere ---
        tx1 = TcpTransport(reg, wire_dtype=wire_dtype)
        try:
            clean = _client(tx1).generate(
                list(prompt_ids), max_new_tokens, sampling=sampling,
                session_id="chaos-clean")
        finally:
            tx1.close()

        # --- arm every side of the swarm with one seeded plan ---
        recs = sorted(reg.live_servers(),
                      key=lambda r: (r.start_block, r.peer_id))
        peer_ids = [r.peer_id for r in recs]
        rules = default_chaos_rules(peer_ids, seed=seed)
        client_plan = FaultPlan([r for r in rules if r.side == "client"],
                                seed=seed)
        server_rules = [r for r in rules if r.side == "server"]
        reg_rules = [r for r in rules if r.side == "registry"]
        # Admin traffic goes over a transport that is NEVER armed — an
        # armed transport's own frames would consume fault-rule matches.
        admin = TcpTransport(reg, wire_dtype=wire_dtype)
        for pid in peer_ids:
            admin.install_fault_plan(pid, FaultPlan(server_rules, seed=seed))
        reg._rpc({"verb": "fault",
                  "plan": FaultPlan(reg_rules, seed=seed).to_dict()})
        # Deterministic control-plane traffic: two heartbeats trip the
        # `duplicate` rule (times=2) and two list calls walk `stale_registry`
        # past nth=2 — the data-plane run alone need not send either verb.
        for _ in range(2):
            reg.heartbeat(peer_ids[0])
            reg.live_servers()

        # --- chaos run: same seed, same prompt, every plan armed ---
        tx2 = TcpTransport(reg, wire_dtype=wire_dtype)
        tx2.set_fault_plan(client_plan)
        try:
            chaos = _client(tx2).generate(
                list(prompt_ids), max_new_tokens, sampling=sampling,
                session_id="chaos-faulty")
        finally:
            tx2.set_fault_plan(None)  # drops pooled conns too
            tx2.close()
        result["tokens_clean"] = list(clean.tokens)
        result["tokens_chaos"] = list(chaos.tokens)
        if list(clean.tokens) != list(chaos.tokens):
            problems.append(
                f"token divergence under faults: clean={list(clean.tokens)} "
                f"chaos={list(chaos.tokens)}")

        # --- deadline probe: an expired budget is a TYPED client error ---
        if deadline_probe:
            tx3 = TcpTransport(reg, wire_dtype=wire_dtype)
            try:
                _client(tx3).generate(list(prompt_ids), 2, sampling=sampling,
                                      session_id="chaos-deadline",
                                      deadline_s=1e-6)
                problems.append(
                    "deadline_s=1e-6 generation finished instead of raising "
                    "DeadlineExceeded")
            except DeadlineExceeded:
                result["deadline_probe"] = "raised DeadlineExceeded"
            finally:
                tx3.close()

        # --- collect firing reports, then disarm for whoever runs next ---
        client_firings = list(client_plan.report())
        server_firings: List[dict] = []
        for pid in peer_ids:
            server_firings += admin.fault_report(pid)
        reg_firings = list(reg._rpc(
            {"verb": "fault", "action": "report"}).get("firings", []))
        for pid in peer_ids:
            admin.install_fault_plan(pid, None)
        reg._rpc({"verb": "fault", "action": "clear"})

        all_firings = client_firings + server_firings + reg_firings
        fired = _collections.Counter(f["kind"] for f in all_firings)
        result["kinds_fired"] = sorted(fired)
        result["firings"] = dict(fired)
        if len(fired) < 5:
            problems.append(
                f"only {len(fired)} distinct fault kinds fired "
                f"({sorted(fired)}); the soak must cover >= 5")

        # --- doctor cross-check: every injection must be reconstructable
        # from the flight-recorder rings as part of a failure chain ---
        streams = [{"meta": {"pid": _os.getpid()},
                    "events": [ev.to_dict()
                               for ev in _events.get_recorder().events()]}]
        if attach:
            streams += _doc.scrape_events(admin, peer_ids)
        timeline = _doc.merge_timeline(streams)
        chains = _doc.failure_chains(timeline)
        in_chains = _collections.Counter(
            ev.get("fields", {}).get("kind")
            for ch in chains for ev in ch["events"]
            if ev.get("event") == "fault_injected")
        # Attach mode cannot read the registry process's ring (no
        # dump-events verb there) — hold the doctor to what it CAN see.
        accountable = client_firings + server_firings + (
            [] if attach else reg_firings)
        for kind, n in _collections.Counter(
                f["kind"] for f in accountable).items():
            if in_chains.get(kind, 0) < n:
                problems.append(
                    f"doctor chains account for {in_chains.get(kind, 0)}/{n} "
                    f"'{kind}' injections")
        fault_chains = [ch for ch in chains
                        if any(ev.get("event") == "fault_injected"
                               for ev in ch["events"])]
        result["chains"] = len(chains)
        result["fault_chains"] = len(fault_chains)
        if not any("chaos-faulty" in ch["sessions"] for ch in fault_chains):
            problems.append(
                "no failure chain correlates an injected fault with the "
                "chaos session (expected session 'chaos-faulty')")
        admin.close()
    finally:
        for srv in servers:
            srv.stop()
        if reg_server is not None:
            reg_server.stop()
    result["problems"] = problems
    result["ok"] = not problems
    return result


def registry_loss_soak(cfg, params, *, prompt_ids, max_new_tokens=8, seed=0,
                       splits=None, wire_dtype="f32", request_timeout=30.0,
                       peers_cache=None, gossip_interval_s=0.25,
                       sampling=None, stage_params=None) -> dict:
    """Total-registry-loss survival drill (the tentpole's acceptance
    scenario): boot a primary+standby registry and a gossiping stage swarm
    in-process, kill BOTH registries deterministically mid-generation, and
    require

      * the in-flight generation to finish with tokens IDENTICAL to a
        clean run (the data plane never depended on the seeds);
      * a FRESH client — empty snapshot, seeds dead — to bootstrap through
        a live stage server's gossip mirror (via the --peers_cache file)
        and generate the same tokens;
      * a restarted seed to be re-adopted (``registry_recovered``), and the
        doctor to reconstruct the whole outage as one failure chain:
        registries lost -> gossip-served discovery -> seeds restored.
    """
    import tempfile as _tempfile

    from .runtime.executor import StageExecutor as _SE
    from .runtime.net import (RegistryServer, RemoteRegistry, TcpStageServer,
                              TcpTransport, gossip_exchange)
    from .runtime.task_pool import StageRuntime
    from .scheduling.gossip import GossipLoop, GossipNode
    from .scheduling.registry import rec_to_dict as _r2d
    from .telemetry import doctor as _doc
    from .telemetry import events as _events

    _events.get_recorder().enable()
    if sampling is None:
        sampling = SamplingParams(temperature=0.0)
    if stage_params is None:
        stage_params = lambda spec: slice_stage_params(cfg, params, spec)  # noqa: E731
    plan = (StagePlan.from_splits(cfg.num_layers, splits) if splits
            else StagePlan.even(cfg.num_layers, 4))
    if peers_cache is None:
        fd, peers_cache = _tempfile.mkstemp(prefix="peers_cache_",
                                            suffix=".json")
        os.close(fd)

    problems: List[str] = []
    result: dict = {"seed": seed, "peers_cache": peers_cache}
    registries: List[RegistryServer] = []
    servers: List[TcpStageServer] = []
    loops: List[GossipLoop] = []
    transports: List[TcpTransport] = []
    try:
        # --- seeds: a primary + one standby, both about to die ---
        for _ in range(2):
            rs = RegistryServer(host="127.0.0.1", port=0)
            rs.start()
            registries.append(rs)
        seed_addrs = ",".join(rs.address for rs in registries)
        reg = RemoteRegistry(seed_addrs, timeout=2.0,
                             peers_cache=peers_cache)

        # --- gossiping stage swarm (every server embeds a mirror) ---
        gnodes: List[GossipNode] = []
        own_recs: List = []
        for spec in plan.stages[1:]:
            ex = _SE(cfg, spec, stage_params(spec),
                     peer_id=f"rloss-s{spec.index}")
            gnode = GossipNode(ex.peer_id,
                               rng=random.Random(seed + spec.index))
            srv = TcpStageServer(ex, host="127.0.0.1", port=0,
                                 wire_dtype=wire_dtype,
                                 runtime=StageRuntime(), gossip=gnode)
            srv.start()
            gnode.self_address = srv.address
            rec = make_server_record(ex.peer_id, spec)
            rec.address = srv.address
            reg.register(rec)
            gnode.publish(_r2d(rec))
            servers.append(srv)
            gnodes.append(gnode)
            own_recs.append(rec)
        all_addrs = [s.address for s in servers]
        for gnode, rec in zip(gnodes, own_recs):
            loop = GossipLoop(gnode, gossip_exchange,
                              record_fn=lambda r=rec: _r2d(r),
                              extra_peers_fn=lambda: list(all_addrs),
                              interval_s=gossip_interval_s)
            loop.start()
            loops.append(loop)
        # Anti-entropy must have replicated the FULL live set everywhere
        # before the seeds die, or a mirror could serve a partial swarm.
        deadline = time.monotonic() + 30.0
        want = len(servers)
        while (any(n.live_count() < want for n in gnodes)
               and time.monotonic() < deadline):
            time.sleep(0.05)
        if any(n.live_count() < want for n in gnodes):
            problems.append(
                "gossip never converged: mirror live counts "
                f"{[n.live_count() for n in gnodes]} < {want}")

        ex0 = _SE(cfg, plan.stages[0], stage_params(plan.stages[0]),
                  peer_id="rloss-client")

        def _client(tx, stage0, registry):
            return PipelineClient(cfg, plan, stage0, tx, registry,
                                  request_timeout=request_timeout,
                                  settle_seconds=0.0, seed=seed)

        # --- clean reference run (also warms the peers cache) ---
        tx1 = TcpTransport(reg, wire_dtype=wire_dtype)
        transports.append(tx1)
        clean = _client(tx1, ex0, reg).generate(
            list(prompt_ids), max_new_tokens, sampling=sampling,
            session_id="rloss-clean")
        result["tokens_clean"] = list(clean.tokens)

        # --- chaos run: the 2nd stage-0 forward kills EVERY seed ---
        class _KillSwitch:
            """Stage-0 proxy that trips `kill` after the Nth forward: the
            registry massacre lands DETERMINISTICALLY mid-generation
            (after prefill, before the decode steps finish)."""

            def __init__(self, inner, after_n, kill):
                self._inner, self._after, self._kill = inner, after_n, kill
                self.calls = 0

            def forward(self, req):
                out = self._inner.forward(req)
                self.calls += 1
                if self.calls == self._after:
                    self._kill()
                return out

            def __getattr__(self, name):
                return getattr(self._inner, name)

        def _kill_seeds():
            for rs in registries:
                try:
                    rs.stop()
                except Exception:
                    pass

        tx2 = TcpTransport(reg, wire_dtype=wire_dtype)
        transports.append(tx2)
        chaos = _client(tx2, _KillSwitch(ex0, 2, _kill_seeds), reg).generate(
            list(prompt_ids), max_new_tokens, sampling=sampling,
            session_id="rloss-chaos")
        result["tokens_chaos"] = list(chaos.tokens)
        if list(clean.tokens) != list(chaos.tokens):
            problems.append(
                "token divergence across the registry massacre: "
                f"clean={list(clean.tokens)} chaos={list(chaos.tokens)}")

        # --- the WARM client's next read must be mirror-served ---
        recs = reg.live_servers()
        if len(recs) < want:
            problems.append(
                f"warm client saw {len(recs)}/{want} servers after seed "
                "loss (gossip fallback should have served the full set)")

        # --- fresh client: no snapshot, dead seeds, only the cache file ---
        reg2 = RemoteRegistry(seed_addrs, timeout=2.0,
                              peers_cache=peers_cache)
        boot = reg2.live_servers()
        result["bootstrap_records"] = len(boot)
        if len(boot) < want:
            problems.append(
                f"fresh client bootstrapped {len(boot)}/{want} records "
                "from the gossip mirrors")
        tx3 = TcpTransport(reg2, wire_dtype=wire_dtype)
        transports.append(tx3)
        fresh = _client(tx3, ex0, reg2).generate(
            list(prompt_ids), max_new_tokens, sampling=sampling,
            session_id="rloss-bootstrap")
        result["tokens_bootstrap"] = list(fresh.tokens)
        if list(clean.tokens) != list(fresh.tokens):
            problems.append(
                "registry-less bootstrap diverged: "
                f"clean={list(clean.tokens)} fresh={list(fresh.tokens)}")

        # --- restore a seed: the swarm must re-adopt it ---
        primary_port = int(registries[0].address.rsplit(":", 1)[1])
        restored = RegistryServer(host="127.0.0.1", port=primary_port)
        restored.start()
        registries.append(restored)
        for rec in own_recs:
            reg2.register(rec)      # the serve heartbeat loop's re-register
        back = reg2.live_servers()
        if len(back) < want:
            problems.append(
                f"restored seed served {len(back)}/{want} records")

        # --- doctor: the outage must read as ONE failure chain ---
        streams = [{"meta": {"pid": os.getpid()},
                    "events": [ev.to_dict()
                               for ev in _events.get_recorder().events()]}]
        chains = _doc.failure_chains(_doc.merge_timeline(streams))
        result["chains"] = len(chains)
        ok_chain = False
        for ch in chains:
            names = {ev.get("event") for ev in ch["events"]}
            if ("registry_unreachable" in names
                    and ({"gossip_fallback", "gossip_served_discovery"}
                         & names)
                    and "registry_recovered" in names):
                ok_chain = True
        if not ok_chain:
            problems.append(
                "doctor chains do not reconstruct the outage (want one "
                "chain with registry_unreachable + gossip-served "
                "discovery + registry_recovered)")
    finally:
        for loop in loops:
            loop.stop()
        for tx in transports:
            try:
                tx.close()
            except Exception:
                pass
        for srv in servers:
            srv.stop()
        for rs in registries:
            try:
                rs.stop()
            except Exception:
                pass
    result["problems"] = problems
    result["ok"] = not problems
    return result


def relay_break_soak(cfg, params, *, prompt_ids, max_new_tokens=8, seed=0,
                     splits=None, wire_dtype="f32", request_timeout=30.0,
                     kill_after=2, sampling=None, stage_params=None) -> dict:
    """Relay-death survival drill (--mode chaos --chaos_scenario relay_break).

    Boots an in-process swarm where the FINAL stage server is NAT'd by
    construction: it advertises an address nothing can dial (a closed local
    port) and serves only through a relay volunteer. Two executor-less
    volunteers stand by; the higher-capacity one wins the attach. The drill:

      * clean run THROUGH the relay (the reference tokens — proving the
        relayed data path is bit-identical to begin with);
      * chaos run: the Nth stage-0 forward stops the active volunteer
        mid-generation and re-attaches the NAT'd server to the standby
        (exactly what its heartbeat re-pick does, compressed in time);
      * the generation must finish with IDENTICAL tokens — the client's
        normal failover/replay path re-resolves the hop through the new
        volunteer;
      * the circuit breaker must blame the dead VOLUNTEER, not the relayed
        peer (one dead relay must not blacklist every peer behind it);
      * the doctor must reconstruct the incident as one failure chain:
        relay lost -> failover -> replay.
    """
    from .runtime.executor import StageExecutor as _SE
    from .runtime.net import (RegistryServer, RemoteRegistry, TcpStageServer,
                              TcpTransport, attach_via_relay)
    from .runtime.task_pool import StageRuntime
    from .telemetry import doctor as _doc
    from .telemetry import events as _events

    _events.get_recorder().enable()
    if sampling is None:
        sampling = SamplingParams(temperature=0.0)
    if stage_params is None:
        stage_params = lambda spec: slice_stage_params(cfg, params, spec)  # noqa: E731
    plan = (StagePlan.from_splits(cfg.num_layers, splits) if splits
            else StagePlan.even(cfg.num_layers, 4))

    problems: List[str] = []
    result: dict = {"seed": seed}
    registries: List[RegistryServer] = []
    servers: List[TcpStageServer] = []
    transports: List[TcpTransport] = []
    try:
        rs = RegistryServer(host="127.0.0.1", port=0)
        rs.start()
        registries.append(rs)
        reg = RemoteRegistry(rs.address, timeout=2.0)

        # --- two relay volunteers: pure forwarders, no stage span. Their
        # records carry an EMPTY span (never routed stage traffic) plus
        # relay_capacity, exactly what attach_via_relay's picker keys on;
        # v1's larger capacity makes it the deterministic first choice. ---
        from .scheduling.registry import ServerRecord as _SR

        vols = {}
        for vid, cap in (("relay-v1", 4), ("relay-v2", 2)):
            vsrv = TcpStageServer(None, host="127.0.0.1", port=0,
                                  wire_dtype=wire_dtype, peer_id=vid,
                                  relay_capacity=cap)
            vsrv.start()
            vrec = _SR(peer_id=vid, start_block=0, end_block=0,
                       address=vsrv.address, relay_capacity=cap)
            reg.register(vrec)
            servers.append(vsrv)
            vols[vid] = vsrv

        # --- stage swarm; the FINAL stage is the NAT'd server ---
        nat_spec = plan.stages[-1]
        nat_rec = None
        nat_srv = None
        for spec in plan.stages[1:]:
            ex = _SE(cfg, spec, stage_params(spec),
                     peer_id=f"rbreak-s{spec.index}")
            srv = TcpStageServer(ex, host="127.0.0.1", port=0,
                                 wire_dtype=wire_dtype,
                                 runtime=StageRuntime())
            srv.start()
            rec = make_server_record(ex.peer_id, spec)
            if spec is nat_spec:
                # Advertise a closed port: any DIRECT dial fails instantly,
                # so a passing run proves every frame rode the relay.
                rec.address = "127.0.0.1:9"
                nat_rec, nat_srv = rec, srv
            else:
                rec.address = srv.address
            reg.register(rec)
            servers.append(srv)

        # --- the NAT'd server attaches (run_serve's post-vote path) ---
        atx = TcpTransport(reg, wire_dtype=wire_dtype)
        transports.append(atx)
        got = attach_via_relay(atx, reg, nat_rec.peer_id, nat_srv.address)
        if got is None or got["relay"] != "relay-v1":
            problems.append(f"attach picked {got and got['relay']}, "
                            "want relay-v1 (highest spare capacity)")
            result["problems"] = problems
            result["ok"] = False
            return result
        nat_rec.relay_via = got["relay"]
        _events.emit("relay_attach", peer=nat_rec.peer_id,
                     relay=nat_rec.relay_via, address=nat_srv.address)
        reg.register(nat_rec)

        ex0 = _SE(cfg, plan.stages[0], stage_params(plan.stages[0]),
                  peer_id="rbreak-client")

        def _client(tx, stage0):
            return PipelineClient(cfg, plan, stage0, tx, reg,
                                  request_timeout=request_timeout,
                                  settle_seconds=0.0, seed=seed)

        # --- clean reference run, THROUGH the relay ---
        tx1 = TcpTransport(reg, wire_dtype=wire_dtype)
        transports.append(tx1)
        clean = _client(tx1, ex0).generate(
            list(prompt_ids), max_new_tokens, sampling=sampling,
            session_id="rbreak-clean")
        result["tokens_clean"] = list(clean.tokens)

        # --- chaos run: Nth stage-0 forward kills the active volunteer ---
        class _KillSwitch:
            """Stage-0 proxy that trips `kill` after the Nth forward, so the
            relay dies DETERMINISTICALLY mid-generation (after prefill,
            before the decode steps finish)."""

            def __init__(self, inner, after_n, kill):
                self._inner, self._after, self._kill = inner, after_n, kill
                self.calls = 0

            def forward(self, req):
                out = self._inner.forward(req)
                self.calls += 1
                if self.calls == self._after:
                    self._kill()
                return out

            def __getattr__(self, name):
                return getattr(self._inner, name)

        def _break_relay():
            vols["relay-v1"].stop()
            # The NAT'd server's heartbeat re-pick, compressed in time:
            # re-attach via the standby and re-advertise relay_via. The
            # in-flight client meanwhile takes the failover/replay path.
            got2 = attach_via_relay(atx, reg, nat_rec.peer_id,
                                    nat_srv.address, exclude=("relay-v1",))
            if got2 is not None:
                nat_rec.relay_via = got2["relay"]
                _events.emit("relay_attach", peer=nat_rec.peer_id,
                             relay=nat_rec.relay_via,
                             address=nat_srv.address)
                reg.register(nat_rec)

        tx2 = TcpTransport(reg, wire_dtype=wire_dtype)
        transports.append(tx2)
        cl2 = _client(tx2, _KillSwitch(ex0, kill_after, _break_relay))
        chaos = cl2.generate(list(prompt_ids), max_new_tokens,
                             sampling=sampling, session_id="rbreak-chaos")
        result["tokens_chaos"] = list(chaos.tokens)
        result["relay_after"] = nat_rec.relay_via
        result["recoveries"] = cl2.recoveries
        if list(clean.tokens) != list(chaos.tokens):
            problems.append(
                "token divergence across the relay kill: "
                f"clean={list(clean.tokens)} chaos={list(chaos.tokens)}")
        if nat_rec.relay_via != "relay-v2":
            problems.append(
                f"re-attach landed on {nat_rec.relay_via}, want relay-v2")
        if cl2.recoveries < 1:
            problems.append(
                "client reported no recoveries — the kill never landed "
                "mid-generation (raise max_new_tokens or lower kill_after)")

        # --- blame: the breaker must track the VOLUNTEER, not the peer ---
        if not cl2.breaker.allow(nat_rec.peer_id):
            problems.append(
                "circuit breaker opened for the RELAYED peer "
                f"{nat_rec.peer_id}; the dead volunteer relay-v1 should "
                "have taken the blame")

        # --- doctor: the incident must read as ONE failure chain ---
        streams = [{"meta": {"pid": os.getpid()},
                    "events": [ev.to_dict()
                               for ev in _events.get_recorder().events()]}]
        chains = _doc.failure_chains(_doc.merge_timeline(streams))
        result["chains"] = len(chains)
        ok_chain = False
        for ch in chains:
            names = {ev.get("event") for ev in ch["events"]}
            if {"relay_forward_error", "failover", "replay_done"} <= names:
                ok_chain = True
        if not ok_chain:
            problems.append(
                "doctor chains do not reconstruct the incident (want one "
                "chain with relay_forward_error + failover + replay_done)")
    finally:
        for tx in transports:
            try:
                tx.close()
            except Exception:
                pass
        for srv in servers:
            try:
                srv.stop()
            except Exception:
                pass
        for rs in registries:
            try:
                rs.stop()
            except Exception:
                pass
    result["problems"] = problems
    result["ok"] = not problems
    return result


def overload_soak(cfg, params, *, prompt_ids, max_new_tokens=8, seed=0,
                  splits=None, wire_dtype="f32", request_timeout=30.0,
                  requests_per_tenant=3, stage_params=None,
                  burst=0) -> dict:
    """Multi-tenant overload drill (--mode chaos --chaos_scenario overload).

    Boots a swarm + gateway in-process, then proves the serving tentpole's
    three contracts end-to-end over real sockets:

      * FAIRNESS — two tenants, gold:bronze weights 4:1, preload the fair
        queue while the scheduler is paused, release it, and require the
        served-TOKEN ratio over the contended window (up to gold's last
        token) to land within +/-25% of the weight ratio;
      * CORRECTNESS — every admitted request (deadline_s generous) must
        finish in budget with tokens IDENTICAL to a sequential no-gateway
        baseline on the same swarm/seed (interleaving is invisible);
      * SHEDDING — a strict gateway must refuse excess load with the typed
        Overloaded (concurrency, rate, and queue_full reasons, each with
        retry_after_s > 0), and the doctor must reconstruct the refusals
        from the flight-recorder ring.
    """
    import threading as _threading

    from .runtime.executor import StageExecutor as _SE
    from .runtime.net import (RegistryServer, RemoteRegistry, TcpStageServer,
                              TcpTransport)
    from .runtime.task_pool import StageRuntime
    from .serving import (GatewayServer, GatewaySubmitClient, Overloaded,
                          TenantConfig)
    from .telemetry import doctor as _doc
    from .telemetry import events as _events

    _events.get_recorder().enable()
    sampling = SamplingParams(temperature=0.0)  # greedy: token-identity oracle
    if stage_params is None:
        stage_params = lambda spec: slice_stage_params(cfg, params, spec)  # noqa: E731
    plan = (StagePlan.from_splits(cfg.num_layers, splits) if splits
            else StagePlan.even(cfg.num_layers, 4))
    prompt_ids = list(prompt_ids)

    def _variant(i: int) -> List[int]:
        # Distinct prompt per request (a rotation): identical results would
        # otherwise mask cross-session KV contamination.
        k = i % max(1, len(prompt_ids))
        return prompt_ids[k:] + prompt_ids[:k]

    weights = {"gold": 4.0, "bronze": 1.0}
    total = 2 * requests_per_tenant
    problems: List[str] = []
    result: dict = {"seed": seed, "weights": weights,
                    "requests_per_tenant": requests_per_tenant}
    reg_server = None
    servers: List = []
    transports: List = []
    gateways: List = []
    try:
        reg_server = RegistryServer(host="127.0.0.1", port=0)
        reg_server.start()
        reg = RemoteRegistry(reg_server.address)
        for spec in plan.stages[1:]:
            ex = _SE(cfg, spec, stage_params(spec),
                     peer_id=f"overload-s{spec.index}")
            srv = TcpStageServer(ex, host="127.0.0.1", port=0,
                                 wire_dtype=wire_dtype,
                                 runtime=StageRuntime())
            srv.start()
            rec = make_server_record(ex.peer_id, spec)
            rec.address = srv.address
            reg.register(rec)
            servers.append(srv)
        if burst > 0:
            # Burst mode: gateway sessions decode in N-tick jitted bursts
            # against a FULL-span batched server. Its record advertises
            # stage_index=0 so classic stage routing (which queries stages
            # 1..N-1) never sees it — the sequential baseline below still
            # runs the per-step path, making it the token oracle for the
            # burst-served gateway requests.
            from .models.partition import ROLE_FULL, StageSpec
            from .runtime.batching import (BatchedStageExecutor,
                                           BatchingStageAdapter)

            full = StageSpec(index=0, role=ROLE_FULL, start=0,
                             end=cfg.num_layers)
            blen = max(len(prompt_ids) + max_new_tokens + burst + 8, 64)
            bex = BatchedStageExecutor(cfg, full, stage_params(full),
                                       slots=max(2 * requests_per_tenant, 4),
                                       max_len=blen)
            bad = BatchingStageAdapter(bex, window_s=0.0,
                                       peer_id="overload-burst")
            bad.warmup(burst=burst)
            bsrv = TcpStageServer(bad, host="127.0.0.1", port=0,
                                  wire_dtype=wire_dtype)
            bsrv.start()
            brec = make_server_record(bad.peer_id, full, engine="batched")
            brec.address = bsrv.address
            reg.register(brec)
            servers.append(bsrv)
            result["burst"] = burst
        ex0 = _SE(cfg, plan.stages[0], stage_params(plan.stages[0]),
                  peer_id="overload-client")

        def _client():
            tx = TcpTransport(reg, wire_dtype=wire_dtype)
            transports.append(tx)
            return PipelineClient(cfg, plan, ex0, tx, reg,
                                  request_timeout=request_timeout,
                                  settle_seconds=0.0, seed=seed)

        # --- sequential no-gateway baseline: the token oracle ---
        base_client = _client()
        baseline: Dict[int, List[int]] = {}
        for i in range(total):
            res = base_client.generate(
                _variant(i), max_new_tokens, sampling=sampling,
                session_id=f"ov-base-{i}")
            baseline[i] = list(res.tokens)

        # --- phase A: fairness + correctness under contention ---
        tenants = {name: TenantConfig(name, weight=w, rate=1000.0,
                                      burst=1000.0, max_concurrency=64)
                   for name, w in weights.items()}
        gw = GatewayServer([_client()], tenants, port=0,
                           max_queue_depth=64, max_active=total,
                           start_paused=True, burst=burst)
        gateways.append(gw)
        gw.start()
        submits: Dict[int, dict] = {}

        def _submit(idx: int, tenant: str):
            try:
                submits[idx] = GatewaySubmitClient(gw.address).submit(
                    tenant, _variant(idx), max_new_tokens,
                    deadline_s=60.0, session_id=f"ov-{tenant}-{idx}",
                    timeout=request_timeout + 60.0)
            except Exception as exc:  # noqa: BLE001 — scored below
                submits[idx] = {"error": f"{type(exc).__name__}: {exc}"}

        tenant_order = (["gold"] * requests_per_tenant
                        + ["bronze"] * requests_per_tenant)
        threads = []
        for i, tenant in enumerate(tenant_order):
            th = _threading.Thread(target=_submit, args=(i, tenant),
                                   daemon=True)
            th.start()
            threads.append(th)
        # Preload completely before releasing the scheduler: fairness is
        # only observable when every tenant contends from step one.
        deadline = time.monotonic() + 15.0
        while gw.queue.depth() < total and time.monotonic() < deadline:
            time.sleep(0.01)
        if gw.queue.depth() < total:
            problems.append(f"preload stalled: queued {gw.queue.depth()}"
                            f"/{total} before resume")
        gw.resume()
        for th in threads:
            th.join(timeout=request_timeout + 90.0)

        for i in range(total):
            got = submits.get(i, {"error": "submit thread never reported"})
            if "error" in got:
                problems.append(f"request {i} failed: {got['error']}")
            elif got["tokens"] != baseline[i]:
                problems.append(
                    f"request {i}: gateway tokens {got['tokens']} != "
                    f"sequential baseline {baseline[i]}")
        result["queue_waits"] = sorted(
            round(s["queue_wait_s"], 4) for s in submits.values()
            if "queue_wait_s" in s)

        # Served-token fairness over the contended window: the step log up
        # to gold's LAST token (afterwards bronze runs uncontended).
        log = list(gw.step_log)
        result["step_log"] = "".join(t[0] for t in log)
        # Gold's total comes from the BASELINE (a stop heuristic — eos/
        # repeat — may end a session before max_new_tokens, identically in
        # both runs), so the window cut lands on gold's true last token.
        gold_total = sum(len(baseline[i])
                         for i, t in enumerate(tenant_order) if t == "gold")
        served = 0
        cut = len(log)
        for pos, tenant in enumerate(log):
            if tenant == "gold":
                served += 1
                if served == gold_total:
                    cut = pos + 1
                    break
        window = log[:cut]
        gold_served = sum(1 for t in window if t == "gold")
        bronze_served = len(window) - gold_served
        result["gold_served"] = gold_served
        result["bronze_served"] = bronze_served
        want_ratio = weights["gold"] / weights["bronze"]
        ratio = (gold_served / bronze_served if bronze_served
                 else float("inf"))
        result["ratio"] = ratio
        # +/-25% of the weight ratio, with one quantum of absolute slack:
        # the window necessarily cuts mid-rotation, and at tier-1 token
        # counts a single boundary step shifts the raw ratio past 25%.
        # Under burst serving the service quantum is a whole burst (one
        # pick = up to N tokens, charged to the DRR after the fact), so
        # the boundary slack is one burst, not one token.
        expected_bronze = gold_served / want_ratio
        if (gold_served < gold_total
                or abs(bronze_served - expected_bronze)
                > max(float(burst or 1), 0.25 * expected_bronze)):
            problems.append(
                f"served-token ratio {gold_served}:{bronze_served} "
                f"(= {ratio:.2f}) outside +/-25% of the 4:1 weights "
                f"(expected bronze ~{expected_bronze:.1f} in the window; "
                f"log {result['step_log']!r})")
        gw.stop()

        # --- phase B: typed shedding on a strict gateway ---
        strict = {
            "slow": TenantConfig("slow", rate=1000.0, burst=1000.0,
                                 max_concurrency=1),
            "bursty": TenantConfig("bursty", rate=1e-3, burst=1.0),
            "filler": TenantConfig("filler", rate=1000.0, burst=1000.0),
        }
        gw2 = GatewayServer([_client()], strict, port=0,
                            max_queue_depth=3, max_active=1,
                            start_paused=True)  # never resumed: pure gate
        gateways.append(gw2)
        gw2.start()
        sub2 = GatewaySubmitClient(gw2.address)

        def _bg(tenant):
            th = _threading.Thread(
                target=lambda: _submit_quietly(sub2, tenant), daemon=True)
            th.start()
            return th

        def _submit_quietly(cli, tenant):
            try:
                cli.submit(tenant, _variant(0), 2, timeout=30.0)
            except Exception:  # noqa: BLE001 — shutdown error expected
                pass

        def _expect_shed(tenant, want_reason):
            try:
                sub2.submit(tenant, _variant(0), 2, timeout=10.0)
                problems.append(
                    f"tenant {tenant}: expected Overloaded "
                    f"({want_reason}), request was served")
            except Overloaded as exc:
                result.setdefault("shed_reasons", {})[exc.reason] = round(
                    exc.retry_after_s, 4)
                if exc.reason != want_reason:
                    problems.append(
                        f"tenant {tenant}: shed reason {exc.reason!r}, "
                        f"wanted {want_reason!r}")
                if exc.retry_after_s <= 0:
                    problems.append(
                        f"tenant {tenant}: retry_after_s "
                        f"{exc.retry_after_s} must be > 0")

        def _wait_depth(n):
            deadline = time.monotonic() + 10.0
            while gw2.queue.depth() < n and time.monotonic() < deadline:
                time.sleep(0.01)

        bgs = [_bg("slow")]
        _wait_depth(1)
        _expect_shed("slow", "concurrency")     # inflight 1 >= cap 1
        bgs.append(_bg("bursty"))
        _wait_depth(2)
        _expect_shed("bursty", "rate")          # burst of 1 already spent
        bgs.append(_bg("filler"))
        _wait_depth(3)
        _expect_shed("filler", "queue_full")    # global watermark
        gw2.stop()                              # fails the queued waiters
        for th in bgs:
            th.join(timeout=10.0)

        # --- doctor: refusals must surface as failure chains ---
        chains = _doc.failure_chains(_doc.merge_timeline(
            [{"meta": {"pid": os.getpid()},
              "events": [ev.to_dict()
                         for ev in _events.get_recorder().events()]}]))
        result["chains"] = len(chains)
        shed_chains = [ch for ch in chains
                       if any(ev.get("event") == "request_shed"
                              for ev in ch["events"])]
        result["shed_chains"] = len(shed_chains)
        if not shed_chains:
            problems.append("doctor chains contain no request_shed trigger "
                            "(flight recorder missed the refusals)")
    finally:
        for gw_ in gateways:
            try:
                gw_.stop()
            except Exception:
                pass
        for tx in transports:
            try:
                tx.close()
            except Exception:
                pass
        for srv in servers:
            srv.stop()
        if reg_server is not None:
            reg_server.stop()
    result["problems"] = problems
    result["ok"] = not problems
    return result


def run_chaos(args, cfg: ModelConfig, params) -> int:
    from . import telemetry

    telemetry.enable()
    tokenizer = load_tokenizer(_remote_store(args).cache_dir
                               if _is_remote(args.checkpoint)
                               else args.checkpoint)
    prompt_ids = [i % cfg.vocab_size for i in tokenizer.encode(args.prompt)]
    splits = parse_splits(args.splits) if args.splits else None
    if args.chaos_scenario == "registry_loss":
        if args.chaos_attach:
            _emit("CHAOS SOAK FAIL: --chaos_scenario registry_loss boots "
                  "its own swarm (it must own the seeds it kills); drop "
                  "--chaos_attach")
            return 1
        res = registry_loss_soak(
            cfg, params, prompt_ids=prompt_ids,
            max_new_tokens=args.max_new_tokens, seed=args.seed,
            splits=splits, wire_dtype=args.wire_dtype,
            request_timeout=args.request_timeout,
            peers_cache=args.peers_cache)
        _emit(f"\n=== Registry-loss soak (seed={res['seed']}) ===")
        _emit(f"tokens (clean)     : {res.get('tokens_clean')}")
        _emit(f"tokens (chaos)     : {res.get('tokens_chaos')}")
        _emit(f"tokens (bootstrap) : {res.get('tokens_bootstrap')}")
        _emit(f"bootstrap records  : {res.get('bootstrap_records')}")
        _emit(f"failure chains     : {res.get('chains', 0)}")
        if res["ok"]:
            _emit("REGISTRY-LOSS SOAK PASS: identical tokens across total "
                  "seed loss; fresh client bootstrapped via gossip; doctor "
                  "reconstructed the outage")
            return 0
        for p in res["problems"]:
            _emit(f"REGISTRY-LOSS SOAK FAIL: {p}")
        return 1
    if args.chaos_scenario == "relay_break":
        if args.chaos_attach:
            _emit("RELAY-BREAK SOAK FAIL: --chaos_scenario relay_break "
                  "boots its own swarm (it must own the volunteer it "
                  "kills); drop --chaos_attach")
            return 1
        res = relay_break_soak(
            cfg, params, prompt_ids=prompt_ids,
            max_new_tokens=args.max_new_tokens, seed=args.seed,
            splits=splits, wire_dtype=args.wire_dtype,
            request_timeout=args.request_timeout)
        _emit(f"\n=== Relay-break soak (seed={res['seed']}) ===")
        _emit(f"tokens (clean, via relay) : {res.get('tokens_clean')}")
        _emit(f"tokens (chaos)            : {res.get('tokens_chaos')}")
        _emit(f"relay after failover      : {res.get('relay_after')}")
        _emit(f"client recoveries         : {res.get('recoveries')}")
        _emit(f"failure chains            : {res.get('chains', 0)}")
        if res["ok"]:
            _emit("RELAY-BREAK SOAK PASS: identical tokens across the "
                  "relay kill; breaker blamed the volunteer; doctor "
                  "reconstructed relay lost -> failover -> replay")
            return 0
        for p in res["problems"]:
            _emit(f"RELAY-BREAK SOAK FAIL: {p}")
        return 1
    if args.chaos_scenario == "overload":
        if args.chaos_attach:
            _emit("OVERLOAD SOAK FAIL: --chaos_scenario overload boots its "
                  "own swarm and gateway in-process; drop --chaos_attach")
            return 1
        res = overload_soak(
            cfg, params, prompt_ids=prompt_ids,
            max_new_tokens=args.max_new_tokens, seed=args.seed,
            splits=splits, wire_dtype=args.wire_dtype,
            request_timeout=args.request_timeout,
            burst=getattr(args, "burst", 0))
        _emit(f"\n=== Overload soak (seed={res['seed']}, weights 4:1"
              + (f", burst={res['burst']}" if res.get("burst") else "")
              + ") ===")
        _emit(f"served tokens (gold:bronze) : {res.get('gold_served')}:"
              f"{res.get('bronze_served')} "
              f"(ratio {res.get('ratio', 0.0):.2f})")
        _emit(f"queue waits (s)             : {res.get('queue_waits')}")
        _emit(f"shed refusals               : {res.get('shed_reasons')}")
        _emit(f"shed chains / total         : {res.get('shed_chains', 0)}"
              f" / {res.get('chains', 0)}")
        if res["ok"]:
            _emit("OVERLOAD SOAK PASS: weighted fairness held, admitted "
                  "requests matched the sequential baseline in budget, and "
                  "excess load was shed with typed retry hints")
            return 0
        for p in res["problems"]:
            _emit(f"OVERLOAD SOAK FAIL: {p}")
        return 1
    res = chaos_soak(
        cfg, params, prompt_ids=prompt_ids,
        max_new_tokens=args.max_new_tokens, seed=args.seed, splits=splits,
        wire_dtype=args.wire_dtype, request_timeout=args.request_timeout,
        registry_addr=(args.registry_addr if args.chaos_attach else None))
    _emit(f"\n=== Chaos soak (seed={res['seed']}, "
          f"{'attached' if res['attach'] else 'in-process'} swarm) ===")
    _emit(f"fault kinds fired : {', '.join(res.get('kinds_fired', []))}")
    _emit(f"firing counts     : {res.get('firings', {})}")
    _emit(f"tokens (clean)    : {res.get('tokens_clean')}")
    _emit(f"tokens (chaos)    : {res.get('tokens_chaos')}")
    _emit(f"deadline probe    : {res.get('deadline_probe', 'skipped')}")
    _emit(f"failure chains    : {res.get('fault_chains', 0)} with faults "
          f"/ {res.get('chains', 0)} total")
    if res["ok"]:
        _emit("CHAOS SOAK PASS: identical tokens under faults; doctor "
              "reconstructed every injection")
        return 0
    for p in res["problems"]:
        _emit(f"CHAOS SOAK FAIL: {p}")
    return 1


# ---------------------------------------------------------------------------
# Argparse (reference flag table, src/main.py:776-819)
# ---------------------------------------------------------------------------

def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.main",
        description="TPU-native distributed LLM inference (mini-Petals parity)",
    )
    p.add_argument("--mode",
                   choices=["local", "fused", "oracle",
                            "registry", "serve", "client", "status",
                            "metrics", "doctor", "top", "dcn-check",
                            "chaos", "gateway", "submit"],
                   default="local")
    p.add_argument("--telemetry", action="store_true",
                   help="enable the process-global metrics registry, "
                        "request tracer, and flight recorder (telemetry "
                        "package). Servers then answer the 'metrics' and "
                        "'dump-events' verbs; clients fold their series "
                        "into the same registry. Default off: every "
                        "instrument site is a cheap boolean check.")
    p.add_argument("--events-dump", dest="events_dump", default=None,
                   metavar="PATH",
                   help="enable the flight recorder and write its event "
                        "ring to PATH as JSONL on fatal exceptions, "
                        "SIGTERM/SIGINT, and normal exit — the file "
                        "--mode doctor ingests. Implies the recorder even "
                        "without --telemetry.")
    p.add_argument("--dumps", default=None, metavar="PATHS",
                   help="doctor mode: comma-separated event-dump files "
                        "(--events-dump output) to diagnose; omit to "
                        "scrape LIVE servers' event rings via the "
                        "registry instead")
    p.add_argument("--critical_path", action="store_true",
                   help="doctor mode: also assemble the client/server "
                        "spans embedded in the dumps into per-request "
                        "span trees and report the critical path, with "
                        "wall time attributed to network / queue / "
                        "compute / replay / client (the parts sum to each "
                        "request's wall time). Needs dumps from runs with "
                        "--telemetry.")
    p.add_argument("--once", action="store_true",
                   help="top mode: render one snapshot and exit instead "
                        "of refreshing (scripting / tests)")
    p.add_argument("--top_interval", type=float, default=2.0,
                   help="top mode: seconds between refreshes")
    p.add_argument("--log-json", dest="log_json", action="store_true",
                   help="emit every log record as one JSON object per "
                        "line (machine-ingestable) instead of the "
                        "structured text format")
    p.add_argument("--model", default="gpt2",
                   help="architecture preset (gpt2[-xl], llama-3-8b, ...)")
    p.add_argument("--model_name", default=None,
                   help="swarm-scoping model id for the registry (the model "
                        "name embedded in every reference DHT key, "
                        "src/dht_utils.py:20-31); defaults to --model. Two "
                        "models can share one registry without cross-routing "
                        "when every server/client passes its own name.")
    p.add_argument("--checkpoint", default=None,
                   help="local HF checkpoint dir, or an http(s):// weight "
                        "store (an HF checkpoint layout behind any static "
                        "file server) — servers then fetch ONLY the shards "
                        "covering their span; omit for random init")
    p.add_argument("--weight_cache_dir", default=None,
                   help="remote --checkpoint: local shard cache directory")
    p.add_argument("--weight_cache_bytes", type=int, default=None,
                   help="remote --checkpoint: LRU-evict cached shards "
                        "beyond this many bytes")
    p.add_argument("--splits", default=None,
                   help='stage boundaries, e.g. "10,20,30" (reference format)')
    p.add_argument("--stage", type=int, default=0,
                   help="serve mode: which pipeline stage this server runs "
                        "(1..N; stage 0 lives in the client). Other modes "
                        "run all stages in-process and ignore it.")
    p.add_argument("--dtype", choices=["float32", "bfloat16", "float16"],
                   default="float32")
    p.add_argument("--lora", default=None, metavar="PATH",
                   help="serve a fine-tune: fold the adapters saved by "
                        "DistributedFineTuner.export_lora (.npz) into the "
                        "weights at load (merged before --quant; every "
                        "mode that loads weights honors it)")
    p.add_argument("--prefix_cache_mb", type=int, default=0,
                   help="enable the content-addressed prompt-prefix KV "
                        "store with this byte budget (MiB) on session "
                        "executors: repeat prefills reuse cached KV for "
                        "shared prompt prefixes at 64-token granularity "
                        "(runtime.prefix_cache). 0 = off")
    p.add_argument("--quant", choices=["none", "int8", "nf4"], default="none",
                   help="weight-only block quantization (reference V9 "
                        "surface: int8 per-channel, nf4 4-bit NormalFloat "
                        "at 4.25 bits/param) — stage servers AND the "
                        "fused/ring/oracle engines. int8 measured +26% "
                        "decode tokens/s on a v5e; nf4 is the capacity "
                        "mode (docs/PERFORMANCE.md)")
    p.add_argument("--prompt", default="Hello, my name is")
    p.add_argument("--max_new_tokens", type=int, default=32)
    p.add_argument("--temperature", type=float, default=0.7)
    p.add_argument("--top_p", type=float, default=0.9)
    p.add_argument("--top_k", type=int, default=50)
    p.add_argument("--repetition_penalty", type=float, default=1.5)
    p.add_argument("--speculative_k", type=int, default=0,
                   help="speculative decoding: draft up to K tokens per "
                        "round trip (n-gram prompt lookup), verified by the "
                        "final stage (greedy: token-identical; temperature>0: "
                        "distribution-preserving rejection sampling)")
    p.add_argument("--request_timeout", type=float, default=60.0)
    # Host offload (reference --use_cpu_offload / --keep_layers_on_gpu,
    # src/main.py flag table): span weights in host RAM, streamed per layer.
    p.add_argument("--use_cpu_offload", action="store_true")
    p.add_argument("--keep_layers_on_gpu", type=int, default=0)
    # Load balancing (reference LB flag group)
    p.add_argument("--use_load_balancing", action="store_true")
    p.add_argument("--route_by_latency", action="store_true",
                   help="module routing minimizes estimated end-to-end step "
                        "latency (server-published next-hop RTTs + client "
                        "pings) instead of greedy max-coverage")
    p.add_argument("--num_blocks", type=int, default=None)
    p.add_argument("--total_blocks", type=int, default=None)
    p.add_argument("--num_servers", type=int, default=3)
    p.add_argument("--balance_quality", type=float, default=0.75)
    p.add_argument("--mean_balance_check_period", type=float, default=120.0)
    p.add_argument("--network_bandwidth_mbps", type=float, default=None)
    # TPU-native knobs
    p.add_argument("--num_stages", type=int, default=None,
                   help="fused mode: pipeline depth (default: #devices, <=4)")
    p.add_argument("--ring_sessions", type=int, default=0,
                   help="fused mode: serve this many CONCURRENT sessions "
                        "('||'-separated --prompt) on the multi-session "
                        "ring-decode schedule — every stage advances a "
                        "different session each tick, so steady-state "
                        "decode has no pipeline bubble (needs >= "
                        "num_stages sessions)")
    p.add_argument("--tp", type=int, default=1,
                   help="fused/serve mode: tensor parallelism per stage "
                        "(serve: the stage step is sharded over a local "
                        "('tp',) mesh of N chips)")
    # Continuous batching in the serving path (the reference's serving
    # runtime is batch-first, petals/server/server.py:557-671)
    p.add_argument("--batched", action="store_true",
                   help="serve mode: continuous slot-batched engine — "
                        "concurrent plain sessions coalesce into ONE "
                        "compiled decode step per round (speculative "
                        "draft steps coalesce too, as multi-token verify "
                        "rounds); advertised as engine=batched so clients "
                        "route plain and speculative sessions here and "
                        "beam/replay to per-session replicas")
    p.add_argument("--slots", type=int, default=8,
                   help="serve --batched: max concurrent sessions")
    p.add_argument("--max_session_len", type=int, default=2048,
                   help="serve --batched: per-slot KV capacity (tokens)")
    p.add_argument("--burst", type=int, default=0, metavar="N",
                   help="burst decode: one jitted dispatch runs N decode "
                        "ticks with on-device sampling on a FULL-span "
                        "--batched server (tokens bit-identical to per-"
                        "step decode). client mode: decode in N-token "
                        "bursts; serve --batched: pre-compile the N-tick "
                        "burst program at warmup; chaos overload: drive "
                        "the gateway at burst granularity. 0 disables")
    # Sequence-parallel long-context serving (SURVEY §5.7 exceed-the-
    # reference axis: the reference's KV must fit one machine)
    p.add_argument("--sp", type=int, default=1,
                   help="serve mode: sequence parallelism — the session's "
                        "prefix KV shards along the sequence axis of a "
                        "local ('sp',) mesh of N chips, so prompts beyond "
                        "one device's KV budget serve end-to-end; "
                        "advertised as engine=sp with --max_context")
    p.add_argument("--sp_zigzag", action="store_true",
                   help="serve --sp: zigzag sequence layout — each device "
                        "holds one early + one late half-chunk, flattening "
                        "causal-prefill work across the mesh (critical "
                        "path ~halves at sp=8); token-identical output")
    p.add_argument("--max_context", type=int, default=None,
                   help="serve --sp: advertised admission limit "
                        "(prompt+generated tokens); default 8192 per chip")
    p.add_argument("--long_context_threshold", type=int, default=None,
                   help="client mode: prompts at/above this length route "
                        "to engine=sp peers")
    # Network roles (reference --dht_port/--rpc_port/--public_ip surface,
    # src/main.py:776-819, re-homed onto the TCP registry/data plane)
    p.add_argument("--registry_addr", default="127.0.0.1:31330",
                   help="serve/client: control-plane address (the "
                        "--dht_initial_peers role). Comma-separate a "
                        "primary + standbys for registry HA: writes "
                        "broadcast to all, reads fail over, and a total "
                        "outage serves cached records under TTL grace")
    p.add_argument("--registry_port", type=int, default=31330,
                   help="registry mode: listen port (the --dht_port role)")
    p.add_argument("--peers_cache", default=None, metavar="PATH",
                   help="serve/client: persist the last-known live server "
                        "addresses to PATH (JSON) after every successful "
                        "registry read, and load them at startup as "
                        "any-peer bootstrap candidates — a fresh process "
                        "can then join the swarm through a live stage "
                        "server's gossip mirror even when EVERY "
                        "--registry_addr seed is down")
    p.add_argument("--rpc_port", type=int, default=0,
                   help="serve mode: data-plane port (0 = ephemeral)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--public_ip", default=None,
                   help="serve mode: advertise this IP instead of --host")
    p.add_argument("--relay_capacity", type=int, default=0,
                   help="serve mode: volunteer to relay traffic for up to N "
                        "NAT'd peers that fail the dial-back reachability "
                        "vote (0 = do not volunteer). Attach requests "
                        "beyond N are shed so load spreads across "
                        "volunteers")
    p.add_argument("--peer_id", default=None)
    p.add_argument("--ttl", type=float, default=45.0,
                   help="registry mode: record TTL seconds (reference 45s); "
                        "servers learn it from heartbeat responses")
    p.add_argument("--allow_fault_injection", action="store_true",
                   help="accept the `fault` admin verb: remote clients may "
                        "install/clear/inspect a deterministic FaultPlan on "
                        "this process (registry and serve roles). NEVER set "
                        "on a production swarm — it lets any client that "
                        "can dial the port inject faults")
    p.add_argument("--chaos_scenario",
                   choices=["faults", "registry_loss", "overload",
                            "relay_break"],
                   default="faults",
                   help="chaos mode: 'faults' runs the seeded fault-"
                        "injection soak; 'registry_loss' kills the primary "
                        "AND every standby registry mid-generation and "
                        "requires identical tokens plus a gossip-served "
                        "fresh-client bootstrap (in-process swarm only); "
                        "'overload' floods a two-tenant gateway and "
                        "requires weighted-fair service, baseline-identical "
                        "tokens, and typed shedding (in-process only)")
    p.add_argument("--chaos_attach", action="store_true",
                   help="chaos mode: instead of booting an in-process "
                        "swarm, attach to the externally launched one at "
                        "--registry_addr (its roles must all run with "
                        "--allow_fault_injection --telemetry; see "
                        "scripts/chaos_swarm.py)")
    # Multi-tenant serving gateway (--mode gateway / submit, docs/SERVING.md)
    p.add_argument("--tenants", default=None, metavar="JSON_OR_PATH",
                   help="gateway mode: tenant table as inline JSON (starts "
                        "with '{') or a path to a JSON file. Per tenant: "
                        "weight (fair share), rate + burst (admission "
                        "token bucket), max_concurrency; top-level "
                        "max_queue_depth / max_active set the global "
                        "watermark and the interleaving width. Omitted: "
                        "one 'default' tenant with library defaults.")
    p.add_argument("--gateway_addr", default="127.0.0.1:31340",
                   help="submit mode: the gateway's host:port "
                        "(--mode gateway prints it at startup)")
    p.add_argument("--gateway_clients", type=int, default=1,
                   help="gateway mode: number of PipelineClients the "
                        "gateway round-robins new sessions across")
    p.add_argument("--tenant", default="default",
                   help="submit mode: tenant to submit as")
    p.add_argument("--submit_requests", type=int, default=1,
                   help="submit mode: how many requests to fire "
                        "sequentially")
    p.add_argument("--queue_high_water", type=int, default=None,
                   help="serve mode: task-pool depth that fires the "
                        "`queue_pressure level=high` flight-recorder event "
                        "(stage falling behind; default 16)")
    p.add_argument("--queue_low_water", type=int, default=None,
                   help="serve mode: task-pool depth at which pressure "
                        "relaxes back to `level=normal` (default 8; must "
                        "be <= --queue_high_water)")
    p.add_argument("--deadline_s", type=float, default=None,
                   help="end-to-end wall-clock budget for the WHOLE "
                        "generation: each hop ships the seconds remaining, "
                        "servers refuse already-expired work, and "
                        "exhaustion raises DeadlineExceeded instead of "
                        "burning retries (pipeline-client modes only)")
    p.add_argument("--wire_dtype", choices=["bf16", "f32"], default="bf16",
                   help="activation compression on the wire")
    # Multi-host DCN cluster (runtime.dcn; SURVEY.md §7.1 layer 7)
    p.add_argument("--dcn_coordinator", default="127.0.0.1:31400",
                   help="dcn-check: process 0's coordinator host:port")
    p.add_argument("--num_processes", type=int, default=1,
                   help="dcn-check: cluster size")
    p.add_argument("--process_id", type=int, default=0,
                   help="dcn-check: this process's rank")
    p.add_argument("--dcn_cpu_devices", type=int, default=None,
                   help="dcn-check: force N virtual CPU devices per process "
                        "(testing without TPU hosts)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--profile", default=None, metavar="DIR",
                   help="write a jax.profiler trace of the run to DIR "
                        "(view with TensorBoard / Perfetto)")
    p.add_argument("--profile_phases", action="store_true",
                   help="enable the host-side phase profiler: per-phase "
                        "latency histograms (server_phase_seconds) over "
                        "the serving hot path and the device "
                        "bubble-fraction gauge "
                        "(server_device_bubble_ratio). Adds a fence per "
                        "collected burst; default off so the hot path "
                        "pays only a boolean check.")
    p.add_argument("-v", "--verbose", action="store_true")
    return p


def _print_swarm_health(infos: dict, total_servers: int = 0) -> None:
    """Swarm-wide aggregation of the per-server request-log rings (the
    ``info`` verb's ``recent_requests`` tail): top error peers, slowest
    hops, cache pressure — one operator surface instead of grepping N
    server logs (exceeds the reference's announcer/log story,
    ``petals/server/handler.py:549-592``, ``server.py:721-726``)."""
    if not infos:
        return
    unreachable = max(0, total_servers - len(infos))
    _emit(f"swarm health ({len(infos)}/{total_servers or len(infos)} "
          "server rings probed):")
    if unreachable:
        # An unreachable server is the LIKELIEST one erroring — never let
        # a clean aggregate of the reachable rings read as all-clear.
        _emit(f"  WARNING: {unreachable} server(s) unreachable for info — "
              "their rings are NOT included below")
    errs = []     # (count, peer, last error record)
    slows = []    # (max_dur_ms, peer, verb)
    for peer, inf in infos.items():
        recs = inf.get("recent_requests") or []
        bad = [r for r in recs if r.get("outcome") != "ok"]
        if bad:
            errs.append((len(bad), peer, bad[-1]))
        durs = [(r.get("dur_ms"), r.get("verb")) for r in recs
                if r.get("dur_ms") is not None]
        if durs:
            d, v = max(durs)
            slows.append((d, peer, v))
    if errs:
        errs.sort(reverse=True)
        for n, peer, last in errs[:3]:
            _emit(f"  errors: {peer} x{n} (last: {last.get('verb')} "
                  f"{last.get('outcome')} {last.get('detail', '')})")
    else:
        _emit(f"  errors: none in the {len(infos)} probed ring(s)")
    if slows:
        slows.sort(reverse=True)
        _emit("  slowest hops: " + ", ".join(
            f"{peer} {d:.1f}ms ({v})" for d, peer, v in slows[:3]))
    pfx = [(peer, inf["prefix_cache"]) for peer, inf in infos.items()
           if isinstance(inf.get("prefix_cache"), dict)]
    if pfx:
        hits = sum(s.get("hits", 0) for _, s in pfx)
        misses = sum(s.get("misses", 0) for _, s in pfx)
        total = hits + misses
        rate = f"{hits / total:.0%}" if total else "n/a"
        _emit(f"  prefix cache: {len(pfx)} server(s), hit rate {rate} "
              f"({hits}/{total}), "
              f"{sum(s.get('grains_reused', 0) for _, s in pfx)} grains "
              f"reused, "
              f"{sum(s.get('bytes', 0) for _, s in pfx) >> 20} MiB resident")
    pressure = [(inf.get("cache_tokens_left"), peer)
                for peer, inf in infos.items()
                if inf.get("cache_tokens_left") is not None]
    if pressure:
        lo, lo_peer = min(pressure)
        _emit(f"  cache pressure: min {lo} tokens left ({lo_peer}); "
              f"total {sum(p for p, _ in pressure)} across "
              f"{len(pressure)} server(s)")


def _status_telemetry_line(tele) -> str:
    """One-line per-server telemetry aggregate for --mode status (empty
    when the peer runs telemetry off or has served no steps yet)."""
    if not tele or not tele.get("steps_total"):
        return ""
    parts = [f"steps={tele['steps_total']}"]
    if tele.get("steps_per_s") is not None:
        parts.append(f"{tele['steps_per_s']:.1f}/s")
    if tele.get("step_p50_ms") is not None:
        parts.append(f"p50={tele['step_p50_ms']:.1f}ms")
    if tele.get("step_p95_ms") is not None:
        parts.append(f"p95={tele['step_p95_ms']:.1f}ms")
    if tele.get("cache_hit_rate") is not None:
        parts.append(f"cache_hit={tele['cache_hit_rate'] * 100:.0f}%")
    return "\n" + " " * 26 + "telemetry: " + " ".join(parts)


def run_metrics(args) -> int:
    """Prometheus-text scrape of every live server's process registry (the
    ``metrics`` verb), concatenated with per-peer comment banners — pipe to
    a file per peer or straight into promtool. Exit 1 when no server could
    be scraped."""
    from .runtime.net import RemoteRegistry, TcpTransport
    from .scheduling.registry import PlacementRegistry as _PR

    registry = RemoteRegistry(args.registry_addr, peers_cache=args.peers_cache)
    records = registry.live_servers(model=args.model_name)
    if not records:
        _emit("no live servers")
        return 1
    snap = _PR()
    for r in records:
        snap.register(r)
    tx = TcpTransport(snap, wire_dtype=args.wire_dtype)
    scraped, failed = 0, []
    try:
        for r in sorted(records, key=lambda r: (r.start_block, r.peer_id)):
            if not r.address:
                continue
            try:
                text = tx.metrics_text(r.peer_id, timeout=3.0)
            except Exception as exc:
                _emit(f"# peer {r.peer_id}: scrape failed "
                      f"({type(exc).__name__})")
                failed.append((r.peer_id, r.address,
                               f"{type(exc).__name__}: {exc}"))
                continue
            _emit(f"# ==== peer {r.peer_id} [{r.start_block},"
                  f"{r.end_block}) ====")
            if text.strip():
                _emit(text, end="" if text.endswith("\n") else "\n")
            else:
                _emit("# (telemetry disabled on this peer — "
                      "start it with --telemetry)")
            scraped += 1
    finally:
        tx.close()
    if failed:
        # A registered-but-unreachable server is an operational problem the
        # scrape must not paper over: name each one and exit non-zero so
        # cron/CI notices even when other peers answered.
        for peer, addr, err in failed:
            _emit(f"error: server {peer} at {addr} unreachable: {err}",
                  file=sys.stderr)
        return 1
    return 0 if scraped else 1


def run_status(args) -> int:
    """Swarm inspector: live records, per-block coverage summary (the
    reference's ``get_remote_module_infos`` coverage log,
    ``src/dht_utils.py:227-240``), and a per-server `info` probe."""
    from .runtime.net import RemoteRegistry, TcpTransport
    from .scheduling.registry import PlacementRegistry as _PR

    registry = RemoteRegistry(args.registry_addr, peers_cache=args.peers_cache)
    # ONE registry snapshot: records, coverage, and info-probe addressing all
    # derive from it, so the report describes a single swarm state (and the
    # registry sees one list RPC, not N+2).
    # Status shows the WHOLE swarm by default; an explicit --model_name scopes
    # the report (and its health verdict) to that model's records.
    records = registry.live_servers(model=args.model_name)
    # Control-plane degradation banner: the report below may describe a
    # mirror- or cache-served swarm view — an operator must never mistake
    # that for "seeds healthy".
    st = registry.stale_info()
    if st["seeds_down"]:
        line = (f"registry seeds DOWN for {st['seeds_down_s']:.1f}s "
                f"(every --registry_addr address unreachable)")
        if st["stale"]:
            line += (f"; serving STALE cached records for "
                     f"{st['stale_s']:.1f}s (TTL grace)")
        else:
            line += "; records served via a stage server's gossip mirror"
        _emit(line)
    if not records:
        _emit("no live servers")
        return 1
    total = args.total_blocks or max(r.end_block for r in records)
    if not args.total_blocks:
        _emit("warning: total_blocks inferred from LIVE records — dead "
              "tail-stage servers shrink it; pass --total_blocks for a "
              "reliable health check")
    _emit(f"{len(records)} live server(s); total_blocks={total}")
    snap = _PR()
    for r in records:
        snap.register(r)
    tx = TcpTransport(snap, wire_dtype=args.wire_dtype)
    infos = {}
    unreachable = []
    for r in sorted(records, key=lambda r: (r.start_block, r.peer_id)):
        extra = ""
        if r.address:
            try:
                inf = tx.info(r.peer_id, timeout=3.0)
                infos[r.peer_id] = inf
                extra = (f" served={inf.get('requests_served')}"
                         f" rtt_probe_ok")
                extra += _status_telemetry_line(inf.get("telemetry"))
            except Exception as exc:
                extra = f" info_probe_failed({type(exc).__name__})"
                unreachable.append(
                    (r.peer_id, r.address, f"{type(exc).__name__}: {exc}"))
        rtts = ("" if not r.next_server_rtts else
                " rtts=" + ",".join(f"{p}:{v * 1e3:.1f}ms"
                                    for p, v in r.next_server_rtts.items()))
        mdl = f" model={r.model}" if r.model else ""
        # Engine capability tag (session/batched/sp): the first thing an
        # operator needs to know when a request class is being refused.
        eng = (f" eng={r.engine}" if getattr(r, "engine", None)
               and r.engine != "session" else "")
        _emit(f"  {r.peer_id:24s} [{r.start_block:3d},{r.end_block:3d}) "
              f"{r.state:8s} thr={r.throughput:8.2f} "
              f"cache_left={r.cache_tokens_left}"
              f"{' FINAL' if r.final_stage else ''}{eng}{mdl}{rtts}{extra}")
    # Coverage summary: contiguous runs of equal server-count, the exact
    # shape of the reference's log (src/dht_utils.py:227-240). The
    # CLIENT-LOCAL prefix (stage 0's span, never served remotely — the
    # lb_min_block floor, src/main.py:338-339) is taken from --splits when
    # given; it is NOT inferred from live records, because "lowest live
    # span" would silently relabel a dead low-block server as client-local.
    base = parse_splits(args.splits)[0] if args.splits else 0
    cov = [sum(1 for r in records if r.start_block <= b < r.end_block)
           for b in range(total)]
    runs, start = [], base
    for b in range(base + 1, total + 1):
        if b == total or cov[b] != cov[start]:
            runs.append((start, b, cov[start]))
            start = b
    prefix = f"[0,{base}) client-local; " if base else ""
    _emit("coverage: " + prefix + ", ".join(
        f"[{a},{b})x{n}" + ("  <-- UNCOVERED" if n == 0 else "")
        for a, b, n in runs))
    _print_swarm_health(infos, total_servers=len(records))
    tx.close()
    healthy = all(n > 0 for _, _, n in runs)
    if not any(r.final_stage for r in records):
        # Catches the dead-tail case even when total_blocks was inferred:
        # a swarm with no live final stage cannot finish any request.
        _emit("no live FINAL-stage server  <-- UNHEALTHY")
        healthy = False
    if unreachable:
        # A registered server that won't answer its own info verb is not a
        # healthy swarm, whatever the coverage map says.
        for peer, addr, err in unreachable:
            _emit(f"error: server {peer} at {addr} unreachable: {err}",
                  file=sys.stderr)
        healthy = False
    return 0 if healthy else 2


def run_doctor(args) -> int:
    """Post-mortem / live diagnosis: merge per-process flight-recorder
    streams onto one timeline and report failure chains (timeout →
    failover → replay → rebalance), per-session replay cost, and metric
    anomalies. Sources: ``--dumps f1.jsonl,f2.jsonl`` (files written by
    ``--events-dump`` / crash hooks), else a LIVE scrape of every
    registered server's event ring over the ``dump-events`` verb."""
    from .telemetry import doctor as _doc

    if args.dumps:
        paths = [p.strip() for p in args.dumps.split(",") if p.strip()]
        missing = [p for p in paths if not os.path.exists(p)]
        if missing:
            _emit("error: dump file(s) not found: " + ", ".join(missing),
                  file=sys.stderr)
            return 1
        streams = _doc.load_dumps(paths)
        _emit(_doc.diagnose_streams(streams), end="")
        if args.critical_path:
            _emit(_doc.render_critical_path(
                _doc.critical_path_reports(streams)), end="")
        return 0

    from .runtime.net import RemoteRegistry, TcpTransport
    from .scheduling.registry import PlacementRegistry as _PR

    registry = RemoteRegistry(args.registry_addr, peers_cache=args.peers_cache)
    records = registry.live_servers(model=args.model_name)
    if not records:
        _emit("no live servers and no --dumps given")
        return 1
    snap = _PR()
    for r in records:
        snap.register(r)
    tx = TcpTransport(snap, wire_dtype=args.wire_dtype)
    try:
        streams = _doc.scrape_events(
            tx, [r.peer_id for r in sorted(
                records, key=lambda r: (r.start_block, r.peer_id))
                if r.address])
    finally:
        tx.close()
    if not streams:
        _emit("no event streams scraped (are servers running with "
              "--telemetry or --events-dump?)")
        return 1
    _emit(_doc.diagnose_streams(streams), end="")
    if args.critical_path:
        _emit(_doc.render_critical_path(
            _doc.critical_path_reports(streams)), end="")
    return 0


def _render_top(rows: list, source: str, gateway: Optional[dict]) -> str:
    """One ``--mode top`` frame: a whole-swarm stats table plus (when a
    gateway answered) per-tenant SLO burn rates."""
    lines = [f"swarm top — {len(rows)} server(s) (source: {source})"]
    hdr = (f"{'PEER':<14} {'SPAN':<10} {'RELAY':<10} {'TOK/S':>8} "
           f"{'QUEUE':>6} {'BRK':>4} {'CACHE%':>7} {'BUBBLE%':>8} "
           f"{'DROP%':>6} {'HOT%':>5} {'UP(S)':>8}")
    lines.append(hdr)
    lines.append("-" * len(hdr))

    def _f(stats, key, scale=1.0, fmt="{:.1f}", dash="-"):
        v = (stats or {}).get(key)
        if v is None:
            return dash
        try:
            return fmt.format(float(v) * scale)
        except (TypeError, ValueError):
            return dash

    for row in sorted(rows, key=lambda r: (r.get("start_block", 0) or 0,
                                           str(r.get("peer_id")))):
        stats = row.get("stats")
        span = f"[{row.get('start_block', '?')},{row.get('end_block', '?')})"
        # NAT'd servers show WHO forwards for them; direct ones a dash.
        relay = str(row.get("relay_via") or "-")
        lines.append(
            f"{str(row.get('peer_id', '?')):<14} {span:<10} "
            f"{relay:<10} "
            f"{_f(stats, 'tok_s'):>8} "
            f"{_f(stats, 'queue_depth', fmt='{:.0f}'):>6} "
            f"{_f(stats, 'breaker_open', fmt='{:.0f}'):>4} "
            f"{_f(stats, 'cache_hit_ratio', 100.0):>7} "
            f"{_f(stats, 'bubble_frac', 100.0):>8} "
            f"{_f(stats, 'moe_drop_frac', 100.0):>6} "
            f"{_f(stats, 'moe_hot_share', 100.0):>5} "
            f"{_f(stats, 'uptime_s', fmt='{:.0f}'):>8}")
    if gateway is not None:
        lines.append("")
        lines.append(f"gateway: queue={gateway.get('queue_depth', '?')} "
                     f"active={gateway.get('active_sessions', '?')} "
                     f"started={gateway.get('sessions_started', '?')}")
        slo = gateway.get("slo") or {}
        for tenant in sorted(slo):
            parts = ", ".join(
                f"{obj} burn={rate:.2f}"
                for obj, rate in sorted(slo[tenant].items()))
            lines.append(f"  slo {tenant}: {parts or 'no objectives'}")
    return "\n".join(lines) + "\n"


def _collect_top(args) -> Tuple[list, str, Optional[dict]]:
    """Gather one top-frame's data: per-server record+stats rows, the
    source description, and the gateway info dict (None if unreachable).

    Stats come gossip-first: dial any live server's ``swarm-stats`` verb
    and read the piggybacked digests off its mirror — that works with
    every seed registry dead (records then come from the mirror or the
    peers cache). Rows whose gossip record carries no digest fall back to
    a direct per-peer scrape."""
    from .runtime.net import RemoteRegistry, TcpTransport
    from .scheduling.registry import PlacementRegistry as _PR

    registry = RemoteRegistry(args.registry_addr, peers_cache=args.peers_cache)
    records = registry.live_servers(model=args.model_name)
    rows: dict = {}
    for r in records:
        d = {"peer_id": r.peer_id, "address": r.address,
             "start_block": r.start_block, "end_block": r.end_block,
             "relay_via": getattr(r, "relay_via", None),
             "stats": None}
        rows[r.peer_id] = d
    snap = _PR()
    for r in records:
        snap.register(r)
    tx = TcpTransport(snap, wire_dtype=args.wire_dtype)
    source = "registry (no stats publisher reachable)"
    try:
        # Any ONE live server's mirror carries the whole swarm's digests.
        for r in records:
            if not r.address:
                continue
            try:
                view = tx.swarm_stats(r.peer_id, timeout=3.0)
            except Exception:  # noqa: BLE001 — try the next peer
                continue
            source = f"gossip via {view.get('peer_id', r.peer_id)}"
            for rec in view.get("records") or ():
                pid = rec.get("peer_id")
                if not pid:
                    continue
                row = rows.setdefault(pid, {"peer_id": pid, "stats": None})
                row.setdefault("address", rec.get("address"))
                row["start_block"] = rec.get("start_block",
                                             row.get("start_block"))
                row["end_block"] = rec.get("end_block", row.get("end_block"))
                row["relay_via"] = rec.get("relay_via",
                                           row.get("relay_via"))
                if isinstance(rec.get("stats"), dict):
                    row["stats"] = rec["stats"]
            # The answering peer's own digest is fresher than its
            # (heartbeat-cadence) gossip record.
            if r.peer_id in rows and isinstance(view.get("self"), dict):
                rows[r.peer_id]["stats"] = view["self"]
            break
        # Direct-scrape fallback for rows gossip had no digest for.
        for row in rows.values():
            if row["stats"] is None and row.get("address"):
                try:
                    row["stats"] = tx.swarm_stats(
                        row["peer_id"], timeout=3.0).get("self")
                except Exception:  # noqa: BLE001 — leave the dashes
                    pass
    finally:
        tx.close()

    gateway = None
    if args.gateway_addr:
        from .serving.gateway import GatewaySubmitClient
        try:
            gateway = GatewaySubmitClient(args.gateway_addr,
                                          connect_timeout=1.0).info(
                                              timeout=2.0)
        except Exception:  # noqa: BLE001 — no gateway running is normal
            gateway = None
    return list(rows.values()), source, gateway


def run_top(args) -> int:
    """Live whole-swarm dashboard (``--mode top``): per-server tok/s,
    queue depth, breaker state, cache hit rate, device bubble fraction —
    fed by the stats digests servers piggyback on their gossip records, so
    it keeps working with every seed registry dead. ``--once`` renders a
    single frame (tests/scripts); otherwise refreshes every
    ``--top_interval`` seconds until interrupted."""
    while True:
        rows, source, gateway = _collect_top(args)
        if not rows:
            _emit("no live servers (and no usable peers cache)")
            return 1
        _emit(_render_top(rows, source, gateway), end="", flush=True)
        if args.once:
            return 0
        try:
            time.sleep(max(0.1, args.top_interval))
        except KeyboardInterrupt:
            return 0


def run_dcn_check(args) -> int:
    """Bring up this process's slot in a multi-host cluster and run the
    cross-host collective smoke tests (runtime.dcn). Run once per host at
    deployment time — the DCN analogue of the reference's reachability
    validation (petals/server/reachability.py)."""
    from .runtime import dcn

    dcn.initialize(dcn.DcnConfig(
        coordinator_address=args.dcn_coordinator,
        num_processes=args.num_processes,
        process_id=args.process_id,
        cpu_devices_per_process=args.dcn_cpu_devices,
    ))
    import jax as _jax

    got, want = dcn.sanity_check()
    ring_ok = dcn.ring_shift()
    ok = (got == want) and ring_ok
    _emit(f"DCN_CHECK process={_jax.process_index()}/{_jax.process_count()} "
          f"devices={_jax.local_device_count()}/{_jax.device_count()} "
          f"psum={got}/{want} ring={'ok' if ring_ok else 'FAIL'} "
          f"{'OK' if ok else 'FAIL'}", flush=True)
    dcn.shutdown()
    return 0 if ok else 1


def main(argv: Optional[Sequence[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    from .telemetry import setup_logging

    setup_logging(json_mode=args.log_json,
                  level=logging.DEBUG if args.verbose else logging.INFO)
    if args.telemetry:
        # Flip the process-global registry + tracer + flight recorder
        # BEFORE any component fetches metric handles; register_all()
        # inside makes even zero-valued families visible to the first
        # scrape.
        from . import telemetry

        telemetry.enable()
    if args.profile_phases:
        # After the telemetry flip so the phase histograms land in the
        # (now-enabled) process registry; works standalone too — the
        # profiler keeps its own per-phase stats and bubble accounting.
        from .telemetry.profiling import enable_phase_profiling

        enable_phase_profiling()
    if args.events_dump:
        # --events-dump alone still records: flip just the recorder (the
        # metrics registry stays off unless --telemetry asked for it) and
        # arm the crash hooks so a fatal exception or SIGTERM/SIGINT
        # leaves the dump behind for --mode doctor.
        import atexit

        from .telemetry import events as _events

        _events.get_recorder().enable()
        _events.emit("process_start", mode=args.mode, pid=os.getpid())
        reg = None
        if args.telemetry:
            from . import telemetry as _t

            reg = _t.get_registry()
        _events.install_crash_hooks(args.events_dump, registry=reg)
        # Normal exits dump too — doctor runs are not crash-only.
        atexit.register(
            lambda: _events.get_recorder().dump(args.events_dump,
                                                registry=reg))
    if args.mode == "registry":
        return run_registry(args, None, None)  # no model needed
    if args.mode == "dcn-check":
        return run_dcn_check(args)  # no model needed
    if args.mode == "status":
        return run_status(args)  # no model needed
    if args.mode == "metrics":
        return run_metrics(args)  # no model needed
    if args.mode == "doctor":
        return run_doctor(args)  # no model needed
    if args.mode == "top":
        return run_top(args)  # no model needed
    if args.mode == "submit":
        return run_submit(args)  # no weights: tokenizer + preset cfg only
    cfg, params = load_model(args)
    run = {"local": run_local, "fused": run_fused, "oracle": run_oracle,
           "serve": run_serve, "client": run_client,
           "chaos": run_chaos, "gateway": run_gateway}[args.mode]
    if args.profile:
        # SURVEY.md §5.1: the reference only had wall-clock prints; we keep
        # its metric names AND produce a real device trace.
        with jax.profiler.trace(args.profile):
            return run(args, cfg, params)
    return run(args, cfg, params)


if __name__ == "__main__":
    sys.exit(main())
