"""Stage partitioning: layer spans, stage roles, per-stage forward functions.

TPU-native counterpart of the reference's model partitioner
(``src/llama_partition.py:477-550`` and the Stage0/StageSegment/StageLast
modules at ``:76-474``): a model is cut into contiguous layer spans; the first
stage also owns the embeddings, the last also owns final-norm + lm_head, and
middle stages are pure layer segments. Instead of three nn.Module classes the
stages here are three pure functions over sliced parameter pytrees, each
independently jittable and shardable.

Span semantics match the reference CLI: ``--splits "s0,s1,s2"`` produces the
four spans [0,s0) [s0,s1) [s1,s2) [s2,L) (``src/main.py:89-94,243-278``); the
generalization to N stages is spans from consecutive boundary pairs.
"""

from __future__ import annotations

import dataclasses
import math
import re
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from .config import ModelConfig
from .transformer import (
    embed_tokens,
    init_kv_cache,
    lm_head,
    stack_forward,
)

Params = Dict[str, Any]

ROLE_STAGE0 = "stage0"
ROLE_SEGMENT = "segment"
ROLE_LAST = "last"
ROLE_FULL = "full"  # degenerate 1-stage plan: both embeddings and head


@dataclasses.dataclass(frozen=True)
class StageSpec:
    """One stage's role and layer span [start, end)."""

    index: int
    role: str
    start: int
    end: int

    @property
    def num_layers(self) -> int:
        return self.end - self.start

    @property
    def is_first(self) -> bool:
        return self.role in (ROLE_STAGE0, ROLE_FULL)

    @property
    def is_last(self) -> bool:
        return self.role in (ROLE_LAST, ROLE_FULL)


@dataclasses.dataclass(frozen=True)
class StagePlan:
    """A full partition of a model into pipeline stages."""

    num_layers: int
    stages: Tuple[StageSpec, ...]

    @property
    def num_stages(self) -> int:
        return len(self.stages)

    def __post_init__(self):
        assert self.stages, "empty plan"
        assert self.stages[0].start == 0
        assert self.stages[-1].end == self.num_layers
        for a, b in zip(self.stages, self.stages[1:]):
            assert a.end == b.start, f"non-contiguous spans: {a} -> {b}"

    @staticmethod
    def from_splits(num_layers: int, splits: Sequence[int]) -> "StagePlan":
        """Reference-CLI style boundaries. splits=[s0,s1,s2] -> 4 stages.

        Mirrors ``src/main.py:89-94`` (stage0 = layers[0:s0]) and
        ``:243-278`` (segments; last stage gets final norm + head).
        """
        bounds = [0, *splits, num_layers]
        assert all(0 < b <= num_layers for b in splits), f"bad splits {splits}"
        assert bounds == sorted(bounds), f"splits must be increasing: {splits}"
        stages = []
        n = len(bounds) - 1
        for i in range(n):
            if n == 1:
                role = ROLE_FULL
            elif i == 0:
                role = ROLE_STAGE0
            elif i == n - 1:
                role = ROLE_LAST
            else:
                role = ROLE_SEGMENT
            stages.append(StageSpec(i, role, bounds[i], bounds[i + 1]))
        return StagePlan(num_layers, tuple(stages))

    @staticmethod
    def even(num_layers: int, num_stages: int) -> "StagePlan":
        """Near-even split into num_stages spans (larger spans first)."""
        base, rem = divmod(num_layers, num_stages)
        sizes = [base + (1 if i < rem else 0) for i in range(num_stages)]
        bounds = [0]
        for s in sizes:
            bounds.append(bounds[-1] + s)
        return StagePlan.from_splits(num_layers, bounds[1:-1])


def parse_splits(splits: str) -> List[int]:
    """"10,20,30" -> [10, 20, 30] (the reference flag format)."""
    return [int(x) for x in splits.split(",") if x.strip()]


def path_name(path) -> str:
    """tree_map_with_path key path -> "a/b/c" rule-matching name."""
    parts = []
    for p in path:
        key = getattr(p, "key", None)
        if key is None:
            key = getattr(p, "idx", p)
        parts.append(str(key))
    return "/".join(parts)


def match_partition_rules(rules, params) -> Params:
    """(regex, PartitionSpec) rules -> a PartitionSpec pytree for `params`.

    The explicit-rules idiom of the big SPMD trainers: each leaf's
    "a/b/c" key path is matched against the rules IN ORDER and the first
    ``re.search`` hit wins, so specific rules go first and a catch-all
    ``(".*", P())`` closes the list (a leaf matching no rule raises —
    silent replication of a weight that should shard corrupts psum'd
    outputs). Scalar/singleton leaves are never partitioned. This is the
    single mechanism behind `parallel.tensor_parallel`'s TP and MoE
    expert-parallel layouts."""
    from jax.sharding import PartitionSpec as P

    def spec_for(path, leaf):
        shape = tuple(getattr(leaf, "shape", ()))
        if len(shape) == 0 or math.prod(shape) == 1:
            return P()
        name = path_name(path)
        for rule, spec in rules:
            if re.search(rule, name):
                return spec
        raise ValueError(f"no partition rule matches param {name!r}")

    return jax.tree_util.tree_map_with_path(spec_for, params)


def slice_stage_params(cfg: ModelConfig, params: Params, spec: StageSpec) -> Params:
    """Prune a full stacked-parameter pytree down to one stage's shard.

    Keeps layers[start:end]; embeddings only on stage0; final-norm + lm_head
    only on the last stage — the same memory-reduction pruning as reference
    ``src/llama_partition.py:506-525``. With tied embeddings the last stage
    retains ``embed.wte`` for the head projection (cf. hf_import's shard
    loading, which does the same at checkpoint-load time).
    """
    out: Params = {}
    if spec.num_layers > 0:
        out["layers"] = jax.tree.map(lambda x: x[spec.start : spec.end], params["layers"])
    if spec.is_first:
        out["embed"] = params["embed"]
    if spec.is_last:
        out["final_norm"] = params["final_norm"]
        if cfg.tie_word_embeddings:
            out["embed"] = {**out.get("embed", {}), "wte": params["embed"]["wte"]}
        else:
            out["lm_head"] = params["lm_head"]
    return out


def init_stage_kv(
    cfg: ModelConfig, spec: StageSpec, batch: int, max_len: int, dtype=jnp.float32
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    return init_kv_cache(cfg, spec.num_layers, batch, max_len, dtype)


def stage_forward(
    cfg: ModelConfig,
    spec: StageSpec,
    params: Params,
    inputs: jnp.ndarray,
    k_caches: jnp.ndarray,
    v_caches: jnp.ndarray,
    cache_len: jnp.ndarray,
    tp_axis: Optional[str] = None,
    prompts: Optional[jnp.ndarray] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Uniform stage forward, role-dispatched.

    inputs: int32 token ids [B,T] for stage0, float hidden [B,T,D] otherwise
    (the same uniform signature as the reference's three stage modules,
    ``src/llama_partition.py:99-137,222-297,391-474``). Returns
    (hidden-or-logits, new k_caches, new v_caches). Positions are derived from
    cache_len exactly like reference ``src/utils.py:40-48``.

    prompts: optional [span_layers, pre_seq, D] inference-time deep prompts
    added at each block's entry (``petals/server/block_functions.py:57-65,
    171-226`` — the ptune serving path).
    """
    if spec.is_first:
        b, t = inputs.shape
        positions = cache_len + jnp.arange(t, dtype=jnp.int32)[None, :]
        x = embed_tokens(cfg, params["embed"], inputs, positions)
    else:
        b, t, _ = inputs.shape
        positions = cache_len + jnp.arange(t, dtype=jnp.int32)[None, :]
        x = inputs

    if spec.num_layers > 0:
        x, k_caches, v_caches = stack_forward(
            cfg, params["layers"], x, positions, k_caches, v_caches, cache_len,
            tp_axis=tp_axis, prompts=prompts,
        )

    if spec.is_last:
        x = lm_head(cfg, params, x)
    return x, k_caches, v_caches


def plan_forward(
    cfg: ModelConfig,
    plan: StagePlan,
    stage_params: Sequence[Params],
    input_ids: jnp.ndarray,
    stage_kvs: Sequence[Tuple[jnp.ndarray, jnp.ndarray]],
    cache_len: jnp.ndarray,
) -> Tuple[jnp.ndarray, List[Tuple[jnp.ndarray, jnp.ndarray]]]:
    """Run all stages sequentially in one process (the correctness oracle for
    every transport: pipeline-of-stage-forwards must equal full_forward)."""
    x = input_ids
    new_kvs: List[Tuple[jnp.ndarray, jnp.ndarray]] = []
    for spec, params, (kc, vc) in zip(plan.stages, stage_params, stage_kvs):
        x, kc, vc = stage_forward(cfg, spec, params, x, kc, vc, cache_len)
        new_kvs.append((kc, vc))
    return x, new_kvs
