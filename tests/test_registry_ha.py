"""Registry HA (VERDICT r3 item 6): the registry the build uses in place of
the reference's Kademlia DHT must not be a single point of failure the way
a lone process is. `RemoteRegistry` accepts a comma-separated address list:
writes broadcast to every registry (primary + standbys), reads fail over,
and a total outage serves the last snapshot under TTL grace. The DHT being
mirrored has no SPOF at all (reference ``src/dht_utils.py:34-242``).
"""

import threading
import time

import jax
import numpy as np
import pytest

from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.models import (
    init_params,
)
from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.models.partition import (
    StagePlan,
    parse_splits,
    slice_stage_params,
)
from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.ops.sampling import (
    SamplingParams,
)
from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.runtime.client import (
    PipelineClient,
    make_server_record,
)
from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.runtime.executor import (
    StageExecutor,
)
from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.runtime.net import (
    RegistryServer,
    RemoteRegistry,
    TcpStageServer,
    TcpTransport,
)
from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.scheduling.registry import (
    ServerRecord,
)

from test_runtime_pipeline import oracle_generate, tiny_cfg


def _rec(peer, stage=1, addr="127.0.0.1:1"):
    return ServerRecord(peer_id=peer, start_block=0, end_block=4,
                        stage_index=stage, address=addr)


def test_write_broadcast_and_read_failover():
    """A record registered through the pair lands on BOTH registries; with
    the primary dead, reads fail over and writes still succeed."""
    a, b = RegistryServer(), RegistryServer()
    a.start(), b.start()
    try:
        rr = RemoteRegistry(f"{a.address},{b.address}")
        rr.register(_rec("p1"))
        assert [r.peer_id for r in a.registry.live_servers()] == ["p1"]
        assert [r.peer_id for r in b.registry.live_servers()] == ["p1"]

        a.stop()
        # read fails over to the standby
        assert [r.peer_id for r in rr.live_servers()] == ["p1"]
        # a NEW server can still join (one dead registry tolerated)
        rr.register(_rec("p2"))
        assert {r.peer_id for r in rr.live_servers()} == {"p1", "p2"}
    finally:
        b.stop()


def test_stale_cache_ttl_grace():
    """Total registry outage: the last snapshot keeps serving, and its
    records age out through the normal TTL instead of erroring."""
    a = RegistryServer(ttl=0.8)
    a.start()
    rr = RemoteRegistry(a.address)
    rr.register(_rec("p1"))
    assert [r.peer_id for r in rr.live_servers()] == ["p1"]
    a.stop()
    # grace: cached snapshot still answers
    assert [r.peer_id for r in rr.live_servers()] == ["p1"]
    # ...and decays through the record TTL rather than living forever
    time.sleep(1.0)
    assert rr.live_servers() == []


def test_heartbeat_repopulates_restarted_registry():
    """A registry that restarts empty answers known=false; the server
    heartbeat loop's re-register contract refills it within one beat."""
    a = RegistryServer()
    a.start()
    host, port = a.address.rsplit(":", 1)
    rr = RemoteRegistry(a.address)
    rec = _rec("p1")
    rr.register(rec)
    assert rr.heartbeat("p1")
    a.stop()
    a2 = RegistryServer(host=host, port=int(port))   # restarted, EMPTY
    a2.start()
    try:
        known = rr.heartbeat("p1")
        assert not known                 # the loop's re-register trigger
        rr.register(rec)                 # what every heartbeat loop does
        assert rr.heartbeat("p1")
        assert [r.peer_id for r in a2.registry.live_servers()] == ["p1"]
    finally:
        a2.stop()


def test_generation_survives_primary_registry_death():
    """The VERDICT 'Done' bar: kill the primary registry mid-generation —
    the session completes — AND a new server joins via the standby and is
    discoverable for the next generation."""
    cfg = tiny_cfg()
    params = init_params(jax.random.PRNGKey(0), cfg)
    plan = StagePlan.from_splits(cfg.num_layers, parse_splits("4"))
    spec = plan.stages[1]

    prim, standby = RegistryServer(), RegistryServer()
    prim.start(), standby.start()
    pair = f"{prim.address},{standby.address}"

    servers = []

    def add_server(peer):
        ex = StageExecutor(cfg, spec, slice_stage_params(cfg, params, spec),
                           peer_id=peer)
        srv = TcpStageServer(ex, wire_dtype="f32")
        srv.start()
        rec = make_server_record(peer, spec)
        rec.address = srv.address
        RemoteRegistry(pair).register(rec)   # the serve path's broadcast
        servers.append(srv)
        return srv

    first = add_server("ha-s1")
    registry = RemoteRegistry(pair)
    transport = TcpTransport(registry, wire_dtype="f32")
    stage0 = StageExecutor(cfg, plan.stages[0],
                           slice_stage_params(cfg, params, plan.stages[0]),
                           peer_id="client-local")
    client = PipelineClient(cfg, plan, stage0, transport, registry,
                            settle_seconds=0.0)
    try:
        rng = np.random.default_rng(0)
        prompt = [int(t) for t in rng.integers(0, cfg.vocab_size, 12)]
        sampling = SamplingParams(temperature=0.0)

        # Kill the primary shortly after generation starts.
        killer = threading.Timer(0.2, prim.stop)
        killer.start()
        got = client.generate(prompt, max_new_tokens=8,
                              sampling=sampling).tokens
        killer.join()
        ref = oracle_generate(cfg, params, prompt, 8, sampling)
        assert got == ref, "generation across the registry kill diverged"

        # New server joins via the standby (primary is gone)...
        add_server("ha-s2")
        # ...and the ORIGINAL server dies, so the next generation can only
        # complete by DISCOVERING the new one through the standby.
        first.stop()
        got2 = client.generate(prompt, max_new_tokens=8,
                               sampling=sampling).tokens
        assert got2 == ref, "post-failover generation diverged"
    finally:
        transport.close()
        for s in servers:
            s.stop()
        standby.stop()
        # prim already stopped by the timer (stop() is idempotent there).


# -- failover internals (round 5 satellites) ----------------------------------

def test_up_order_rotates_and_demotes_backed_off_registries():
    """Read-path ordering: indices rotate from the preferred start, but
    registries inside their down-backoff window sink to the end — tried
    only as a last resort until the backoff expires."""
    rr = RemoteRegistry("127.0.0.1:1,127.0.0.1:2,127.0.0.1:3",
                        timeout=0.05)
    assert rr._up_order(0) == [0, 1, 2]
    assert rr._up_order(2) == [2, 0, 1]

    rr._down_until[1] = time.monotonic() + 60.0      # 1 is backing off
    assert rr._up_order(0) == [0, 2, 1]
    assert rr._up_order(1) == [2, 0, 1]

    rr._down_until[1] = time.monotonic() - 1.0       # backoff expired
    assert rr._up_order(1) == [1, 2, 0]


def test_stale_persistent_socket_retries_fresh_not_down():
    """A registry restart leaves the client's persistent socket half-open;
    the next RPC must retry ONCE on a fresh connection instead of marking
    the (live) registry down."""
    a = RegistryServer()
    a.start()
    host, port = a.address.rsplit(":", 1)
    rr = RemoteRegistry(a.address)
    rr.register(_rec("p1"))             # caches the persistent socket
    a.stop()
    a2 = RegistryServer(host=host, port=int(port))   # restarted, EMPTY
    a2.start()
    try:
        assert rr.live_servers() == []  # stale socket -> fresh retry wins
        assert rr._down_until[0] == 0.0, "live registry marked down"
    finally:
        a2.stop()


def test_register_buffered_during_outage_flushes_on_reconnect():
    """Satellite: a register issued while EVERY registry is down is
    buffered (last record per peer) and replayed on the first successful
    reconnect — it must not silently vanish."""
    a = RegistryServer()
    a.start()
    host, port = a.address.rsplit(":", 1)
    rr = RemoteRegistry(a.address, timeout=0.5)
    a.stop()

    rr.register(_rec("p1"))             # total outage: buffered, no raise
    assert "p1" in rr._pending_register

    a2 = RegistryServer(host=host, port=int(port))
    a2.start()
    try:
        rr.live_servers()               # first success triggers the flush
        assert not rr._pending_register
        assert [r.peer_id for r in a2.registry.live_servers()] == ["p1"]
        assert [r.peer_id for r in rr.live_servers()] == ["p1"]
    finally:
        a2.stop()
