"""int8 weight-only serving (V9 parity) + quantization-aware block sizing."""

import jax
import jax.numpy as jnp
import numpy as np

from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.models import (
    init_params,
)
from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.models.partition import (
    StagePlan,
    parse_splits,
    slice_stage_params,
)
from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.models.quant import (
    QuantizedTensor,
    block_bytes,
    choose_num_blocks,
    dequant_tree,
    is_quantized,
    quantize_params,
)
from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.ops.sampling import (
    SamplingParams,
)
from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.runtime.client import (
    PipelineClient,
    make_server_record,
)
from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.runtime.executor import (
    StageExecutor,
)
from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.runtime.transport import (
    LocalTransport,
)
from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.scheduling.registry import (
    PlacementRegistry,
)

from test_runtime_pipeline import oracle_generate, tiny_cfg
from test_tensor_parallel import tiny_cfg as tp_tiny_cfg


def test_roundtrip_error_bounded():
    cfg = tiny_cfg()
    params = init_params(jax.random.PRNGKey(0), cfg)
    qp = quantize_params(params)
    assert is_quantized(qp["layers"]) and not is_quantized(params["layers"])
    deq = dequant_tree(qp["layers"])
    for orig, got in zip(jax.tree.leaves(params["layers"]),
                         jax.tree.leaves(deq)):
        scale = float(jnp.max(jnp.abs(orig)))
        np.testing.assert_allclose(np.asarray(got), np.asarray(orig),
                                   atol=scale / 100)


def test_quantized_pipeline_matches_dequantized_oracle():
    """Serving with int8 weights must be token-identical to serving with
    those SAME weights explicitly dequantized — the quantization error is in
    the weights, never in the execution path."""
    cfg = tiny_cfg()
    params = init_params(jax.random.PRNGKey(0), cfg)
    plan = StagePlan.from_splits(cfg.num_layers, parse_splits("2,4,6"))
    qfull = quantize_params({"layers": params["layers"]})
    deq_params = dict(params, layers=dequant_tree(qfull["layers"]))

    import random as _random

    transport = LocalTransport()
    registry = PlacementRegistry(rng=_random.Random(0))
    for spec in plan.stages[1:]:
        sp = quantize_params(slice_stage_params(cfg, params, spec))
        peer = f"q-s{spec.index}"
        transport.add_peer(peer, StageExecutor(cfg, spec, sp, peer_id=peer))
        registry.register(make_server_record(peer, spec))
    stage0 = StageExecutor(
        cfg, plan.stages[0],
        quantize_params(slice_stage_params(cfg, params, plan.stages[0])),
        peer_id="client-local")
    client = PipelineClient(cfg, plan, stage0, transport, registry,
                            settle_seconds=0.0)
    res = client.generate([5, 9, 23, 7, 81], max_new_tokens=6,
                          sampling=SamplingParams(temperature=0.0))
    ref = oracle_generate(cfg, deq_params, [5, 9, 23, 7, 81], 6,
                          SamplingParams(temperature=0.0))
    assert res.tokens == ref


def test_moe_router_stays_full_precision():
    cfg = tp_tiny_cfg("mixtral")
    params = init_params(jax.random.PRNGKey(0), cfg)
    qp = quantize_params(params)
    router = qp["layers"]["mlp"]["router"]
    assert not isinstance(router, QuantizedTensor)
    assert isinstance(qp["layers"]["mlp"]["wg"], QuantizedTensor)
    assert isinstance(qp["layers"]["attn"]["wq"], QuantizedTensor)
    # quantized mixtral forward runs end-to-end
    from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.models import (
        full_forward,
        init_kv_cache,
    )

    kc, vc = init_kv_cache(cfg, cfg.num_layers, 1, 16)
    ids = jnp.asarray([[1, 2, 3]], jnp.int32)
    logits, _, _ = full_forward(cfg, qp, ids, kc, vc, jnp.int32(0))
    assert logits.shape == (1, 3, cfg.vocab_size)
    assert bool(jnp.all(jnp.isfinite(logits)))


def test_quantized_offload_combo():
    """QuantizedTensor leaves survive host pinning + per-layer streaming."""
    cfg = tiny_cfg()
    params = init_params(jax.random.PRNGKey(0), cfg)
    plan = StagePlan.from_splits(cfg.num_layers, parse_splits("2,6"))
    spec = plan.stages[1]
    sp = quantize_params(slice_stage_params(cfg, params, spec))
    res = StageExecutor(cfg, spec, sp, peer_id="q")
    off = StageExecutor(cfg, spec, sp, peer_id="qo", offload=True,
                        keep_layers_resident=1)
    from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.runtime.messages import (
        StageRequest,
    )

    hid = np.random.default_rng(0).standard_normal(
        (1, 6, cfg.hidden_size)).astype(np.float32)
    a = res.forward(StageRequest(session_id="s", hidden=jnp.asarray(hid),
                                 seq_len=6, cur_len=0, is_prefill=True,
                                 max_length=16))
    b = off.forward(StageRequest(session_id="s", hidden=jnp.asarray(hid),
                                 seq_len=6, cur_len=0, is_prefill=True,
                                 max_length=16))
    np.testing.assert_allclose(np.asarray(b.hidden), np.asarray(a.hidden),
                               atol=1e-5, rtol=1e-5)


def test_block_sizing_and_auto_capacity():
    cfg = tiny_cfg()
    full = block_bytes(cfg, dtype_bytes=2)
    i8 = block_bytes(cfg, quant="int8")
    nf4 = block_bytes(cfg, quant="nf4")
    assert nf4 < i8 < full
    budget = full * 4
    assert choose_num_blocks(cfg, budget, dtype_bytes=2) <= 4
    assert choose_num_blocks(cfg, budget, quant="int8") >= \
        choose_num_blocks(cfg, budget, dtype_bytes=2)
    # clamps: never below 1, never above the model depth
    assert choose_num_blocks(cfg, 1) == 1
    assert choose_num_blocks(cfg, 1 << 40) == cfg.num_layers


def test_tp_over_quantized_params_rejected():
    """TP sharding tables are name-keyed; quantized leaves would silently
    replicate and double-count through the psum — must fail loudly."""
    import pytest
    from jax.sharding import Mesh

    from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.models.partition import (
        StagePlan as SP,
    )
    from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.parallel.tensor_parallel import (
        stage_param_specs,
    )

    cfg = tp_tiny_cfg("llama")
    params = quantize_params(init_params(jax.random.PRNGKey(0), cfg))
    with pytest.raises(NotImplementedError):
        stage_param_specs(cfg, params)


def test_block_bytes_rejects_unknown_mode():
    import pytest

    cfg = tiny_cfg()
    with pytest.raises(ValueError):
        block_bytes(cfg, quant="int4")


# ---------------------------------------------------------------------------
# NF4 (4-bit NormalFloat) execution — petals/server/block_utils.py:46 tier
# ---------------------------------------------------------------------------

def test_nf4_roundtrip_error_bounded():
    from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.models.quant import (
        NF4Tensor,
        _quantize_leaf_nf4,
    )

    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.standard_normal((128, 96)).astype(np.float32))
    q = _quantize_leaf_nf4(w)
    assert isinstance(q, NF4Tensor)
    assert q.shape == (128, 96)
    assert q.packed.shape == (64, 96) and q.packed.dtype == jnp.uint8
    assert q.scales.shape == (2, 96) and q.scales.dtype == jnp.bfloat16
    deq = np.asarray(q.dequant())
    # Worst-case NF4 snap error is half the widest level gap (~0.14) times
    # the block absmax; for N(0,1) blocks of 64 the absmax is ~2.5-3.5.
    err = np.abs(deq - np.asarray(w))
    assert float(err.max()) < 0.5
    # Mean snap error ≈ half the mid-range level gap (~0.045) x the block
    # absmax (~3 for 64 N(0,1) draws) x E[density-weighted factor] ≈ 0.07.
    assert float(err.mean()) < 0.1


def test_nf4_padding_for_odd_input_dim():
    from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.models.quant import (
        _quantize_leaf_nf4,
    )

    rng = np.random.default_rng(1)
    w = jnp.asarray(rng.standard_normal((80, 16)).astype(np.float32))  # 80 % 64 != 0
    q = _quantize_leaf_nf4(w)
    assert q.shape == (80, 16)
    deq = np.asarray(q.dequant())
    assert deq.shape == (80, 16)
    assert float(np.abs(deq - np.asarray(w)).max()) < 0.5


def test_nf4_stacked_layers_slice_and_scan():
    """NF4 leaves are pytree nodes: stacked [L, in, out] weights slice per
    layer and run under lax.scan like plain arrays."""
    from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.models.quant import (
        NF4Tensor,
        dequant_tree,
        quantize_layers,
    )

    cfg = tiny_cfg()
    params = init_params(jax.random.PRNGKey(3), cfg)
    ql = quantize_layers(params["layers"], "nf4")
    assert isinstance(ql["attn"]["wq"], NF4Tensor)
    # Sub-span slicing flattens THROUGH the pytree (executor._get_subspan
    # does jax.tree.map(lambda x: x[a:b]) with no is_leaf): the packed codes
    # and scales slice on their stacked layer axis.
    sub = jax.tree.map(lambda x: x[2:4], ql)
    assert isinstance(sub["attn"]["wq"], NF4Tensor)
    assert sub["attn"]["wq"].shape[0] == 2
    deq = dequant_tree(sub)
    want = jax.tree.map(lambda x: x[2:4], params["layers"])
    for a, b in zip(jax.tree.leaves(deq), jax.tree.leaves(want)):
        assert np.asarray(a).shape == np.asarray(b).shape


def test_nf4_pipeline_matches_dequantized_oracle():
    """Serving with NF4 weights is token-identical to serving the SAME
    weights explicitly dequantized (error lives in the weights, not the
    execution path) — the int8 contract at the 4-bit tier."""
    cfg = tiny_cfg()
    params = init_params(jax.random.PRNGKey(0), cfg)
    plan = StagePlan.from_splits(cfg.num_layers, parse_splits("2,4,6"))
    qfull = quantize_params({"layers": params["layers"]}, "nf4")
    deq_params = dict(params, layers=dequant_tree(qfull["layers"]))

    import random as _random

    transport = LocalTransport()
    registry = PlacementRegistry(rng=_random.Random(0))
    for spec in plan.stages[1:]:
        sp = quantize_params(slice_stage_params(cfg, params, spec), "nf4")
        peer = f"nf4-s{spec.index}"
        transport.add_peer(peer, StageExecutor(cfg, spec, sp, peer_id=peer))
        registry.register(make_server_record(peer, spec))
    stage0 = StageExecutor(
        cfg, plan.stages[0],
        quantize_params(slice_stage_params(cfg, params, plan.stages[0]),
                        "nf4"),
        peer_id="client-local")
    client = PipelineClient(cfg, plan, stage0, transport, registry,
                            settle_seconds=0.0)
    res = client.generate([5, 9, 23, 7, 81], max_new_tokens=6,
                          sampling=SamplingParams(temperature=0.0))
    ref = oracle_generate(cfg, deq_params, [5, 9, 23, 7, 81], 6,
                          SamplingParams(temperature=0.0))
    assert res.tokens == ref


def test_nf4_sizing_matches_4_25_bits():
    from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.models.quant import (
        params_per_block,
    )

    cfg = tiny_cfg()
    assert block_bytes(cfg, quant="nf4") == int(params_per_block(cfg) * 4.25 / 8)
    # auto-capacity fits more nf4 blocks than int8 than bf16
    budget = block_bytes(cfg, dtype_bytes=2) * 3
    assert (choose_num_blocks(cfg, budget, quant="nf4")
            >= choose_num_blocks(cfg, budget, quant="int8")
            >= choose_num_blocks(cfg, budget, dtype_bytes=2))


def test_quantized_fused_decode_matches_dequantized_fused():
    """The fused multi-step decode engine (the bench's flagship path) must
    produce the same greedy tokens whether QuantizedTensor leaves
    dequantize inside the scan or the dequantized weights are materialized
    up front — for BOTH int8 and nf4."""
    from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.models import (
        full_forward,
        init_kv_cache,
    )
    from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.runtime.fused_decode import (
        make_fused_decode,
    )

    cfg = tiny_cfg()
    params = init_params(jax.random.PRNGKey(0), cfg)
    rng = np.random.default_rng(1)
    prompt = rng.integers(0, cfg.vocab_size, 6).astype(np.int32)

    for mode in ("int8", "nf4"):
        qparams = quantize_params(params, mode)
        dparams = dequant_tree(qparams)   # materialized reference

        def run(p):
            fn = make_fused_decode(cfg, 8, 1, exact_head=True)
            kc, vc = init_kv_cache(cfg, cfg.num_layers, 1, 64)
            logits, kc, vc = full_forward(cfg, p, jnp.asarray(prompt[None]),
                                          kc, vc, jnp.int32(0))
            tok = jnp.argmax(logits[:, -1], -1).astype(jnp.int32)
            toks, _, _ = fn(p, tok, kc, vc, jnp.int32(len(prompt)),
                            jnp.int32(8))
            return [int(tok[0])] + np.asarray(toks[:, 0]).tolist()

        assert run(qparams) == run(dparams), f"{mode} fused decode diverged"


def test_quantized_batched_serving_matches_dequantized():
    """The batched serving engine (the --mode serve --batched path that a
    --quant server runs) must match its dequantized twin token-for-token."""
    from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.models.partition import (
        ROLE_FULL,
        StageSpec,
    )
    from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.runtime.batching import (
        BatchedStageExecutor,
    )

    cfg = tiny_cfg()
    params = init_params(jax.random.PRNGKey(3), cfg)
    qparams = quantize_params(params, "int8")
    dparams = dequant_tree(qparams)
    spec = StageSpec(index=0, role=ROLE_FULL, start=0, end=cfg.num_layers)
    rng = np.random.default_rng(5)
    prompts = [rng.integers(0, cfg.vocab_size, 5).astype(np.int32)
               for _ in range(2)]

    def serve(p):
        ex = BatchedStageExecutor(cfg, spec, p, slots=2, max_len=32)
        toks = {}
        for s, prompt in enumerate(prompts):
            h = ex.prefill(f"s{s}", prompt[None, :])
            toks[f"s{s}"] = [int(jnp.argmax(ex.logits(h[:, -1:])[0, -1]))]
        for _ in range(5):
            out = ex.decode_batch({
                sid: jnp.asarray([[t[-1]]], jnp.int32)
                for sid, t in toks.items()})
            for sid in toks:
                toks[sid].append(int(jnp.argmax(out[sid][0, -1])))
        return toks

    assert serve(qparams) == serve(dparams)
