#!/usr/bin/env python
"""Fail (exit 1) when telemetry catalogs and docs/OBSERVABILITY.md drift.

Covers BOTH catalogs, in both directions:

  * every metric in ``telemetry.catalog.SPEC`` must appear (backticked) in
    docs/OBSERVABILITY.md — new instrumentation cannot ship undocumented;
  * every backticked ``server_*``/``client_*``/``transport_*``/
    ``scheduler_*`` metric-shaped name in the doc must exist in the catalog
    — stale docs cannot describe metrics that no longer exist;
  * every flight-recorder event in ``telemetry.events.EVENTS`` must appear
    (backticked) in the doc's "Event log & doctor" section, and every
    backticked token in that section's event table must be a real event.

Pure stdlib + the dependency-free telemetry package (no jax import), so the
check is fast enough to run as a tier-1 test
(tests/test_metrics_documented.py).
"""

import pathlib
import re
import sys

REPO = pathlib.Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.telemetry.catalog import (  # noqa: E402
    SPEC,
    all_names,
)
from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.telemetry.events import (  # noqa: E402
    EVENTS,
    all_event_names,
)
from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.telemetry.profiling import (  # noqa: E402
    DIGEST_FIELDS,
    PHASES,
)

DOC = REPO / "docs" / "OBSERVABILITY.md"

# Backticked tokens that look like catalog metrics. The suffix alternation
# keeps prose like `server_forward` (a span name) out of scope.
_DOC_METRIC_RE = re.compile(
    r"`((?:server|client|transport|scheduler|gateway)_[a-z0-9_]+"
    r"(?:_total|_seconds|_bytes|_ratio|_sessions|_hops|_depth|_rate))`"
)

# Event names in the doc's event table: backticked first-column cells.
# Scoped to table rows (leading pipe) so prose backticks like `--mode
# doctor` or field names stay out of scope.
_DOC_EVENT_RE = re.compile(r"^\|\s*`([a-z][a-z0-9_]+)`", re.MULTILINE)


def main() -> int:
    if not DOC.exists():
        print(f"missing {DOC.relative_to(REPO)}")
        return 1
    text = DOC.read_text(encoding="utf-8")

    undocumented = [n for n in all_names() if f"`{n}`" not in text]
    unknown = sorted(
        {m for m in _DOC_METRIC_RE.findall(text) if m not in SPEC}
    )
    ev_undocumented = [n for n in all_event_names()
                       if f"`{n}`" not in text]
    ev_unknown = sorted(
        {m for m in _DOC_EVENT_RE.findall(text)
         if m not in EVENTS and m not in SPEC
         and m not in PHASES and m not in DIGEST_FIELDS}
    )
    # The profiler's phase names and the gossiped stats-digest fields are
    # operator surface too (--profile_phases histograms, --mode top
    # columns): each must appear backticked in the doc.
    prof_undocumented = [n for n in (*PHASES, *DIGEST_FIELDS)
                         if f"`{n}`" not in text]

    if undocumented:
        print("metrics in telemetry/catalog.py missing from "
              "docs/OBSERVABILITY.md:")
        for n in undocumented:
            print(f"  {n}")
    if unknown:
        print("metric names documented in docs/OBSERVABILITY.md but absent "
              "from telemetry/catalog.py:")
        for n in unknown:
            print(f"  {n}")
    if ev_undocumented:
        print("events in telemetry/events.py missing from "
              "docs/OBSERVABILITY.md:")
        for n in ev_undocumented:
            print(f"  {n}")
    if ev_unknown:
        print("event names documented in docs/OBSERVABILITY.md but absent "
              "from telemetry/events.py:")
        for n in ev_unknown:
            print(f"  {n}")
    if prof_undocumented:
        print("profiler phases / stats-digest fields (telemetry/"
              "profiling.py) missing from docs/OBSERVABILITY.md:")
        for n in prof_undocumented:
            print(f"  {n}")
    if (undocumented or unknown or ev_undocumented or ev_unknown
            or prof_undocumented):
        return 1
    print(f"ok: {len(all_names())} metrics, {len(all_event_names())} "
          f"events, {len(PHASES)} phases, and {len(DIGEST_FIELDS)} digest "
          "fields documented")
    return 0


if __name__ == "__main__":
    sys.exit(main())
