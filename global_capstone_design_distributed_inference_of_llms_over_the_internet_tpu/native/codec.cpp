// Wire codec for the TCP data plane: bf16<->fp32 payload conversion and a
// Castagnoli CRC-32C frame checksum.
//
// Role in the system: the reference serializes fp16 tensors into protobuf
// via hivemind's serializer backed by torch (+ its Go libp2p daemon); our
// multi-host transport (runtime/net.py) frames raw tensor bytes instead, and
// this small native library provides the two hot byte-level operations:
//   * halving the activation payload (fp32 host buffers -> bf16 wire bytes
//     and back) without round-tripping through numpy's scalar loops;
//   * integrity checksums per frame (WAN links corrupt; TCP's 16-bit
//     checksum is weak at these payload sizes).
// Python binds via ctypes (native/__init__.py) with a numpy fallback when
// the shared library has not been built.
//
// Build: make -C native   (g++ -O3 -shared; no external dependencies)

#include <cstddef>
#include <cstdint>
#include <cstring>

extern "C" {

// fp32 -> bf16 with round-to-nearest-even (matches XLA/TPU semantics).
void fp32_to_bf16(const float* src, uint16_t* dst, size_t n) {
  const uint32_t* bits = reinterpret_cast<const uint32_t*>(src);
  for (size_t i = 0; i < n; ++i) {
    uint32_t x = bits[i];
    // NaN: keep a quiet NaN mantissa, avoid rounding into infinity.
    if ((x & 0x7fffffffu) > 0x7f800000u) {
      dst[i] = static_cast<uint16_t>((x >> 16) | 0x0040u);
      continue;
    }
    uint32_t rounding_bias = 0x7fffu + ((x >> 16) & 1u);
    dst[i] = static_cast<uint16_t>((x + rounding_bias) >> 16);
  }
}

// bf16 -> fp32 (exact).
void bf16_to_fp32(const uint16_t* src, float* dst, size_t n) {
  uint32_t* out = reinterpret_cast<uint32_t*>(dst);
  for (size_t i = 0; i < n; ++i) {
    out[i] = static_cast<uint32_t>(src[i]) << 16;
  }
}

// CRC-32C (Castagnoli), slice-by-1 table, software implementation.
static uint32_t kCrcTable[256];
static bool table_init = false;

static void init_table() {
  const uint32_t poly = 0x82f63b78u;  // reversed Castagnoli polynomial
  for (uint32_t i = 0; i < 256; ++i) {
    uint32_t c = i;
    for (int k = 0; k < 8; ++k) {
      c = (c & 1u) ? (poly ^ (c >> 1)) : (c >> 1);
    }
    kCrcTable[i] = c;
  }
  table_init = true;
}

uint32_t crc32c(const uint8_t* data, size_t n) {
  if (!table_init) init_table();
  uint32_t crc = 0xffffffffu;
  for (size_t i = 0; i < n; ++i) {
    crc = kCrcTable[(crc ^ data[i]) & 0xffu] ^ (crc >> 8);
  }
  return crc ^ 0xffffffffu;
}

}  // extern "C"
