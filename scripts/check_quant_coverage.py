#!/usr/bin/env python
"""Fail (exit 1) when a quant format ships without bench + parity coverage.

Every format listed in ``models/quant.py::QUANT_BITS`` (except "none",
the unquantized baseline every row already is) must have:

  * a bench row: a ``quantize_params(..., "<fmt>")`` call (or the
    ``_qp(..., "<fmt>")`` alias) inside bench.py, so regressions in the
    format's serving path surface in ``BENCH_*`` numbers;
  * a parity test: a ``"<fmt>"`` quantize under tests/ whose module
    asserts token equality against a dequantized/materialized reference
    (grepped as a quantize call in a tests/test_*.py file that also
    contains a parity-style assertion);
  * an MoE-path parity test: the same, in a module that exercises the
    MoE layer stack (mentions mixtral/moe) — the sparse dispatch keeps
    expert stacks PACKED (models/moe.py ``_expert_dot``), a separate code
    path from the 2-D per-layer dequant the dense tests pin, so a format
    can regress there while every dense parity test stays green.

The format list is read from quant.py's SOURCE TEXT (regex, no import):
quant.py pulls in jax at import time and this check must stay cheap
enough to run as a tier-1 test (tests/test_quant_coverage.py).
"""

import pathlib
import re
import sys

REPO = pathlib.Path(__file__).resolve().parents[1]
QUANT = (REPO / "global_capstone_design_distributed_inference_of_llms"
         "_over_the_internet_tpu" / "models" / "quant.py")
BENCH = REPO / "bench.py"
TESTS = sorted((REPO / "tests").glob("test_*.py"))


def quant_formats(src: str) -> list:
    m = re.search(r"QUANT_BITS\s*=\s*\{(.*?)\}", src, re.S)
    if not m:
        print(f"could not find QUANT_BITS in {QUANT.relative_to(REPO)}")
        sys.exit(2)
    fmts = re.findall(r'"([a-z0-9_]+)"\s*:', m.group(1))
    return [f for f in fmts if f != "none"]


_CALL = r"(?:quantize_params|quantize_layers|_qp|_sqp)"
# Call args with one level of paren nesting allowed before the mode string
# (e.g. quantize_params(slice_stage_params(cfg, params, spec), "nf4")).
_ARGS = r"\((?:[^()]|\([^()]*\))*?"


def _quantize_calls(text: str, fmts) -> set:
    # quantize_params(x, "fmt") / quantize_layers(x, "fmt") and the local
    # aliases bench.py uses (_qp/_sqp). Mode omitted means int8 (the
    # signature default).
    called = {f for f in fmts
              if re.search(_CALL + _ARGS + '"%s"' % re.escape(f), text)}
    if re.search(_CALL + r'\(\s*[a-zA-Z_][^,")]*\)', text):
        called.add("int8")
    return called


def main() -> int:
    fmts = quant_formats(QUANT.read_text(encoding="utf-8"))
    bench_cov = _quantize_calls(BENCH.read_text(encoding="utf-8"), fmts)
    parity_cov = set()
    moe_cov = set()
    for p in TESTS:
        text = p.read_text(encoding="utf-8")
        # A parity module compares quantized serving against a dequantized
        # or materialized reference by exact equality.
        if not re.search(r"dequant|materializ", text):
            continue
        if not re.search(r"assert .*==|assert_array_equal", text):
            continue
        covered = _quantize_calls(text, fmts)
        parity_cov |= covered
        # The MoE-path requirement: the parity module must run the expert
        # stack (mixtral config / moe module), not just dense layers.
        if re.search(r"mixtral|moe", text, re.I):
            moe_cov |= covered
    failed = False
    for fmt in fmts:
        missing = []
        if fmt not in bench_cov:
            missing.append("bench row in bench.py")
        if fmt not in parity_cov:
            missing.append("parity test under tests/")
        if fmt not in moe_cov:
            missing.append("MoE-path parity test under tests/ "
                           "(mixtral/moe module)")
        if missing:
            failed = True
            print(f"quant format {fmt!r} (models/quant.py QUANT_BITS) "
                  f"lacks: {', '.join(missing)}")
    if not failed:
        print(f"ok: all {len(fmts)} quant formats have bench rows, parity "
              f"tests, and MoE-path parity tests")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
