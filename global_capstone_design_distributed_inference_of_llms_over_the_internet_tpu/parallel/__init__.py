"""Single-program parallel engines (fused pipeline, ring decode/attention,
tensor/sequence/expert parallelism).

Compat: these modules target the promoted ``jax.shard_map`` (jax >= 0.4.38).
On older jax the same function lives at ``jax.experimental.shard_map``; graft
it onto the jax namespace here — every ``parallel.*`` import runs through
this package first, so both the ``jax.shard_map`` attribute uses and
``from jax import shard_map`` resolve on either version. Call sites only use
the kwargs common to both (mesh/in_specs/out_specs).
"""

import functools

import jax

if not hasattr(jax, "shard_map"):
    from jax.experimental.shard_map import shard_map as _shard_map

    # check_rep=False: the old replication checker has no rule for
    # while/fori_loop bodies (the engines' tick loops) and the new vma
    # annotations it would want (pcast) don't exist here; disabling it is
    # the jax-documented workaround and does not change computed values.
    jax.shard_map = functools.partial(_shard_map, check_rep=False)

if not hasattr(jax.lax, "pcast"):
    # ``pcast(x, axes, to="varying")`` is a varying-manual-axes TYPE
    # annotation (new-jax check_vma); old shard_map's check_rep infers
    # replication itself, so the value-level identity is exact.
    def _pcast(x, axis_name, to=None):
        del axis_name, to
        return x

    jax.lax.pcast = _pcast
