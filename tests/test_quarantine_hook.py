"""Meta-test: the parity-flake quarantine machinery itself.

tests/conftest.py's ``pytest_runtest_protocol`` reruns a failed
``parity``-marked test once, in-process — load-induced host corruption
(the documented test_batching.py flake) passes the rerun and the suite
stays green-and-trustworthy; a real logic bug fails both runs and the
suite stays red. This canary FAILS ITS FIRST CALL by construction, so a
full-suite run proves the rerun path executes (expect one loud
"PARITY RERUN" warning naming this test — that warning is this test's
success signature, not a problem).
"""

import pytest

_calls = {"recover": 0, "plain": 0}


@pytest.mark.parity
def test_parity_quarantine_canary_recovers_on_rerun():
    _calls["recover"] += 1
    if _calls["recover"] == 1:
        raise AssertionError(
            "synthetic first-attempt corruption (the quarantine hook must "
            "rerun this test; if you see this as a FAILURE the hook is "
            "broken)")
    assert _calls["recover"] == 2


def test_unmarked_tests_do_not_rerun(request):
    # The hook must scope to the parity marker: an unmarked test runs the
    # default protocol exactly once.
    _calls["plain"] += 1
    assert _calls["plain"] == 1
    assert request.node.get_closest_marker("parity") is None
