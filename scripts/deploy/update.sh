#!/usr/bin/env bash
# Auto-update: pull the repo and restart the server services when upstream
# moved — the reference's auto-update unit pair (deploy playbook) as one
# idempotent script, safe to run from cron or a systemd timer.
set -euo pipefail

REPO="$(cd "$(dirname "$0")/../.." && pwd)"
cd "$REPO"

git fetch --quiet
local_rev="$(git rev-parse @)"
remote_rev="$(git rev-parse '@{u}' 2>/dev/null || echo "$local_rev")"
if [ "$local_rev" = "$remote_rev" ]; then
    echo "[update.sh] up to date at ${local_rev:0:12}"
    exit 0
fi
echo "[update.sh] updating ${local_rev:0:12} -> ${remote_rev:0:12}"
git merge --ff-only '@{u}'

# Restart managed services if systemd runs them; bare serve.sh loops pick up
# the new code on their next crash-restart cycle (or SIGHUP them manually).
if command -v systemctl >/dev/null 2>&1; then
    for unit in mpt-server mpt-registry; do
        if systemctl is-active --quiet "$unit" 2>/dev/null; then
            echo "[update.sh] restarting $unit"
            systemctl restart "$unit"
        fi
    done
fi
