"""Prioritized task scheduling for a stage server.

TPU-native counterpart of the vendored Petals scheduling pieces:

  * ``petals/server/task_pool.py:17-167`` — ``Task`` (priority, submit time,
    future, args) and ``PrioritizedTaskPool`` (handlers submit, a runtime
    drains in priority order, with a max-batch-size admission guard);
  * ``petals/server/task_prioritizer.py:6-20`` — the pluggable QoS policy
    (``DummyTaskPrioritizer``: inference outranks forward/backward);
  * the hivemind ``Runtime`` loop the reference's ``ModuleContainer`` runs
    (``petals/server/server.py:557-671``): ONE compute thread owns the
    accelerator and repeatedly executes the most urgent task across all pools.

The reference spreads this machinery across processes (mp.SimpleQueue from
handler processes into a runtime process); here handler threads and the
compute thread share one process per stage host, so the cross-process future
plumbing collapses to ``concurrent.futures.Future`` + one ``heapq`` per pool —
same semantics, no pipes. Keeping a SINGLE compute thread is not incidental:
executor steps donate their KV buffers (``executor.py`` ``donate_argnums``),
so two threads stepping the same session concurrently would race on donated
buffers; the runtime serializes all device work per stage host the way the
reference's Runtime serializes all CUDA work per GPU.
"""

from __future__ import annotations

import dataclasses
import heapq
import itertools
import logging
import threading
from concurrent.futures import Future
from typing import Any, Callable, Dict, Optional, Tuple

from ..telemetry import catalog as _tm
from ..telemetry import events as _ev
from .errors import register as _catalog

logger = logging.getLogger(__name__)

# Task kinds, mirroring the three pools each backend owns
# (petals/server/backend.py:53-63).
KIND_INFERENCE = "inference"
KIND_FORWARD = "forward"
KIND_BACKWARD = "backward"
KINDS = (KIND_INFERENCE, KIND_FORWARD, KIND_BACKWARD)


@_catalog
class TaskRejected(RuntimeError):
    """The pool refused the task (oversized, or the runtime is stopped).

    ``permanent=True`` marks rejections that can NEVER succeed on any
    retry or replacement peer (an oversized task stays oversized), so the
    wire layer can surface them as typed non-retryable errors instead of
    burning the client's retry budget. Transient rejections (runtime
    stopping during shutdown) stay retryable — failover to a replacement
    server is exactly the right response to those."""

    def __init__(self, message: str, permanent: bool = False):
        super().__init__(message)
        self.permanent = permanent


class TaskPrioritizerBase:
    """QoS policy hook (``petals/server/task_prioritizer.py:6-13``). Lower
    values are MORE urgent."""

    def prioritize(self, kind: str, size: int, **kwargs: Any) -> float:
        raise NotImplementedError


class DummyTaskPrioritizer(TaskPrioritizerBase):
    """Default policy (``task_prioritizer.py:15-20``): interactive inference
    steps outrank fine-tuning forward/backward batches."""

    def prioritize(self, kind: str, size: int, **kwargs: Any) -> float:
        if kind == KIND_INFERENCE:
            # The serving gateway stamps a per-tenant priority on inference
            # steps (StageRequest.priority, lower = more urgent); without a
            # gateway the reference's constant applies.
            priority = kwargs.get("priority")
            return float(priority) if priority is not None else 1.0
        return 2.0


@dataclasses.dataclass(order=True)
class Task:
    """One unit of device work. Orders by (priority, seq): FIFO within a
    priority level — `seq` is a monotonic submission counter, which both
    breaks ties deterministically and spares comparing the payload."""

    priority: float
    seq: int
    size: int = dataclasses.field(compare=False)
    fn: Callable[..., Any] = dataclasses.field(compare=False)
    args: Tuple[Any, ...] = dataclasses.field(compare=False)
    future: Future = dataclasses.field(compare=False)


class PrioritizedTaskPool:
    """One kind's submission queue (``task_pool.py:29-167``).

    `max_batch_size` bounds a single task's token count — oversized work must
    be chunked by the caller (the size guard of ``task_pool.py:103-106``;
    chunking itself lives in ``StageExecutor`` chunked prefill).
    """

    # Pressure hysteresis: `queue_pressure level=high` fires when the queue
    # depth reaches the high water mark, `level=normal` once it drains back
    # below the low mark — the flight-recorder signal that a stage fell
    # behind. Class attrs are the defaults; operators override per server
    # via --queue_high_water/--queue_low_water.
    HIGH_WATER = 16
    LOW_WATER = 8

    def __init__(self, name: str, max_batch_size: int = 8192,
                 high_water: Optional[int] = None,
                 low_water: Optional[int] = None):
        self.name = name
        self.max_batch_size = max_batch_size
        self.high_water = self.HIGH_WATER if high_water is None else high_water
        self.low_water = self.LOW_WATER if low_water is None else low_water
        if self.low_water > self.high_water:
            raise ValueError(
                f"pool {name}: low_water {self.low_water} must not exceed "
                f"high_water {self.high_water}")
        self._heap: list[Task] = []
        self._lock = threading.Lock()
        self._pressured = False

    def submit(self, task: Task) -> None:
        if task.size > self.max_batch_size:
            _ev.emit("task_rejected", pool=self.name,
                     reason=f"size {task.size} > max_batch_size "
                            f"{self.max_batch_size}")
            raise TaskRejected(
                f"pool {self.name}: task of size {task.size} exceeds "
                f"max_batch_size {self.max_batch_size}",
                permanent=True,
            )
        with self._lock:
            heapq.heappush(self._heap, task)
            depth = len(self._heap)
            crossed = not self._pressured and depth >= self.high_water
            if crossed:
                self._pressured = True
        _tm.get("server_task_queue_depth").labels(pool=self.name).set(depth)
        if crossed:
            _ev.emit("queue_pressure", pool=self.name, level="high",
                     depth=depth)

    def pop(self) -> Optional[Task]:
        with self._lock:
            task = heapq.heappop(self._heap) if self._heap else None
            depth = len(self._heap)
            relaxed = self._pressured and depth < self.low_water
            if relaxed:
                self._pressured = False
        if task is not None:
            _tm.get("server_task_queue_depth").labels(
                pool=self.name).set(depth)
        if relaxed:
            _ev.emit("queue_pressure", pool=self.name, level="normal",
                     depth=depth)
        return task

    def peek_key(self) -> Optional[Tuple[float, int]]:
        """Pool priority = its most urgent task (``task_pool.py:159-167``)."""
        with self._lock:
            if not self._heap:
                return None
            t = self._heap[0]
            return (t.priority, t.seq)

    def __len__(self) -> int:
        with self._lock:
            return len(self._heap)


class StageRuntime:
    """The per-stage compute loop: drain all pools strictly most-urgent-first.

    Handlers call `submit(kind, fn, *args)` and block on the returned Future;
    the single runtime thread executes tasks one at a time. `run_once()` is
    the deterministic test surface (execute exactly one task, on the calling
    thread); `start()`/`stop()` run the background loop for real serving.
    """

    def __init__(
        self,
        prioritizer: Optional[TaskPrioritizerBase] = None,
        max_batch_size: int = 8192,
        high_water: Optional[int] = None,
        low_water: Optional[int] = None,
    ):
        self.prioritizer = prioritizer or DummyTaskPrioritizer()
        self.pools: Dict[str, PrioritizedTaskPool] = {
            kind: PrioritizedTaskPool(kind, max_batch_size,
                                      high_water=high_water,
                                      low_water=low_water)
            for kind in KINDS
        }
        self._seq = itertools.count()
        self._work = threading.Semaphore(0)
        self._stop = threading.Event()
        # Serializes submit's stopped-check+push against stop's
        # flag-set+drain: without it a task pushed in that window would never
        # be popped and its waiter would hang for its full timeout.
        self._submit_lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self.tasks_done = 0

    # -- submission ---------------------------------------------------------

    def submit(self, kind: str, fn: Callable[..., Any], *args: Any,
               size: int = 1, **priority_kwargs: Any) -> Future:
        if kind not in self.pools:
            _ev.emit("task_rejected", pool=kind, reason="unknown task kind")
            raise TaskRejected(f"unknown task kind {kind!r}")
        priority = self.prioritizer.prioritize(kind, size, **priority_kwargs)
        task = Task(priority=priority, seq=next(self._seq), size=size,
                    fn=fn, args=args, future=Future())
        with self._submit_lock:
            if self._stop.is_set():
                _ev.emit("task_rejected", pool=kind,
                         reason="runtime is stopped")
                raise TaskRejected("runtime is stopped")
            self.pools[kind].submit(task)
        self._work.release()
        return task.future

    def call(self, kind: str, fn: Callable[..., Any], *args: Any,
             size: int = 1, timeout: Optional[float] = None,
             **priority_kwargs: Any) -> Any:
        """Submit and wait — the handler-thread convenience path. On timeout
        the task is cancelled (a no-op if already running) so abandoned work
        does not keep occupying the compute thread."""
        fut = self.submit(kind, fn, *args, size=size, **priority_kwargs)
        try:
            return fut.result(timeout)
        except TimeoutError:
            fut.cancel()
            raise

    # -- execution ----------------------------------------------------------

    def _next_task(self) -> Optional[Task]:
        best_pool, best_key = None, None
        for pool in self.pools.values():
            key = pool.peek_key()
            if key is not None and (best_key is None or key < best_key):
                best_pool, best_key = pool, key
        return best_pool.pop() if best_pool is not None else None

    def run_once(self) -> bool:
        """Execute the single most urgent task. Returns False when idle."""
        task = self._next_task()
        if task is None:
            return False
        if not task.future.set_running_or_notify_cancel():
            return True  # cancelled while queued
        try:
            task.future.set_result(task.fn(*task.args))
        except BaseException as exc:  # noqa: BLE001 — deliver to the waiter
            task.future.set_exception(exc)
        self.tasks_done += 1
        return True

    def _loop(self) -> None:
        while True:
            self._work.acquire()
            if self._stop.is_set():
                return
            try:
                self.run_once()
            except Exception:  # pragma: no cover — run_once traps task errors
                logger.exception("runtime task crashed")

    def start(self) -> None:
        if self._thread is not None:
            if self._thread.is_alive():
                # A second compute thread would break the donation-safety
                # invariant (two threads stepping donated KV buffers).
                return
            self._thread = None  # exited after a timed-out stop(); restart
        with self._submit_lock:
            self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="stage-runtime")
        self._thread.start()

    def stop(self) -> None:
        with self._submit_lock:
            self._stop.set()
        self._work.release()  # wake the loop
        thread = self._thread
        if thread is not None:
            thread.join(timeout=5.0)
            if thread.is_alive():
                # Wedged in a long task (e.g. a slow first compile). Keep the
                # handle so start() cannot spawn a second compute thread; the
                # loop exits at its next wakeup since the stop flag is set.
                logger.warning("runtime thread still busy after 5s; "
                               "it will exit after the current task")
            else:
                self._thread = None
        # Fail queued work rather than leaving waiters hanging forever.
        for pool in self.pools.values():
            while True:
                task = pool.pop()
                if task is None:
                    break
                if task.future.set_running_or_notify_cancel():
                    task.future.set_exception(TaskRejected("runtime stopped"))

    def queue_depths(self) -> Dict[str, int]:
        return {kind: len(pool) for kind, pool in self.pools.items()}
