"""Client-side token drafting for speculative decoding.

The reference pays one WAN round trip per generated token — its dominant
latency term (SURVEY.md §3.2 hot loop 2). Speculative decoding amortizes it:
the client drafts K candidate tokens, ships them through the pipeline as ONE
multi-token step, and the final stage greedily verifies them against the real
model (executor.forward draft path), returning up to K+1 tokens per round
trip.

The default drafter is **prompt-lookup (n-gram) drafting**: propose the K
tokens that followed the most recent earlier occurrence of the current
suffix n-gram. It needs no extra weights — crucial here, because the client
only holds stage0 of the model — and does well exactly where autoregressive
decoding is most wasteful (repetitive spans, quoted context, code). When no
n-gram matches, the round degrades to a normal single-token step.

Pluggable: `PipelineClient.generate(draft_fn=...)` accepts anything with
this signature, e.g. a small full draft model run client-side.
"""

from __future__ import annotations

from typing import Sequence, Tuple


def ngram_draft(context: Sequence[int], k: int, *,
                max_ngram: int = 3, min_ngram: int = 1) -> Tuple[int, ...]:
    """Draft up to ``k`` tokens by prompt lookup.

    Finds the longest suffix n-gram (``max_ngram`` down to ``min_ngram``)
    with an earlier occurrence in ``context`` and returns the tokens that
    followed its MOST RECENT occurrence. Returns () when nothing matches
    (caller falls back to a plain decode step).
    """
    if k <= 0 or len(context) < min_ngram + 1:
        return ()
    ctx = list(context)
    n_ctx = len(ctx)
    for n in range(min(max_ngram, n_ctx - 1), min_ngram - 1, -1):
        suffix = ctx[-n:]
        # Scan right-to-left for the most recent earlier occurrence: recent
        # matches predict the continuation better than distant ones.
        for start in range(n_ctx - n - 1, -1, -1):
            if ctx[start:start + n] == suffix:
                follow = ctx[start + n:start + n + k]
                if follow:
                    return tuple(int(t) for t in follow)
    return ()
