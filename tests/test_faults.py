"""Deterministic chaos layer, circuit breakers, and deadline budgets.

Five concerns:

  * FaultPlan semantics — rule validation, seeded reproducibility, the
    to_dict/from_dict wire round trip;
  * every fault kind round-trips through its REAL hook: client dial/send,
    server send/dispatch, registry dispatch — over real TCP sockets;
  * the `fault` admin verb — install/report/clear over the wire, and the
    --allow_fault_injection consent gate refusing unconsented processes;
  * runtime hardening — the per-peer circuit breaker state machine (driven
    by an injected clock, no sleeps), the route-cache LRU affinity
    exemption, the LoRA capability gate, and deadline expiry as a TYPED
    non-retryable error on both the client and server side;
  * the acceptance e2e: the in-process chaos soak — clean run vs seeded
    FaultPlan run must emit IDENTICAL tokens while >= 5 fault kinds fire,
    and the doctor must reconstruct every injection from the event ring.
    (The full multi-process variant rides scripts/chaos_swarm.py and is
    marked slow.)
"""

import pathlib
import subprocess
import sys

import jax
import jax.numpy as jnp
import pytest

from test_runtime_pipeline import build_cluster, tiny_cfg

from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.main import (
    chaos_soak,
)
from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.models import (
    init_params,
)
from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.models.partition import (
    StagePlan,
    parse_splits,
    slice_stage_params,
)
from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.runtime.client import (
    CircuitBreaker,
    make_server_record,
)
from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.runtime.executor import (
    StageExecutionError,
    StageExecutor,
)
from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.runtime.faults import (
    FaultPlan,
    FaultRule,
    default_chaos_rules,
)
from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.runtime.messages import (
    StageRequest,
)
from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.runtime.net import (
    RegistryServer,
    RemoteRegistry,
    TcpStageServer,
    TcpTransport,
)
from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.runtime.transport import (
    DeadlineExceeded,
)

REPO = pathlib.Path(__file__).resolve().parents[1]


# -- FaultPlan semantics ------------------------------------------------------

def test_fault_rule_validation():
    with pytest.raises(ValueError):
        FaultRule("not_a_kind")
    with pytest.raises(ValueError):
        FaultRule("delay", side="martian")


def test_seeded_plans_reproducible_and_wire_roundtrip():
    rules = [FaultRule("delay", prob=0.3, times=1000, delay_s=0.0)]

    def firing_pattern(plan):
        return [plan.fire("send", ("delay",), side="client", peer="p",
                          verb="v") is not None for _ in range(64)]

    a = firing_pattern(FaultPlan(rules, seed=7))
    b = firing_pattern(FaultPlan(rules, seed=7))
    assert a == b and any(a) and not all(a)
    # A different seed draws a different probabilistic schedule.
    assert a != firing_pattern(FaultPlan(rules, seed=8))
    # from_dict(to_dict()) is behavior-preserving: the remote end of the
    # `fault` verb replays the exact schedule the operator declared.
    wired = FaultPlan.from_dict(FaultPlan(rules, seed=7).to_dict())
    assert firing_pattern(wired) == a


def test_default_chaos_rules_cover_every_side():
    rules = default_chaos_rules(["p0", "p1", "p2"], seed=0)
    assert {r.side for r in rules} == {"client", "server", "registry"}
    assert len({r.kind for r in rules}) == 7


# -- every fault kind through its real TCP hook -------------------------------

@pytest.fixture(scope="module")
def mini():
    """One registry + one stage server (both fault-consenting), real TCP."""
    cfg = tiny_cfg()
    params = init_params(jax.random.PRNGKey(0), cfg)
    plan = StagePlan.from_splits(cfg.num_layers, parse_splits("4"))
    reg_server = RegistryServer(allow_fault_injection=True)
    reg_server.start()
    spec = plan.stages[1]
    ex = StageExecutor(cfg, spec, slice_stage_params(cfg, params, spec),
                       peer_id="fault-s1")
    srv = TcpStageServer(ex, wire_dtype="f32", allow_fault_injection=True)
    srv.start()
    rec = make_server_record(ex.peer_id, spec)
    rec.address = srv.address
    reg_server.registry.register(rec)
    reg = RemoteRegistry(reg_server.address)
    yield {"cfg": cfg, "plan": plan, "reg": reg, "reg_server": reg_server,
           "srv": srv, "ex": ex, "peer": ex.peer_id, "rec": rec}
    srv.stop()
    reg_server.stop()


@pytest.mark.parametrize("kind,recovers_inline", [
    ("refuse_connect", False),
    ("reset_mid_frame", False),
    ("corrupt_payload", False),
    ("partial_write_stall", True),
    ("delay", True),
])
def test_client_side_kinds_fire_once_then_clear(mini, kind, recovers_inline):
    tx = TcpTransport(mini["reg"], wire_dtype="f32")
    plan = FaultPlan([FaultRule(kind, side="client", peer=mini["peer"],
                                nth=1, delay_s=0.01)])
    tx.set_fault_plan(plan)
    try:
        if recovers_inline:
            # Latency-only faults: the call still completes.
            assert tx.info(mini["peer"])["verb"] == "info"
        else:
            with pytest.raises((ConnectionError, OSError, TimeoutError)):
                tx.info(mini["peer"])
        assert plan.fired_count() == 1
        assert plan.report()[0]["kind"] == kind
        # One-shot (times=1): the next call sails through untouched.
        assert tx.info(mini["peer"])["verb"] == "info"
        assert plan.fired_count() == 1
    finally:
        tx.set_fault_plan(None)
        tx.close()


@pytest.mark.parametrize("kind", ["corrupt_payload", "accept_hang"])
def test_server_side_kinds_installed_over_the_wire(mini, kind):
    tx = TcpTransport(mini["reg"], wire_dtype="f32")
    try:
        tx.install_fault_plan(mini["peer"], FaultPlan(
            [FaultRule(kind, side="server", nth=1, delay_s=0.01)]))
        with pytest.raises((ConnectionError, OSError, TimeoutError)):
            tx.info(mini["peer"])
        assert tx.info(mini["peer"])["verb"] == "info"
        rep = tx.fault_report(mini["peer"])
        assert [f["kind"] for f in rep] == [kind]
        tx.install_fault_plan(mini["peer"], None)
        assert tx.fault_report(mini["peer"]) == []
    finally:
        tx.close()


def test_fault_verb_refused_without_consent(mini):
    # A second listener sharing the executor but WITHOUT the consent flag:
    # the verb must refuse, not install.
    gated = TcpStageServer(mini["ex"], wire_dtype="f32")
    gated.start()
    rec = make_server_record("gated-peer", mini["plan"].stages[1])
    rec.address = gated.address
    mini["reg_server"].registry.register(rec)
    tx = TcpTransport(mini["reg"], wire_dtype="f32")
    try:
        with pytest.raises(RuntimeError, match="fault injection disabled"):
            tx.install_fault_plan("gated-peer", FaultPlan(
                [FaultRule("delay", side="server", nth=1)]))
    finally:
        tx.close()
        gated.stop()
        mini["reg_server"].registry.unregister("gated-peer")


def test_registry_side_duplicate_and_stale(mini):
    reg = mini["reg"]
    reg._rpc({"verb": "fault", "plan": FaultPlan([
        FaultRule("duplicate", side="registry", verb="heartbeat", times=2),
        FaultRule("stale_registry", side="registry", verb="list", nth=1,
                  age_s=1000.0),
    ]).to_dict()})
    try:
        # duplicate: the verb is processed TWICE per frame — proving the
        # registry's verbs are idempotent under at-least-once delivery.
        assert reg.heartbeat(mini["peer"]) is True
        assert reg.heartbeat(mini["peer"]) is True
        # stale_registry: freshness rewound 1000 s >> ttl, the record
        # vanishes from the live view — a lagging/partitioned registry.
        assert reg.live_servers() == []
        firings = reg._rpc({"verb": "fault", "action": "report"})["firings"]
        assert sorted({f["kind"] for f in firings}) == [
            "duplicate", "stale_registry"]
        assert sum(f["kind"] == "duplicate" for f in firings) == 2
    finally:
        reg._rpc({"verb": "fault", "action": "clear"})
        mini["reg_server"].registry.register(mini["rec"])  # re-freshen
    assert [r.peer_id for r in reg.live_servers()] == [mini["peer"]]


# -- circuit breaker state machine (injected clock, no sleeps) ----------------

def test_breaker_opens_probes_and_readmits():
    t = [0.0]
    br = CircuitBreaker(threshold=3, base_backoff_s=1.0, jitter=0.0,
                        now=lambda: t[0])
    for _ in range(2):
        br.record_failure("p")
    assert br.state("p") == "closed" and br.allow("p")
    br.record_failure("p")
    assert br.state("p") == "open"
    assert not br.allow("p")                 # backoff pending: dial skipped
    t[0] = 1.01
    assert br.allow("p")                     # the half-open single probe
    assert br.state("p") == "half_open"
    assert not br.allow("p")                 # no probe stampede
    br.record_success("p")                   # probe succeeded
    assert br.state("p") == "closed"         # full readmission, no
    assert br.allow("p")                     # blacklist clear needed


def test_breaker_failed_probe_doubles_backoff():
    t = [0.0]
    br = CircuitBreaker(threshold=3, base_backoff_s=1.0, jitter=0.0,
                        now=lambda: t[0])
    for _ in range(3):
        br.record_failure("p")
    t[0] = 1.01
    assert br.allow("p")
    br.record_failure("p")                   # probe failed -> re-open
    assert br.state("p") == "open"
    t[0] = 1.01 + 1.5
    assert not br.allow("p")                 # 2nd backoff is 2.0 s
    t[0] = 1.01 + 2.01
    assert br.allow("p")


# -- route-cache LRU: affinity=None keys are exempt ---------------------------

def test_route_cache_evicts_only_affinity_keys():
    client, *_ = build_cluster(tiny_cfg(), splits="4")
    client.route()                           # (plain, None, None) fallback
    client.route(min_context=128)            # a second exempt fallback
    for i in range(80):                      # unbounded digest churn
        client.route(affinity=f"digest-{i}")
    assert len(client._routes) <= 64
    assert ("plain", None, None) in client._routes
    assert ("plain", 128, None) in client._routes
    # Only affinity-carrying keys paid eviction.
    assert sum(1 for k in client._routes if k[2] is None) == 2


# -- LoRA capability gate -----------------------------------------------------

def test_lora_train_call_rejected_before_shipping(mini):
    tx = TcpTransport(mini["reg"], wire_dtype="f32")
    try:
        # A successful info probe that LACKS the capability blocks the call
        # before any adapter bytes hit the wire.
        tx._peer_caps[mini["peer"]] = {"verb": "info", "version": 1,
                                       "lora": False}
        req = StageRequest(session_id="lora-gate",
                           hidden=jnp.zeros((1, 1, mini["cfg"].hidden_size)),
                           seq_len=1, cur_len=0, is_prefill=True,
                           max_length=8, train=True,
                           lora={"wq": {"a": None, "b": None}})
        with pytest.raises(StageExecutionError, match="does not advertise"):
            tx.call(mini["peer"], req)
        # The real server DOES advertise it: probe and confirm the flag.
        tx._peer_caps.pop(mini["peer"])
        caps = tx._capabilities(mini["peer"])
        assert caps and caps.get("lora") is True
    finally:
        tx.close()


# -- deadline budgets ---------------------------------------------------------

def test_deadline_expired_is_typed_and_non_retryable():
    client, *_ = build_cluster(tiny_cfg(), splits="4")
    with pytest.raises(DeadlineExceeded) as ei:
        client.generate([1, 2, 3], 4, deadline_s=1e-9)
    assert not isinstance(ei.value, (ConnectionError, TimeoutError))


def test_server_rejects_expired_budget(mini):
    tx = TcpTransport(mini["reg"], wire_dtype="f32")
    try:
        req = StageRequest(session_id="dead-on-arrival",
                           hidden=jnp.zeros((1, 1, mini["cfg"].hidden_size),
                                            jnp.float32),
                           seq_len=1, cur_len=0, is_prefill=True,
                           max_length=8, deadline_budget_s=-0.5)
        with pytest.raises(DeadlineExceeded):
            tx.call(mini["peer"], req)
    finally:
        tx.close()


# -- acceptance e2e: the chaos soak -------------------------------------------

def test_chaos_soak_tokens_identical_and_doctor_accounts(monkeypatch):
    cfg = tiny_cfg()
    params = init_params(jax.random.PRNGKey(0), cfg)
    res = chaos_soak(cfg, params, prompt_ids=[1, 2, 3, 4, 5],
                     max_new_tokens=10, seed=0, splits=(2, 4, 6),
                     wire_dtype="f32", request_timeout=5.0)
    assert res["ok"], res["problems"]
    assert res["tokens_clean"] == res["tokens_chaos"]
    assert len(res["kinds_fired"]) >= 5
    assert res["deadline_probe"] == "raised DeadlineExceeded"
    assert res["fault_chains"] >= 1


@pytest.mark.slow
def test_chaos_swarm_multiprocess():
    """Full-fidelity soak: one OS process per role, faults crossing real
    process boundaries, doctor merging scraped rings from every server."""
    rc = subprocess.call(
        [sys.executable, "scripts/chaos_swarm.py", "--splits", "4,8",
         "--max_new_tokens", "8", "--seed", "0"], cwd=REPO)
    assert rc == 0
