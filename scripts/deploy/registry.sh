#!/usr/bin/env bash
# Run the registry (control plane) with crash-restart.
# Env (or /etc/mpt/registry.env): MPT_REGISTRY_PORT (31330), MPT_TTL (45).
set -euo pipefail

ENV_FILE="${MPT_ENV:-/etc/mpt/registry.env}"
[ -f "$ENV_FILE" ] && . "$ENV_FILE"
REPO="$(cd "$(dirname "$0")/../.." && pwd)"
PYTHON="${MPT_PYTHON:-python3}"

backoff=2
while true; do
    set +e
    (cd "$REPO" && "$PYTHON" -m \
        global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.main \
        --mode registry --host 0.0.0.0 \
        --registry_port "${MPT_REGISTRY_PORT:-31330}" --ttl "${MPT_TTL:-45}")
    rc=$?
    set -e
    [ $rc -eq 0 ] && exit 0
    echo "[registry.sh] exited rc=$rc; restarting in ${backoff}s" >&2
    sleep "$backoff"
    backoff=$(( backoff < 60 ? backoff * 2 : 60 ))
done
