"""Failure-flow retry-safety analysis (graftlint phase 2, family 1).

The failover guarantee — clients transparently fail over and replay —
rests on a taxonomy: which exceptions are retryable, which are terminal,
and which component gets blamed. ``runtime/errors.py`` is now that
taxonomy's single source of truth; this analyzer statically checks the
failure plane (``runtime/``, ``serving/``, ``scheduling/``) against it.

Rules:

- ``exc-uncatalogued`` — a public exception class defined in the failure
  plane whose policy reaches the recovery wrapper through no catalogued
  ancestor. Private classes (``_BreakerOpen``, ``_HopFailed``) are
  internal control flow and exempt; subclasses of catalogued classes
  (``WireError`` under ``ConnectionError``) inherit their row.
- ``exc-unregistered`` — a class that HAS a catalog row but whose
  definition site lacks the ``@register`` decorator, so the runtime
  registry and the static table can drift apart.
- ``exc-swallowed`` — a broad ``except Exception``/``BaseException``/
  ``OSError`` (or bare ``except``) handler in recovery-reachable code
  that neither re-raises nor constructs a catalogued type: the failure
  disappears instead of driving failover. Cleanup ``try`` blocks (close/
  shutdown/cancel-only bodies) are exempt — swallowing there is the
  idiom, not a bug.
- ``exc-side-effect-before-raise`` — a journal append or KV/prefix-cache
  mutation lexically before a raise of a retryable type in the same
  recovery-reachable function: on replay the side effect happens twice.
- ``wire-error-blame`` — a ``kind="push"`` error-frame literal built
  without deciding ``breaker_peer`` blame (neither a key in the literal
  nor an ``err["breaker_peer"] = ...`` in the enclosing function). Sites
  where breaker blame deliberately coincides with routing blame are
  baselined with that argument in writing.
- ``taxonomy-undocumented`` / ``taxonomy-unknown`` — drift between the
  catalog and docs/FAULT_TOLERANCE.md's taxonomy table, both directions
  (a row per class with its policy; a mismatched policy counts as
  undocumented).

Precision notes: reachability is ``astutil.CallGraph`` — the shared
name-based walker (``self.m()`` resolves within the class, bare names
within the module, and a generic ``obj.m()`` only when exactly ONE
failure-plane class defines ``m``: the unique-target discipline the lock
analyzer's fixpoint shares).
Everything here parses ASTs; the errors module is never imported.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from . import astutil
from .core import Context, Finding

PLANE_DIRS = ("runtime", "serving", "scheduling")

BUILTIN_EXC = {
    "BaseException", "Exception", "ArithmeticError", "AssertionError",
    "AttributeError", "ConnectionError", "EOFError", "IOError",
    "KeyError", "LookupError", "MemoryError", "NotImplementedError",
    "OSError", "RuntimeError", "StopIteration", "TimeoutError",
    "TypeError", "ValueError",
}

BROAD_HANDLERS = {"Exception", "BaseException", "OSError"}

# A try body made only of these calls is teardown; swallowing its errors
# is the idiom (a close() racing a dead socket must not crash recovery).
CLEANUP_CALLS = {
    "close", "shutdown", "unlink", "cancel", "join", "kill", "terminate",
    "remove", "rmtree", "release", "stop", "disconnect", "detach", "pop",
    "clear", "settimeout",
}

# Side-effecting mutations that must not precede a retryable raise:
# receiver-name tokens x mutator terminals.
_JOURNAL_TERMINALS = {"journal_append", "_journal_append"}
_MUTATORS = {"append", "appendleft", "add", "put", "setdefault", "insert",
             "store", "extend", "allocate", "write", "push"}
_STATE_TOKENS = {"journal", "cache", "store", "prefix", "arena"}


# ---------------------------------------------------------------------------
# Taxonomy: parse runtime/errors.py without importing it
# ---------------------------------------------------------------------------

def _parse_taxonomy(mod: astutil.Module) -> Dict[str, Tuple[str, str]]:
    """ErrorPolicy rows -> {name: (policy, scope)}. Resolves the policy
    constants (RETRYABLE = "retryable") from module-level assignments."""
    consts: Dict[str, str] = {}
    for node in mod.tree.body:
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            v = astutil.str_const(node.value)
            if v is not None:
                consts[node.targets[0].id] = v

    def resolve(node: Optional[ast.AST]) -> Optional[str]:
        if node is None:
            return None
        s = astutil.str_const(node)
        if s is not None:
            return s
        name = astutil.dotted_name(node)
        if name is not None:
            return consts.get(name.split(".")[-1])
        return None

    entries: Dict[str, Tuple[str, str]] = {}
    for call in ast.walk(mod.tree):
        if (isinstance(call, ast.Call)
                and astutil.terminal_attr(call) == "ErrorPolicy"):
            kw = {k.arg: k.value for k in call.keywords}
            name = astutil.str_const(
                kw.get("name", call.args[0] if call.args else None))
            policy = resolve(
                kw.get("policy",
                       call.args[1] if len(call.args) > 1 else None))
            scope = resolve(kw.get("scope")) or "client"
            if name and policy:
                entries[name] = (policy, scope)
    return entries


def _taxonomy_module(ctx: Context) -> Optional[astutil.Module]:
    for m in ctx.modules:
        if m.rel.endswith("/errors.py") or m.rel == "errors.py":
            return m
    return None


# ---------------------------------------------------------------------------
# Failure-plane scope + class census
# ---------------------------------------------------------------------------

def _scope_modules(ctx: Context) -> List[astutil.Module]:
    scoped = [m for m in ctx.modules
              if set(m.rel.split("/")) & set(PLANE_DIRS)]
    # Fixture packages have no runtime/serving/scheduling layout; the
    # whole fixture tree is the failure plane.
    return scoped or list(ctx.modules)


def _class_census(mods: List[astutil.Module]
                  ) -> Dict[str, Tuple[astutil.Module, ast.ClassDef,
                                       List[str]]]:
    """name -> (module, node, base names). Last definition wins on a
    (rare, and lint-worthy elsewhere) name collision."""
    out = {}
    for mod in mods:
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ClassDef):
                bases = []
                for b in node.bases:
                    dn = astutil.dotted_name(b)
                    if dn:
                        bases.append(dn.split(".")[-1])
                out[node.name] = (mod, node, bases)
    return out


def _exceptionish(census) -> Set[str]:
    """Names whose base chain reaches a builtin exception (fixpoint over
    the package class graph — no imports, names only)."""
    known: Set[str] = set()
    changed = True
    while changed:
        changed = False
        for name, (_, _, bases) in census.items():
            if name in known:
                continue
            if any(b in BUILTIN_EXC or b in known for b in bases):
                known.add(name)
                changed = True
    return known


def _covered(name: str, taxonomy: Dict[str, Tuple[str, str]],
             census) -> bool:
    """Catalogued directly or via any ancestor (package chain + builtin
    bases — ConnectionError/TimeoutError rows cover their subclasses)."""
    seen: Set[str] = set()
    stack = [name]
    while stack:
        n = stack.pop()
        if n in taxonomy:
            return True
        if n in seen:
            continue
        seen.add(n)
        if n in census:
            stack.extend(census[n][2])
    return False


def _has_register_decorator(node: ast.ClassDef) -> bool:
    for dec in node.decorator_list:
        target = dec.func if isinstance(dec, ast.Call) else dec
        name = astutil.dotted_name(target) or ""
        if "register" in name or "catalog" in name:
            return True
    return False


# ---------------------------------------------------------------------------
# Recovery reachability (name-based BFS, unique-target discipline)
# ---------------------------------------------------------------------------

_ROOT_NAMES = {"_call_with_recovery", "_walk_chain_traced", "_replay",
               "_replay_chain"}
_ROOT_PREFIXES = ("_dispatch", "_handle", "_relay_forward", "_serve")


def _is_root(qual: str, cls: Optional[str]) -> bool:
    name = qual.split(".")[-1]
    if name in _ROOT_NAMES:
        return True
    if name.startswith(_ROOT_PREFIXES):
        return True
    # Transport entry points: the retried region's dynamic extent.
    if cls and "Transport" in cls and name in {"call", "backward"}:
        return True
    return False


class _Reach:
    """Recovery-reachable function set over the failure plane — a thin
    binding of astutil.CallGraph (the shared unique-target walker) to the
    recovery-root predicate."""

    def __init__(self, mods: List[astutil.Module]):
        self.graph = astutil.CallGraph(mods)
        self.reachable = self.graph.reachable(
            key for key, (_fn, cls) in self.graph.funcs.items()
            if _is_root(key[1], cls))


# ---------------------------------------------------------------------------
# Rule bodies
# ---------------------------------------------------------------------------

def _check_catalog(mods, taxonomy, findings: List[Finding]) -> None:
    census = _class_census(mods)
    excish = _exceptionish(census)
    for name in sorted(excish):
        mod, node, _bases = census[name]
        if name.startswith("_"):
            continue  # private: internal control flow, never crosses a seam
        if not _covered(name, taxonomy, census):
            findings.append(Finding(
                rule="exc-uncatalogued", path=mod.rel, line=node.lineno,
                anchor=name,
                message=f"exception {name} can surface through the failure "
                        "plane but has no runtime/errors.py TAXONOMY row "
                        "(and no catalogued ancestor) — recovery cannot "
                        "classify it"))
        elif name in taxonomy and not _has_register_decorator(node):
            findings.append(Finding(
                rule="exc-unregistered", path=mod.rel, line=node.lineno,
                anchor=name,
                message=f"{name} has a TAXONOMY row but its definition "
                        "lacks @register — the runtime registry and the "
                        "static catalog can drift"))


def _handler_names(handler: ast.ExceptHandler) -> List[str]:
    t = handler.type
    if t is None:
        return ["Exception"]  # bare except
    nodes = t.elts if isinstance(t, ast.Tuple) else [t]
    out = []
    for n in nodes:
        dn = astutil.dotted_name(n)
        if dn:
            out.append(dn.split(".")[-1])
    return out


def _is_cleanup_try(try_node: ast.Try) -> bool:
    for stmt in try_node.body:
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
            if astutil.terminal_attr(stmt.value) in CLEANUP_CALLS:
                continue
        if isinstance(stmt, ast.Assign) and isinstance(stmt.value, ast.Call):
            if astutil.terminal_attr(stmt.value) in CLEANUP_CALLS:
                continue
        if isinstance(stmt, ast.Pass):
            continue
        return False
    return bool(try_node.body)


def _converts_or_reraises(handler: ast.ExceptHandler,
                          catalogued: Set[str]) -> bool:
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            t = astutil.terminal_attr(node)
            if t in catalogued:
                return True  # converted even if returned/recorded
    return False


def _first_try_call(try_node: ast.Try) -> str:
    for node in ast.walk(ast.Module(body=try_node.body, type_ignores=[])):
        if isinstance(node, ast.Call):
            return astutil.terminal_attr(node) or "block"
    return "block"


def _check_swallowed(mods, reach: _Reach, catalogued: Set[str],
                     findings: List[Finding]) -> None:
    for mod in mods:
        for qual, _cls, fn in astutil.walk_functions(mod.tree):
            if (mod.rel, qual) not in reach.reachable:
                continue
            for node in ast.walk(fn):
                if not isinstance(node, ast.Try):
                    continue
                if _is_cleanup_try(node):
                    continue
                for handler in node.handlers:
                    broad = [n for n in _handler_names(handler)
                             if n in BROAD_HANDLERS]
                    if not broad:
                        continue
                    if _converts_or_reraises(handler, catalogued):
                        continue
                    anchor = (f"{qual}:except-{broad[0]}"
                              f"@{_first_try_call(node)}")
                    findings.append(Finding(
                        rule="exc-swallowed", path=mod.rel,
                        line=handler.lineno, anchor=anchor,
                        message=f"{qual}: broad except {broad[0]} in "
                                "recovery-reachable code neither re-raises "
                                "nor converts to a catalogued type — the "
                                "failure vanishes instead of driving "
                                "failover"))


def _side_effect_kind(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Call):
        dn = astutil.dotted_name(node.func)
        if not dn:
            return None
        parts = dn.split(".")
        term, recv = parts[-1], parts[:-1]
        if term in _JOURNAL_TERMINALS:
            return term
        if term in _MUTATORS and any(
                tok in seg for seg in recv for tok in _STATE_TOKENS):
            return f"{parts[-2]}.{term}" if len(parts) > 1 else term
    if isinstance(node, ast.AugAssign):
        target = astutil.dotted_name(node.target)
        if target and any(tok in target for tok in ("journal", "_seq")):
            return target
    return None


def _check_side_effects(mods, reach: _Reach, retryable: Set[str],
                        findings: List[Finding]) -> None:
    for mod in mods:
        for qual, _cls, fn in astutil.walk_functions(mod.tree):
            if (mod.rel, qual) not in reach.reachable:
                continue
            effects = []  # (line, label)
            raises = []   # (line, exc name)
            for node in ast.walk(fn):
                kind = _side_effect_kind(node)
                if kind is not None:
                    effects.append((node.lineno, kind))
                if isinstance(node, ast.Raise) and node.exc is not None:
                    exc = node.exc
                    target = exc.func if isinstance(exc, ast.Call) else exc
                    dn = astutil.dotted_name(target)
                    if dn and dn.split(".")[-1] in retryable:
                        raises.append((node.lineno, dn.split(".")[-1]))
            for line, label in effects:
                hit = [r for r in raises if r[0] > line]
                if hit:
                    findings.append(Finding(
                        rule="exc-side-effect-before-raise", path=mod.rel,
                        line=line, anchor=f"{qual}:{label}",
                        message=f"{qual}: {label} mutates journaled/cached "
                                f"state before raising retryable "
                                f"{hit[0][1]} — the replayed attempt "
                                "repeats the side effect"))


def _msg_slug(d: ast.Dict) -> str:
    for k, v in zip(d.keys, d.values):
        if k is not None and astutil.str_const(k) == "message":
            txt = astutil.str_const(v) or ""
            if isinstance(v, ast.JoinedStr):
                for part in v.values:
                    if isinstance(part, ast.Constant):
                        txt = str(part.value)
                        break
            words = re.findall(r"[a-z]+", txt.lower())[:3]
            if words:
                return "-".join(words)
    return "push"


def _dict_str_items(d: ast.Dict) -> Dict[str, ast.AST]:
    out = {}
    for k, v in zip(d.keys, d.values):
        if k is not None:
            s = astutil.str_const(k)
            if s is not None:
                out[s] = v
    return out


def _check_wire_blame(mods, findings: List[Finding]) -> None:
    for mod in mods:
        for qual, _cls, fn in astutil.walk_functions(mod.tree):
            assigns_blame = any(
                isinstance(n, ast.Assign)
                and any(isinstance(t, ast.Subscript)
                        and astutil.str_const(t.slice) == "breaker_peer"
                        for t in n.targets)
                for n in ast.walk(fn))
            for node in ast.walk(fn):
                if not isinstance(node, ast.Dict):
                    continue
                items = _dict_str_items(node)
                if (astutil.str_const(items.get("verb")) != "error"
                        or astutil.str_const(items.get("kind")) != "push"):
                    continue
                if "breaker_peer" in items or assigns_blame:
                    continue
                findings.append(Finding(
                    rule="wire-error-blame", path=mod.rel, line=node.lineno,
                    anchor=f"{qual}:push-frame:{_msg_slug(node)}",
                    message=f"{qual}: kind=push error frame decides no "
                            "breaker_peer blame — if routing blame and "
                            "breaker blame differ here (relay paths), the "
                            "wrong breaker opens; if they coincide, say so "
                            "in the baseline"))


_DOC_ROW = re.compile(r"^\s*\|\s*`(\w+)`\s*\|\s*(\w+)\s*\|", re.M)


def _check_doc_drift(ctx: Context, tax_mod: astutil.Module,
                     taxonomy: Dict[str, Tuple[str, str]],
                     findings: List[Finding]) -> None:
    if "runtime/errors.py" not in tax_mod.rel:
        return  # fixture taxonomy: no doc contract
    doc = ctx.docs_text.get("docs/FAULT_TOLERANCE.md")
    if doc is None:
        findings.append(Finding(
            rule="taxonomy-undocumented", path=tax_mod.rel, line=1,
            anchor="FAULT_TOLERANCE.md",
            message="docs/FAULT_TOLERANCE.md is missing — the taxonomy "
                    "table lives there"))
        return
    documented = {m.group(1): m.group(2) for m in _DOC_ROW.finditer(doc)}
    for name, (policy, _scope) in sorted(taxonomy.items()):
        if name not in documented:
            findings.append(Finding(
                rule="taxonomy-undocumented", path=tax_mod.rel, line=1,
                anchor=name,
                message=f"TAXONOMY row {name} ({policy}) has no table row "
                        "in docs/FAULT_TOLERANCE.md"))
        elif documented[name] != policy:
            findings.append(Finding(
                rule="taxonomy-undocumented", path=tax_mod.rel, line=1,
                anchor=f"{name}:{documented[name]}",
                message=f"docs/FAULT_TOLERANCE.md documents {name} as "
                        f"{documented[name]} but the catalog says "
                        f"{policy}"))
    for name in sorted(set(documented) - set(taxonomy)):
        findings.append(Finding(
            rule="taxonomy-unknown", path="docs/FAULT_TOLERANCE.md",
            line=1, anchor=name,
            message=f"docs/FAULT_TOLERANCE.md documents {name} but "
                    "runtime/errors.py has no such TAXONOMY row"))


# ---------------------------------------------------------------------------
# Entry point
# ---------------------------------------------------------------------------

def analyze(ctx: Context) -> List[Finding]:
    findings: List[Finding] = []
    mods = _scope_modules(ctx)
    tax_mod = _taxonomy_module(ctx)
    taxonomy = _parse_taxonomy(tax_mod) if tax_mod is not None else {}
    if tax_mod is None:
        # No catalog at all: every failure-plane exception is uncatalogued
        # by definition; report the absence once instead of drowning.
        findings.append(Finding(
            rule="exc-uncatalogued", path=PLANE_DIRS[0], line=1,
            anchor="errors.py",
            message="no errors.py taxonomy module found — the failure "
                    "plane has no machine-readable retryability catalog"))
        return findings

    retryable = ({n for n, (p, _s) in taxonomy.items() if p == "retryable"}
                 | {"TimeoutError", "ConnectionError"})
    catalogued = set(taxonomy) | {"TimeoutError", "ConnectionError"}
    reach = _Reach(mods)

    _check_catalog(mods, taxonomy, findings)
    _check_swallowed(mods, reach, catalogued, findings)
    _check_side_effects(mods, reach, retryable, findings)
    _check_wire_blame(mods, findings)
    _check_doc_drift(ctx, tax_mod, taxonomy, findings)
    return findings
