#!/usr/bin/env python
"""Thin shim over the graftlint driver (analyzer: ``bare_print``).

The check itself lives in scripts/graftlint/legacy.py — one driver, one
finding format, one baseline. This entry point survives so existing
tier-1 wrappers (tests/test_no_bare_print.py) and muscle memory keep
working; it exits non-zero on any non-baselined bare ``print()`` in the
package's library code.
"""

import pathlib
import sys

REPO = pathlib.Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

from scripts.graftlint.__main__ import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main(["--analyzer", "bare_print"]))
