"""Test harness: force a virtual 8-device CPU mesh before JAX initializes.

Multi-chip sharding paths (pipeline ppermute, TP psum, ring attention) are
exercised on host CPU devices — the reference had no equivalent in-process
test rig at all (SURVEY.md §4: verification was operational/manual).
"""

# FORCE cpu: the container env pins JAX_PLATFORMS=axon (the real-TPU tunnel)
# and a wedged tunnel would hang every test at backend init. The workaround
# details live in one place, utils.platform.
from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.utils.platform import (
    force_cpu_devices,
)

force_cpu_devices(8, hard=True)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(0)
