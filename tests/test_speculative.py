"""Speculative decoding: drafting, final-stage verification, token parity.

No reference counterpart — this attacks the reference's dominant latency term
(one WAN round trip per generated token, SURVEY.md §3.2 hot loop 2): the
client drafts K tokens per round, the pipeline processes them as ONE
multi-token step, the final stage greedily verifies (executor._verify_drafts)
and the rejected tail is rolled back via the session-rewind mechanism
(petals ``start_from_position`` semantics reused as speculative rollback).

The invariant tested throughout: speculative greedy output is TOKEN-IDENTICAL
to non-speculative greedy output, for any draft quality.
"""

import random

import jax
import jax.numpy as jnp
import numpy as np

from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.models import (
    init_params,
)
from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.models.partition import (
    StagePlan,
    parse_splits,
    slice_stage_params,
)
from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.ops.sampling import (
    SamplingParams,
)
from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.runtime.client import (
    PipelineClient,
    make_server_record,
)
from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.runtime.executor import (
    StageExecutor,
)
from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.runtime.speculative import (
    ngram_draft,
)
from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.runtime.transport import (
    LocalTransport,
)
from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.scheduling.registry import (
    PlacementRegistry,
)

from test_runtime_pipeline import build_cluster, oracle_generate, tiny_cfg

GREEDY = SamplingParams(temperature=0.0)
PROMPT = [5, 9, 23, 7, 81]


def perfect_draft(oracle_tokens, prompt_len):
    """Draft fn that always proposes the model's true continuation."""

    def fn(context, k):
        pos = len(context) - prompt_len
        return tuple(oracle_tokens[pos:pos + k])

    return fn


def garbage_draft(vocab):
    rng = random.Random(123)

    def fn(context, k):
        return tuple(rng.randrange(vocab) for _ in range(k))

    return fn


# ---------------------------------------------------------------------------
# Drafter
# ---------------------------------------------------------------------------

def test_ngram_draft_basic_lookup():
    # suffix [1, 2] occurred earlier, followed by 3, 4.
    assert ngram_draft([1, 2, 3, 4, 9, 1, 2], 2) == (3, 4)


def test_ngram_draft_prefers_most_recent_match():
    # [7] occurs twice; the RECENT occurrence is followed by 5.
    assert ngram_draft([7, 1, 7, 5, 9, 7], 1, max_ngram=1) == (5,)


def test_ngram_draft_prefers_longer_ngrams():
    ctx = [1, 2, 9, 5, 2, 9, 7, 0, 2, 9]
    # 2-gram [2,9] matches at index 4 (recent), followed by 7, 0.
    assert ngram_draft(ctx, 2) == (7, 0)


def test_ngram_draft_no_match_and_caps():
    assert ngram_draft([1, 2, 3], 3) == ()            # no repeat at all
    assert ngram_draft([4, 4], 3, max_ngram=1) == (4,)  # only 1 follower
    assert ngram_draft([], 3) == ()
    assert ngram_draft([1, 2], 0) == ()


# ---------------------------------------------------------------------------
# End-to-end parity (the core invariant)
# ---------------------------------------------------------------------------

def test_speculative_matches_oracle_with_perfect_drafts():
    cfg = tiny_cfg()
    client, transport, _, params, _ = build_cluster(cfg, splits="4")
    ref = oracle_generate(cfg, params, PROMPT, 12, GREEDY)

    res = client.generate(
        PROMPT, max_new_tokens=12, sampling=GREEDY,
        speculative_k=4, draft_fn=perfect_draft(ref, len(PROMPT)),
    )
    assert res.tokens == ref
    # Perfect drafts: every round accepts K+1 tokens -> round trips collapse.
    # Non-speculative would need 12 remote calls; prefill(1) + ceil(11/5)=3.
    assert transport.calls <= 1 + 4


def test_speculative_matches_oracle_with_garbage_drafts():
    cfg = tiny_cfg()
    client, _, _, params, _ = build_cluster(cfg, splits="4")
    ref = oracle_generate(cfg, params, PROMPT, 10, GREEDY)
    res = client.generate(
        PROMPT, max_new_tokens=10, sampling=GREEDY,
        speculative_k=3, draft_fn=garbage_draft(cfg.vocab_size),
    )
    # All drafts rejected every round -> one real token per round, but the
    # rejected-overhang rollback must keep the KV consistent throughout.
    assert res.tokens == ref


def test_speculative_with_default_ngram_drafter():
    cfg = tiny_cfg("gpt2")
    # A repetitive prompt gives the n-gram drafter something to find.
    prompt = [3, 1, 4, 1, 5, 3, 1, 4]
    client, _, _, params, _ = build_cluster(cfg, splits="4")
    ref = oracle_generate(cfg, params, prompt, 10, GREEDY)
    res = client.generate(prompt, max_new_tokens=10, sampling=GREEDY,
                          speculative_k=3)
    assert res.tokens == ref


def test_speculative_multi_hop_pipeline():
    cfg = tiny_cfg()
    client, transport, _, params, _ = build_cluster(cfg, splits="2,4,6")
    ref = oracle_generate(cfg, params, PROMPT, 12, GREEDY)
    res = client.generate(
        PROMPT, max_new_tokens=12, sampling=GREEDY,
        speculative_k=4, draft_fn=perfect_draft(ref, len(PROMPT)),
    )
    assert res.tokens == ref
    # 3 hops x (prefill + 3 spec rounds) = 12 calls vs 36 non-speculative.
    assert transport.calls <= 3 * (1 + 3)


# (Round 1 rejected temperature>0 speculative decoding outright; round 2
# supports it via rejection-sampling verification — see the
# test_speculative_verify_* and test_speculative_generation_with_sampling_*
# tests below for the replacing coverage.)


def test_speculative_survives_failover():
    cfg = tiny_cfg()
    client, transport, registry, params, plan = build_cluster(
        cfg, splits="4", replicas=2)
    ref = oracle_generate(cfg, params, PROMPT, 12, GREEDY)

    res = None
    # Inject a transient failure on whichever peer actually serves the
    # session (captured from the first tapped call — the route is
    # affinity-keyed, so pre-computing client.route() could watch a
    # replica the generation never uses): the speculative round must fail
    # over, REPLAY the (amended) journal into the replica, and keep
    # producing oracle-identical tokens.
    done_prefill = {"n": 0, "peer": None}

    def tap(peer_id, req):
        done_prefill["n"] += 1
        if done_prefill["peer"] is None:
            done_prefill["peer"] = peer_id
        if done_prefill["n"] == 3:  # prefill + 1 spec round done; fail next
            transport.fail_next(done_prefill["peer"], 1)

    transport.on_call = tap
    res = client.generate(
        PROMPT, max_new_tokens=12, sampling=GREEDY,
        speculative_k=3, draft_fn=perfect_draft(ref, len(PROMPT)),
    )
    assert res.tokens == ref
    assert client.recoveries >= 1


def test_speculative_push_chain():
    cfg = tiny_cfg()
    params = init_params(jax.random.PRNGKey(0), cfg)
    plan = StagePlan.from_splits(cfg.num_layers, parse_splits("2,4,6"))
    transport = LocalTransport()
    registry = PlacementRegistry(rng=random.Random(0))
    for spec in plan.stages[1:]:
        peer = f"peer-s{spec.index}"
        ex = StageExecutor(cfg, spec, slice_stage_params(cfg, params, spec),
                           peer_id=peer)
        transport.add_peer(peer, ex)
        registry.register(make_server_record(peer, spec))
    stage0 = StageExecutor(cfg, plan.stages[0],
                           slice_stage_params(cfg, params, plan.stages[0]),
                           peer_id="client-local")
    client = PipelineClient(cfg, plan, stage0, transport, registry,
                            use_push_chain=True, settle_seconds=0.0, seed=0)
    ref = oracle_generate(cfg, params, PROMPT, 12, GREEDY)
    res = client.generate(
        PROMPT, max_new_tokens=12, sampling=GREEDY,
        speculative_k=4, draft_fn=perfect_draft(ref, len(PROMPT)),
    )
    assert res.tokens == ref


def test_speculative_eos_inside_accepted_run():
    cfg = tiny_cfg()
    client, _, _, params, _ = build_cluster(cfg, splits="4")
    ref = oracle_generate(cfg, params, PROMPT, 12, GREEDY)
    # Pick an "EOS" whose FIRST occurrence is past the first round, so it
    # lands mid-accepted-run (a token seen earlier would stop immediately).
    j = next(i for i in range(1, len(ref)) if ref[i] not in ref[:i])
    eos = ref[j]
    res = client.generate(
        PROMPT, max_new_tokens=12, sampling=GREEDY, eos_token_id=eos,
        speculative_k=4, draft_fn=perfect_draft(ref, len(PROMPT)),
    )
    # Generation must stop AT the EOS token even when it lands mid-round.
    assert res.tokens == ref[:j + 1]
    assert res.stopped_by == "eos"


def test_speculative_over_tcp_wire():
    from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.runtime.net import (
        TcpStageServer,
        TcpTransport,
    )

    cfg = tiny_cfg()
    params = init_params(jax.random.PRNGKey(0), cfg)
    plan = StagePlan.from_splits(cfg.num_layers, parse_splits("4"))
    registry = PlacementRegistry(rng=random.Random(0))
    spec = plan.stages[1]
    ex = StageExecutor(cfg, spec, slice_stage_params(cfg, params, spec),
                       peer_id="tcp-final")
    srv = TcpStageServer(ex, port=0, wire_dtype="f32")
    srv.start()
    try:
        rec = make_server_record("tcp-final", spec)
        rec.address = srv.address
        registry.register(rec)
        transport = TcpTransport(registry, wire_dtype="f32")
        stage0 = StageExecutor(cfg, plan.stages[0],
                               slice_stage_params(cfg, params, plan.stages[0]),
                               peer_id="client-local")
        client = PipelineClient(cfg, plan, stage0, transport, registry,
                                settle_seconds=0.0, seed=0)
        ref = oracle_generate(cfg, params, PROMPT, 10, GREEDY)
        res = client.generate(
            PROMPT, max_new_tokens=10, sampling=GREEDY,
            speculative_k=3, draft_fn=perfect_draft(ref, len(PROMPT)),
        )
        assert res.tokens == ref
        transport.close()
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# Rejection-sampling verification (temperature > 0)
# ---------------------------------------------------------------------------

def test_speculative_verify_accept_and_reject_paths():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.ops.sampling import (
        RECENT_WINDOW,
        speculative_verify,
    )

    V, K = 16, 3
    recent = np.zeros((RECENT_WINDOW,), np.int32)
    # logits put ~all mass on token 5 at every position
    logits = np.full((K + 1, V), -20.0, np.float32)
    logits[:, 5] = 20.0
    toks, n_acc = speculative_verify(
        jax.random.PRNGKey(0), jnp.asarray(logits), [5, 5, 5], recent, 0,
        0.8, 1.0, 0, 1.0)
    assert n_acc == K and toks[:K] == [5, 5, 5] and len(toks) == K + 1
    # draft 9 has ~zero mass -> rejected at position 0, correction != 9
    toks, n_acc = speculative_verify(
        jax.random.PRNGKey(1), jnp.asarray(logits), [9, 5, 5], recent, 0,
        0.8, 1.0, 0, 1.0)
    assert n_acc == 0 and len(toks) == 1 and toks[0] != 9


def test_speculative_verify_preserves_distribution():
    """The first output position's law must equal the target sampler's law
    regardless of what the (deterministic) draft proposed — the speculative
    sampling correctness property, checked empirically against the oracle
    sample_probs distribution."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.ops.sampling import (
        RECENT_WINDOW,
        sample_probs,
        speculative_verify,
    )

    V = 12
    rng = np.random.default_rng(7)
    logits = jnp.asarray(rng.standard_normal((2, V)).astype(np.float32) * 2)
    recent = np.zeros((RECENT_WINDOW,), np.int32)
    temp, top_p, top_k, rp = 0.9, 1.0, 0, 1.0
    target = np.asarray(sample_probs(
        logits[0], jnp.asarray(recent), jnp.asarray(0, jnp.int32),
        jnp.asarray(temp, jnp.float32), jnp.asarray(top_p, jnp.float32),
        jnp.asarray(top_k, jnp.int32), jnp.asarray(rp, jnp.float32)))
    draft = int(np.argmax(target))          # draft the LIKELIEST token —
    n = 1500                                # max acceptance bias if wrong
    counts = np.zeros(V)
    for s in range(n):
        toks, _ = speculative_verify(
            jax.random.PRNGKey(s), logits, [draft], recent, 0,
            temp, top_p, top_k, rp)
        counts[toks[0]] += 1
    emp = counts / n
    # ~3 sigma for a multinomial with n=1500: ~0.039 absolute. An
    # acceptance-bias bug shifts mass by O(p_draft) ~ 0.3 — far outside
    # this band; n=1500 keeps the check decisive at a third of the wall
    # cost of the original n=4000 (this was the single slowest test).
    np.testing.assert_allclose(emp, target, atol=0.045)


def test_speculative_generation_with_sampling_runs():
    """End-to-end: temperature>0 + speculative drafts through the pipeline
    generates without error (the output law matches non-speculative
    sampling by the verifier property; token equality is not expected —
    the randomness path differs)."""
    import jax

    from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.models import (
        init_params,
    )
    from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.ops.sampling import (
        SamplingParams,
    )

    from test_runtime_pipeline import build_cluster, tiny_cfg

    cfg = tiny_cfg()
    client, _, _, _, _ = build_cluster(cfg)
    res = client.generate([5, 9, 23, 7, 81], max_new_tokens=8,
                          sampling=SamplingParams(temperature=0.9),
                          speculative_k=3)
    assert 1 <= len(res.tokens) <= 8
    assert all(0 <= t < cfg.vocab_size for t in res.tokens)
