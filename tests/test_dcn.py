"""Multi-host DCN layer: REAL cross-process collectives on a CPU cluster.

SURVEY.md §4 notes the reference never simulated multi-node ("the reference
either runs all stages on localhost or on real cloud VMs — no fake
transport"); §7.1 layer 7 demands a jax.distributed multi-process story.
This test forms an actual 2-process JAX cluster over loopback (gloo CPU
collectives), with 2 virtual devices per process, and checks that psum and
ppermute really cross the process boundary — the DCN analogue.

Runs in SUBPROCESSES: jax.distributed must initialize before the backend,
and the parent test process already holds an initialized single-process
backend.
"""

import os
import socket
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _spawn(argv):
    """Launch a cluster worker with a scrubbed environment: the worker
    forces its own CPU platform/device count, so the parent conftest's
    JAX_PLATFORMS and 8-device XLA flag must not leak in."""
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env["XLA_FLAGS"] = " ".join(
        f for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f)
    return subprocess.Popen(
        [sys.executable, *argv], cwd=REPO, env=env,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )


def _dcn_check_argv(port, pid, nprocs):
    return ["-m",
            "global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.main",
            "--mode", "dcn-check",
            "--dcn_coordinator", f"127.0.0.1:{port}",
            "--num_processes", str(nprocs),
            "--process_id", str(pid),
            "--dcn_cpu_devices", "2"]


def test_fused_pipeline_spans_processes():
    """The fused ICI pipeline (parallel.pipeline) runs UNCHANGED over a mesh
    spanning two processes: stages 0-1 on proc 0, stages 2-3 on proc 1, the
    inter-stage ppermute crossing the process boundary (the DCN hop)."""
    port = _free_port()
    procs = [
        _spawn([os.path.join(REPO, "tests", "_dcn_pipeline_worker.py"),
                f"127.0.0.1:{port}", str(pid)])
        for pid in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    sums = []
    for pid, (p, out) in enumerate(zip(procs, outs)):
        lines = [ln for ln in out.splitlines() if "DCN_PIPE" in ln]
        assert lines, f"proc {pid}:\n{out[-2000:]}"
        assert f"proc={pid}" in lines[-1], lines[-1]
        assert "shape=(2, 1, 1, 128)" in lines[-1], lines[-1]
        sums.append(lines[-1].rsplit("checksum=", 1)[1])
        assert p.returncode == 0, f"proc {pid} exited {p.returncode}:\n{out[-2000:]}"
    # Both controllers must agree on the pipeline's output.
    assert sums[0] == sums[1] and float(sums[0]) > 0.0


def test_two_process_cluster_collectives():
    port = _free_port()
    procs = [_spawn(_dcn_check_argv(port, pid, 2)) for pid in range(2)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for pid, (p, out) in enumerate(zip(procs, outs)):
        lines = [ln for ln in out.splitlines() if "DCN_CHECK" in ln]
        assert lines, f"proc {pid} produced no DCN_CHECK line:\n{out[-2000:]}"
        line = lines[-1]
        # 2 processes x 2 devices: global view must be 4 devices, psum must
        # see both processes' contributions (2*1 + 2*2 = 6), ring must pass.
        assert f"process={pid}/2" in line, line
        assert "devices=2/4" in line, line
        assert "psum=6.0/6.0" in line, line
        assert "ring=ok" in line, line
        assert line.rstrip().endswith(" OK"), line
        assert p.returncode == 0, f"proc {pid} exited {p.returncode}:\n{out[-2000:]}"
