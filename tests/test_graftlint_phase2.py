"""graftlint phase 2: failure-flow retry safety + determinism taint.

Same three layers as tests/test_graftlint.py, for the two new analyzer
families (docs/STATIC_ANALYSIS.md):
  1. every new rule FIRES on the seeded fixtures (pkg/errors.py carries a
     mini taxonomy so the fixture tree has a catalog to lint against);
  2. the real package is CLEAN — the full-tree gate lives in
     test_graftlint.py and already covers the new families via
     ALL_ANALYZERS; here we gate the new families in isolation so a
     failure names the family;
  3. the real findings fixed when these analyzers first ran stay fixed
     (their keys must never reappear), plus behavioral checks on the
     taxonomy module the failures family enforces.

Also covers the phase-2 CLI surface: --sarif and --changed-only.
"""

import json
import pathlib
import subprocess
import sys

import pytest

REPO = pathlib.Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

from scripts.graftlint import (  # noqa: E402
    Baseline, build_context, run_analyzers,
)

FIXTURES = REPO / "tests" / "fixtures" / "graftlint"
PKG = ("global_capstone_design_distributed_inference_of_llms"
       "_over_the_internet_tpu")


# ---------------------------------------------------------------------------
# 1. Fixtures: every new rule provably fires
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def fixture_findings():
    ctx = build_context(FIXTURES, pkg=FIXTURES / "pkg")
    return {f.key for f in run_analyzers(ctx, ["failures", "determinism"])}


def test_fixture_uncatalogued_exception_fires(fixture_findings):
    assert ("exc-uncatalogued:pkg/failures_bad.py:UncataloguedError"
            in fixture_findings)


def test_fixture_unregistered_exception_fires(fixture_findings):
    assert ("exc-unregistered:pkg/failures_bad.py:CataloguedButUnregistered"
            in fixture_findings)


def test_fixture_registered_exception_is_clean(fixture_findings):
    for rule in ("exc-uncatalogued", "exc-unregistered"):
        assert (f"{rule}:pkg/failures_bad.py:FixtureRetryable"
                not in fixture_findings)


def test_fixture_swallowing_handler_fires(fixture_findings):
    assert ("exc-swallowed:pkg/failures_bad.py:"
            "Recovering._call_with_recovery:except-Exception@_attempt"
            in fixture_findings)


def test_fixture_side_effect_before_raise_fires(fixture_findings):
    assert ("exc-side-effect-before-raise:pkg/failures_bad.py:"
            "Recovering._call_with_recovery:journal.append"
            in fixture_findings)


def test_fixture_blameless_push_frame_fires(fixture_findings):
    assert ("wire-error-blame:pkg/failures_bad.py:"
            "_handle_push:push-frame:fixture-push-failed"
            in fixture_findings)


def test_fixture_unseeded_rng_fires(fixture_findings):
    assert ("det-unseeded-rng:pkg/determinism_bad.py:"
            "Sampler.__init__:random.Random" in fixture_findings)
    assert ("det-unseeded-rng:pkg/determinism_bad.py:"
            "Sampler.__init__:default_rng" in fixture_findings)


def test_fixture_clock_tainted_seed_fires(fixture_findings):
    assert ("det-taint:pkg/determinism_bad.py:Sampler.clock_seed:PRNGKey"
            in fixture_findings)


def test_fixture_clock_tainted_session_id_fires(fixture_findings):
    assert ("det-taint:pkg/determinism_bad.py:Sampler.clock_session:"
            "session_id" in fixture_findings)


def test_fixture_key_double_consume_fires(fixture_findings):
    assert ("det-key-reuse:pkg/determinism_bad.py:sample_twice:key"
            in fixture_findings)


def test_fixture_key_consumed_in_loop_fires(fixture_findings):
    assert ("det-key-reuse:pkg/determinism_bad.py:sample_in_loop:key"
            in fixture_findings)


def test_fixture_prngkey_burst_idiom_is_sanctioned(fixture_findings):
    hits = [k for k in fixture_findings
            if k.startswith("det-key-reuse") and "sanctioned_burst" in k]
    assert not hits, hits


# ---------------------------------------------------------------------------
# 2. The real tree: the new families alone report nothing unbaselined
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def real_tree():
    ctx = build_context(REPO)
    findings = run_analyzers(ctx, ["failures", "determinism"])
    baseline = Baseline.load(REPO / "graftlint_baseline.json")
    return findings, baseline


def test_real_tree_new_families_clean(real_tree):
    findings, baseline = real_tree
    new, _, _ = baseline.split(findings)
    assert not new, "new phase-2 findings:\n" + "\n".join(
        f.render() for f in new)


def test_real_tree_taxonomy_doc_in_sync(real_tree):
    findings, _ = real_tree
    drift = [f for f in findings
             if f.rule in ("taxonomy-undocumented", "taxonomy-unknown")]
    assert not drift, "\n".join(f.render() for f in drift)


# ---------------------------------------------------------------------------
# 3. Regression pins: the real findings fixed in phase 2 stay fixed
# ---------------------------------------------------------------------------

# The concrete nondeterminism and retry-safety defects this round of lint
# triage fixed forward. If any of these keys fires again, the fix
# regressed (unseeded fallback RNGs; a recovery loop that retried
# permanent failures through all attempts).
FIXED_KEYS = [
    f"det-unseeded-rng:{PKG}/runtime/server.py:"
    "ElasticStageServer.__init__:random.Random",
    f"det-unseeded-rng:{PKG}/scheduling/gossip.py:"
    "GossipNode.__init__:random.Random",
    f"det-unseeded-rng:{PKG}/scheduling/registry.py:"
    "PlacementRegistry.__init__:random.Random",
    f"det-unseeded-rng:{PKG}/scheduling/load_balancing.py:"
    "should_choose_other_blocks:default_rng",
    f"exc-swallowed:{PKG}/runtime/client.py:"
    "PipelineClient._call_with_recovery:except-Exception@_replay",
]


def test_fixed_findings_stay_fixed(real_tree):
    findings, _ = real_tree
    keys = {f.key for f in findings}
    back = [k for k in FIXED_KEYS if k in keys]
    assert not back, f"previously fixed findings reappeared: {back}"


def test_taxonomy_module_behaves():
    """The runtime contract the failures analyzer leans on: the catalog
    resolves policies via registered ancestors, excludes server-scope and
    non-retryable rows from the client tuple, and maps wire markers in
    terminal-flag-first order."""
    from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.runtime import (  # noqa: E501
        client as _client,  # noqa: F401 - triggers registration imports
        errors,
        net as _net,  # noqa: F401
    )

    rt = errors.retryable_types()
    names = {c.__name__ for c in rt}
    assert {"PeerUnavailable", "TimeoutError", "ConnectionError"} <= names
    # Permanent/shed/server-scope rows must never enter the client tuple.
    assert not {"DeadlineExceeded", "TaskRejected", "NoRouteError",
                "Overloaded", "SlotFull", "AllocationFailed",
                "AdmissionDenied"} & names

    # WireError inherits retryability through its ConnectionError ancestor
    # even before (and after) its own registration.
    assert isinstance(errors.from_wire({"deadline_expired": True}),
                      errors.registered("DeadlineExceeded"))
    rej = errors.from_wire({"task_rejected": True, "kind": "stage"})
    assert type(rej).__name__ == "TaskRejected"
    # Terminal flags win over kind= discriminators: a task_rejected frame
    # riding a stage frame must NOT come back retryable.
    assert not isinstance(rej, rt)
    push = errors.from_wire(
        {"kind": "push", "peer": "p2", "breaker_peer": "relay-1",
         "message": "downstream died"})
    assert type(push).__name__ == "PushChainError"
    assert errors.breaker_blame(push, "p2") == "relay-1"
    stage = errors.from_wire({"kind": "stage", "peer": "p3",
                              "message": "boom"})
    assert type(stage).__name__ == "StageExecutionError"
    assert isinstance(stage, rt)


def test_policy_of_walks_mro():
    from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.runtime import (  # noqa: E501
        errors, transport,
    )

    class _Private(transport.PeerUnavailable):
        pass

    row = errors.policy_of(_Private("x"))
    assert row is not None and row.name == "PeerUnavailable"
    assert errors.policy_of(KeyError("x")) is None


# ---------------------------------------------------------------------------
# 4. CLI surface: --sarif and --changed-only
# ---------------------------------------------------------------------------

def test_cli_sarif_output(tmp_path):
    out = tmp_path / "lint.sarif"
    proc = subprocess.run(
        [sys.executable, "-m", "scripts.graftlint", "--sarif", str(out)],
        capture_output=True, text=True, timeout=300, cwd=REPO)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    sarif = json.loads(out.read_text(encoding="utf-8"))
    assert sarif["version"] == "2.1.0"
    run = sarif["runs"][0]
    assert run["tool"]["driver"]["name"] == "graftlint"
    # Clean tree: baselined findings are suppressed by design, so the
    # SARIF result list (new findings only) is empty.
    assert run["results"] == []


def test_cli_sarif_carries_new_findings(tmp_path):
    """--no-baseline --sarif: every finding is 'new', so the SARIF run
    must carry results with rule ids, locations, and stable keys."""
    out = tmp_path / "raw.sarif"
    proc = subprocess.run(
        [sys.executable, "-m", "scripts.graftlint", "--no-baseline",
         "--analyzer", "failures", "--analyzer", "determinism",
         "--sarif", str(out)],
        capture_output=True, text=True, timeout=300, cwd=REPO)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    sarif = json.loads(out.read_text(encoding="utf-8"))
    results = sarif["runs"][0]["results"]
    assert results, "expected baselined findings to appear raw"
    for r in results:
        assert r["ruleId"]
        loc = r["locations"][0]["physicalLocation"]
        assert loc["artifactLocation"]["uri"]
        assert loc["region"]["startLine"] >= 1
        assert r["partialFingerprints"]["graftlintKey"].startswith(
            r["ruleId"] + ":")


def test_cli_changed_only_scopes_reporting():
    """--changed-only vs HEAD on a clean worktree (or one whose changed
    files are lint-clean) exits 0 and says how many files it scoped to;
    vs a bogus ref it falls back to full-tree with a warning."""
    proc = subprocess.run(
        [sys.executable, "-m", "scripts.graftlint", "--changed-only"],
        capture_output=True, text=True, timeout=300, cwd=REPO)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "changed file(s)" in proc.stdout

    proc = subprocess.run(
        [sys.executable, "-m", "scripts.graftlint", "--changed-only",
         "not-a-ref-anyone-has"],
        capture_output=True, text=True, timeout=300, cwd=REPO)
    assert "git diff failed" in proc.stderr
    assert "full tree" in proc.stdout
