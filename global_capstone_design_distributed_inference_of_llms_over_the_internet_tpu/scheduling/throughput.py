"""Server throughput self-measurement — the scalar each server gossips.

Behavior-parity port of the reference's two-tier scheme:

  * compute term (``src/throughput_measurement.py:15-154``): time a dummy
    batch-1 seq-1 forward, 2 warmup + 10 timed steps, report requests/sec,
    surviving per-step failures;
  * network term (``src/throughput_measurement.py:157-190``): requests/sec a
    link can carry = bandwidth / per-request payload (one fp16 hidden-state
    tensor), default 100 Mbps when unmeasured;
  * combination (``:193-263``): final = min(compute, network × (1 − relay
    penalty 0.2)), falling back to network-only and finally a 10.0 rps
    constant so a server can always advertise something;
  * persistent JSON cache keyed by (model, device, dtype) with an
    expected-blocks-per-request correction, from the vendored full version
    (``petals/server/throughput.py:65-100``).

On TPU the compute probe times the jitted stage step (compile excluded by the
warmup steps) and the network term models the DCN/ICI hop instead of a WAN
speedtest — the reference's speedtest-cli dependency is deliberately dropped
(SURVEY.md §7.4).
"""

from __future__ import annotations

import json
import logging
import os
import time
from typing import Callable, Optional

logger = logging.getLogger(__name__)

DEFAULT_BANDWIDTH_MBPS = 100.0   # src/throughput_measurement.py:180-183
RELAY_PENALTY = 0.2              # src/throughput_measurement.py:237-250
FALLBACK_RPS = 10.0              # src/throughput_measurement.py:253-255
WARMUP_STEPS = 2
TIMED_STEPS = 10


def measure_compute_rps(
    step: Callable[[], object],
    warmup_steps: int = WARMUP_STEPS,
    timed_steps: int = TIMED_STEPS,
) -> Optional[float]:
    """Requests/sec of `step` (a zero-arg callable running one batch-1 seq-1
    forward and blocking until done). Per-step failures are survived; returns
    None if no step succeeded (``src/throughput_measurement.py:105-132``)."""
    for _ in range(warmup_steps):
        try:
            step()
        except Exception as exc:
            logger.warning("throughput warmup step failed: %s", exc)
    total, ok = 0.0, 0
    for _ in range(timed_steps):
        try:
            t0 = time.perf_counter()
            step()
            total += time.perf_counter() - t0
            ok += 1
        except Exception as exc:
            logger.warning("throughput timed step failed: %s", exc)
    if ok == 0 or total <= 0:
        return None
    return ok / total


def estimate_network_rps(
    bandwidth_mbps: Optional[float],
    request_bytes: int,
) -> float:
    """Requests/sec the network link sustains for one hidden-state payload."""
    bw = bandwidth_mbps if bandwidth_mbps and bandwidth_mbps > 0 else DEFAULT_BANDWIDTH_MBPS
    if request_bytes <= 0:
        return FALLBACK_RPS
    return (bw * 1e6 / 8.0) / request_bytes


def hidden_request_bytes(hidden_size: int, seq_len: int = 1, batch: int = 1,
                         bytes_per_elem: int = 2) -> int:
    """Per-request wire payload: one fp16/bf16 hidden tensor [B, T, D]."""
    return batch * seq_len * hidden_size * bytes_per_elem


def get_server_throughput(
    step: Optional[Callable[[], object]],
    hidden_size: int,
    *,
    bandwidth_mbps: Optional[float] = None,
    use_relay: bool = False,
    num_blocks: int = 1,
    cache_path: Optional[str] = None,
    cache_key: Optional[str] = None,
) -> float:
    """The advertised scalar: min(compute, network·(1−relay_penalty)).

    `num_blocks` applies the vendored expected-blocks-per-request correction
    ``(num_blocks + 1) / 2`` (``petals/server/throughput.py:96-100``): a
    client chain rarely uses every block a server holds.
    """
    if cache_path and cache_key:
        try:
            with open(cache_path) as f:
                cached = json.load(f)
            if cache_key in cached:
                return float(cached[cache_key])
        except (OSError, ValueError):
            pass

    compute_rps = None
    if step is not None:
        try:
            compute_rps = measure_compute_rps(step)
        except Exception as exc:
            logger.warning("compute throughput probe failed entirely: %s", exc)
    if compute_rps is not None and num_blocks > 1:
        compute_rps = compute_rps * 2.0 / (num_blocks + 1)

    network_rps = estimate_network_rps(
        bandwidth_mbps, hidden_request_bytes(hidden_size)
    )
    if use_relay:
        network_rps *= 1.0 - RELAY_PENALTY

    # Fallback chain: min(compute, network) -> network-only when the compute
    # probe failed. estimate_network_rps itself bottoms out at FALLBACK_RPS
    # (degenerate payload size), so a server can always advertise something.
    result = min(compute_rps, network_rps) if compute_rps is not None else network_rps

    if cache_path and cache_key:
        try:
            cached = {}
            if os.path.exists(cache_path):
                with open(cache_path) as f:
                    cached = json.load(f)
            cached[cache_key] = result
            tmp = cache_path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(cached, f)
            os.replace(tmp, cache_path)
        except OSError as exc:
            logger.warning("could not persist throughput cache: %s", exc)
    return result
