"""Tensor / expert parallelism for one pipeline stage via shard_map.

The reference gets TP only through the external ``tensor_parallel`` package
wrapping torch blocks (``petals/server/backend.py:43``, asserts every backend
is a TensorParallel instance); MoE/EP exists only as config guards with no
runnable code (SURVEY.md §2.3). Here both are first-class mesh axes:

  * TP ("megatron"-style): q/k/v and mlp-in projections are column-sharded
    over the ``tp`` axis, out-projections row-sharded, so each matmul pair
    needs exactly ONE ``psum`` (already emitted inside
    ``models.transformer`` when ``tp_axis`` is set). The KV cache shards
    over kv heads — GQA requires ``num_kv_heads % tp == 0``.
  * EP (MoE): expert weights shard over the same axis; the router stays
    replicated so top-k routing is global, each device computes its local
    experts' weighted contribution, and the same closing psum combines.

Composability: the specs returned here are ordinary PartitionSpecs over one
named axis, so a stage can run TP inside a pipeline stage's device group
(mesh ("stage", "tp")) — the fused pipeline shard-maps over "stage" and this
module's body runs inside it over "tp".
"""

from __future__ import annotations

import re
from functools import partial
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.config import ModelConfig
from ..models.partition import (
    StageSpec,
    match_partition_rules,
    path_name,
    stage_forward,
)

Params = Dict[str, Any]


def tp_partition_rules(cfg: ModelConfig, axis: str = "tp"):
    """Explicit (regex, PartitionSpec) rules for stacked [L, ...] layer
    leaves, consumed by `models.partition.match_partition_rules`.

    Dense blocks: column-parallel in (q/k/v and mlp-in sharded on the
    OUTPUT axis), row-parallel out (wo/wd sharded on the INPUT axis) — one
    psum per matmul pair, emitted inside models.transformer. MoE blocks:
    the expert axis (axis 1 of [L, E, ...]) shards over the SAME mesh axis
    (expert parallelism) while the router stays replicated so top-k
    routing and the sparse dispatch's capacity/drop decisions are global;
    the per-token combine rides the same closing psum. Norms, biases
    without a sharded sibling, and the per-layer `window` leaf replicate
    via the catch-all."""
    attn = (
        (r"attn/(wq|wk|wv)$", P(None, None, axis)),
        (r"attn/(bq|bk|bv)$", P(None, axis)),
        (r"attn/wo$", P(None, axis)),
    )
    if cfg.is_moe:
        mlp = (
            (r"mlp/router$", P()),
            (r"mlp/(wg|wu|wd)$", P(None, axis)),    # expert axis of [L,E,..]
        )
    else:
        mlp = (
            (r"mlp/(wg|wu|wi)$", P(None, None, axis)),
            (r"mlp/(wd|wo|bi)$", P(None, axis)),
        )
    return (*attn, *mlp, (r".*", P()))


# Replicated-leaf registry: every shardable layer leaf that DELIBERATELY
# rides the catch-all, with the reason. Replication must be a decision,
# never a fall-through — a new leaf that matches neither a sharding rule
# above nor a row here fails graftlint's spmd-catchall-leaf check, which
# parses this table (regex, reason) without importing the module.
REPLICATED_LEAVES = (
    (r"ln[0-9]/(w|b)$",
     "norm scale/shift are O(d): sharding saves nothing and would cost an "
     "all-gather before every norm"),
    (r"attn/bo$",
     "output-projection bias is applied once to the closing psum's "
     "replicated sum; a sharded copy would be counted tp times"),
    (r"mlp/bo$",
     "mlp output bias is applied after the closing psum, same layout "
     "argument as attn/bo"),
    (r"^window$",
     "per-layer attention-window vector is [L] int32 config state, not a "
     "weight — every rank needs the whole thing"),
)


def layer_partition_specs(cfg: ModelConfig, axis: str = "tp"):
    """Spec RESOLVER for stacked-layer leaves: returns a function
    (tree_map_with_path path) -> PartitionSpec for a [L, ...] leaf, rule-
    matched against `tp_partition_rules`. Use `stage_param_specs` for a
    ready-made spec pytree over a whole stage."""
    rules = tp_partition_rules(cfg, axis)

    def spec_for(path) -> P:
        name = path_name(path)
        for rule, spec in rules:
            if re.search(rule, name):
                return spec
        return P()

    return spec_for


def stage_param_specs(cfg: ModelConfig, params: Params, axis: str = "tp") -> Params:
    """PartitionSpec pytree for a stage's parameter shard: layer leaves get
    the `tp_partition_rules` layout; embeddings, final norm, and
    lm_head are replicated over the axis (the head's vocab matmul is
    recomputed identically on each rank — cheap next to the layer stack, and
    it keeps logits replicated for sampling). The single source of truth for
    both placement (`shard_stage_params`) and shard_map in_specs
    (`make_tp_stage_fn`)."""
    from ..models.quant import is_quantized

    if is_quantized(params):
        # QuantizedTensor's q/s leaves would miss the name-keyed TP rules
        # and silently replicate — each rank would then compute the FULL
        # projection and the closing psum would multiply results by tp.
        # Fail loudly instead of corrupting logits.
        raise NotImplementedError(
            "tensor parallelism over int8-quantized params is not "
            "supported; shard full-precision params (quantize per shard "
            "afterwards if needed)"
        )
    out = {k: jax.tree.map(lambda _: P(), v)
           for k, v in params.items() if k != "layers"}
    if "layers" in params:
        out["layers"] = match_partition_rules(
            tp_partition_rules(cfg, axis), params["layers"])
    return out


def shard_stage_params(
    cfg: ModelConfig, params: Params, mesh: Mesh, axis: str = "tp"
) -> Params:
    """Place a stage's parameter shard on the mesh with TP/EP layout."""
    return jax.tree.map(
        lambda leaf, spec: jax.device_put(leaf, NamedSharding(mesh, spec)),
        params, stage_param_specs(cfg, params, axis),
    )


def validate_tp(cfg: ModelConfig, tp: int) -> None:
    from ..models.config import custom_engine_unsupported

    reason = custom_engine_unsupported(cfg)
    if reason:
        # stage_forward would compute correctly, but the param-spec table
        # has no layout for the per-layer window leaf and the softcap has
        # no shard_map test coverage — refuse until implemented.
        raise ValueError(f"tensor parallelism: {reason}")
    if cfg.num_heads % tp:
        raise ValueError(f"num_heads {cfg.num_heads} % tp {tp} != 0")
    if cfg.num_kv_heads % tp:
        raise ValueError(
            f"num_kv_heads {cfg.num_kv_heads} % tp {tp} != 0 "
            "(GQA cache shards over kv heads)"
        )
    if cfg.is_moe and cfg.num_experts % tp:
        raise ValueError(f"num_experts {cfg.num_experts} % tp {tp} != 0")
    if not cfg.is_moe and cfg.intermediate_size % tp:
        raise ValueError(f"intermediate_size {cfg.intermediate_size} % tp != 0")


def make_tp_stage_fn(
    cfg: ModelConfig,
    spec: StageSpec,
    mesh: Mesh,
    axis: str = "tp",
    donate_cache: bool = False,
    with_prompts: bool = False,
):
    """Jitted TP stage forward. Caller passes params placed by
    `shard_stage_params` and a KV cache sharded over kv heads
    ([L, B, S, Hkv, Dh] with spec P(None, None, None, axis)).

    Returns fn(params, x, k, v, cache_len) -> (out, k, v); out replicated.
    `donate_cache=True` donates the k/v buffers (serving: the caller
    threads the returned cache and never reuses the input arrays).
    `with_prompts=True` appends a replicated deep-prompts argument
    ([span, pre, D], injected at every block entry — the ptune serving
    path): fn(params, x, k, v, cache_len, prompts).
    """
    tp = mesh.shape[axis]
    validate_tp(cfg, tp)
    kv_spec = P(None, None, None, axis)

    def build(params_example: Params):
        param_specs = stage_param_specs(cfg, params_example, axis)
        in_specs = (param_specs, P(), kv_spec, kv_spec, P())
        if with_prompts:
            in_specs = in_specs + (P(),)   # prompts replicated across tp

        def fn(params, x, k_cache, v_cache, cache_len, prompts=None):
            out, k_cache, v_cache = stage_forward(
                cfg, spec, params, x, k_cache, v_cache, cache_len,
                tp_axis=axis, prompts=prompts,
            )
            # out is replicated by the closing psums (vma: psum output is
            # axis-invariant), matching out_specs=P().
            return out, k_cache, v_cache

        fn = partial(jax.shard_map, mesh=mesh, in_specs=in_specs,
                     out_specs=(P(), kv_spec, kv_spec))(fn)
        return partial(jax.jit,
                       donate_argnums=(2, 3) if donate_cache else ())(fn)

    return build


def init_tp_kv(
    cfg: ModelConfig, spec: StageSpec, mesh: Mesh, batch: int, max_len: int,
    dtype=jnp.float32, axis: str = "tp",
):
    shape = (max(spec.num_layers, 1), batch, max_len, cfg.num_kv_heads,
             cfg.head_dim)
    sh = NamedSharding(mesh, P(None, None, None, axis))
    return (jax.device_put(jnp.zeros(shape, dtype), sh),
            jax.device_put(jnp.zeros(shape, dtype), sh))
