"""SPMD sharding-discipline invariants (phase 3).

The GSPMD-lineage failure mode: a parameter path that silently falls to
the replicated catch-all costs the entire sharding win (or, for a weight
with a sharded sibling, correctness after the closing psum) — and nothing
catches it before an on-chip run. Four rule groups:

  * ``spmd-catchall-leaf``: every shardable model-tree leaf path (statically
    extracted from ``init_layer_params``'s dict-literal / subscript-store
    structure, plus per-layer leaves ``init_params`` adds to the stacked
    tree) must match a non-catch-all ``tp_partition_rules`` regex in some
    config variant, or match an entry of the ``REPLICATED_LEAVES``
    (regex, reason) table next to the rules — replication must be a
    decision with a written reason, never a fall-through.
  * ``spmd-replicated-no-reason``: a REPLICATED_LEAVES entry whose reason
    is empty — the table exists to carry the why.
  * ``spmd-rule-shadowed``: first-regex-wins means an earlier rule can
    subsume a later one; a non-catch-all rule that is never the first
    match for any corpus path in any variant it appears in is dead weight
    (and very likely a misordered edit).
  * ``spmd-axis-unbound``: a collective (``psum``/``all_gather``/
    ``axis_index``/``ppermute``/...) naming a string-literal axis must be
    reachable — via the shared :class:`astutil.CallGraph` walker — from a
    function traced by ``shard_map``/``pmap`` (or sit lexically inside a
    ``shard_map`` lambda). An unbound axis name raises only at trace time
    on-TPU; the lint moves that to tier-1.
  * donation discipline at the ``donate_argnums`` sites:
    ``spmd-missed-donation`` — a caller's loop rebinds a buffer through a
    jitted step whose donate set omits that position (double KV memory);
    ``spmd-use-after-donate`` — a donated argument is read after the
    jitted call (garbage on TPU, where donation really invalidates).

Precision notes. The leaf corpus and the rule table are parsed, never
imported; config-conditional branches (moe vs dense mlp, bias toggles)
become VARIANTS, and a leaf is covered when ANY variant covers it —
branches mirror the config that creates the leaf, which a no-import
analyzer cannot correlate. Collectives with non-literal axis arguments are
the caller's responsibility and exempt. Donation checks only apply to
callables that declare a donate set (``donate_argnums`` decorators,
``engine_donation``, jit-call assignments, and constructor-kwarg wiring
like ``RingDecoder(_step=step)``); a name that maps to conflicting donate
sets is dropped as ambiguous rather than guessed.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from . import astutil
from .core import Context, Finding

COLLECTIVES = {
    "psum", "pmean", "pmax", "pmin", "all_gather", "all_to_all",
    "psum_scatter", "ppermute", "pshuffle", "pbroadcast", "axis_index",
}
SPMD_WRAPPERS = {"shard_map", "pmap", "xmap"}
CATCHALL = {".*", "^.*$"}


# ---------------------------------------------------------------------------
# Leaf corpus: the shardable model tree, parsed from the init functions
# ---------------------------------------------------------------------------

def _dict_paths(d: ast.Dict, prefix: str, out: Dict[str, int]) -> None:
    for k, v in zip(d.keys, d.values):
        key = astutil.str_const(k) if k is not None else None
        if key is None:
            continue
        path = f"{prefix}/{key}" if prefix else key
        if isinstance(v, ast.Dict):
            _dict_paths(v, path, out)
        else:
            out.setdefault(path, v.lineno)


def _store_path(node: ast.Subscript) -> Optional[Tuple[str, List[str]]]:
    """``p["attn"]["bq"]`` -> ("p", ["attn", "bq"]); None if dynamic."""
    keys: List[str] = []
    while isinstance(node, ast.Subscript):
        sl = node.slice
        if isinstance(sl, ast.Index):     # pragma: no cover — py<3.9 only
            sl = sl.value
        key = astutil.str_const(sl)
        if key is None:
            return None
        keys.append(key)
        node = node.value
    if isinstance(node, ast.Name):
        return node.id, list(reversed(keys))
    return None


def _leaf_corpus(ctx: Context):
    """(paths -> first line, module rel) for the per-layer shardable tree,
    or None when no ``init_layer_params`` exists (fixture trees opt in by
    defining one)."""
    for mod in ctx.modules:
        fns = {qual.split(".")[-1]: fn
               for qual, _cls, fn in astutil.walk_functions(mod.tree)}
        init_layer = fns.get("init_layer_params")
        if init_layer is None:
            continue
        paths: Dict[str, int] = {}
        roots = {node.value.id for node in ast.walk(init_layer)
                 if isinstance(node, ast.Return)
                 and isinstance(node.value, ast.Name)}
        for node in astutil.scope_walk(init_layer):
            if (isinstance(node, ast.AnnAssign)
                    and isinstance(node.target, ast.Name)
                    and node.target.id in roots
                    and isinstance(node.value, ast.Dict)):
                _dict_paths(node.value, "", paths)
            elif isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name) and t.id in roots \
                            and isinstance(node.value, ast.Dict):
                        _dict_paths(node.value, "", paths)
                    elif isinstance(t, ast.Subscript):
                        sp = _store_path(t)
                        if sp is None or sp[0] not in roots:
                            continue
                        prefix = "/".join(sp[1])
                        if isinstance(node.value, ast.Dict):
                            _dict_paths(node.value, prefix, paths)
                        else:
                            paths.setdefault(prefix, node.lineno)
        # Per-layer leaves init_params adds to the STACKED tree (e.g. the
        # gemma2 `window` vector): subscript stores on the variable bound
        # to the returned dict's "layers" key.
        init_params = fns.get("init_params")
        if init_params is not None:
            layer_vars: Set[str] = set()
            for node in astutil.scope_walk(init_params):
                if isinstance(node, ast.Dict):
                    for k, v in zip(node.keys, node.values):
                        if (k is not None
                                and astutil.str_const(k) == "layers"
                                and isinstance(v, ast.Name)):
                            layer_vars.add(v.id)
            for node in astutil.scope_walk(init_params):
                if (isinstance(node, ast.Assign)
                        and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Subscript)):
                    sp = _store_path(node.targets[0])
                    if sp and sp[0] in layer_vars:
                        paths.setdefault("/".join(sp[1]), node.lineno)
        return paths, mod.rel
    return None


# ---------------------------------------------------------------------------
# Partition-rule table: variants, coverage, shadowing
# ---------------------------------------------------------------------------

def _rule_tuples(node: ast.AST) -> Optional[List[Tuple[str, int]]]:
    """A tuple-of-(regex, spec) literal -> [(regex, line)], else None."""
    if not isinstance(node, (ast.Tuple, ast.List)):
        return None
    out = []
    for elt in node.elts:
        if not (isinstance(elt, ast.Tuple) and len(elt.elts) >= 2):
            return None
        rx = astutil.str_const(elt.elts[0])
        if rx is None:
            return None
        out.append((rx, elt.lineno))
    return out


def _rule_variants(fn: ast.AST) -> List[List[Tuple[str, int]]]:
    """Expand ``return (*attn, *mlp, catchall)`` over every branch
    assignment of the starred names: the cross-product of per-name
    choices, each an ordered rule list."""
    choices: Dict[str, List[List[Tuple[str, int]]]] = {}
    for node in astutil.scope_walk(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            rules = _rule_tuples(node.value)
            if rules is not None:
                choices.setdefault(node.targets[0].id, []).append(rules)
    ret = next((n for n in astutil.scope_walk(fn)
                if isinstance(n, ast.Return)
                and isinstance(n.value, ast.Tuple)), None)
    if ret is None:
        return []
    variants: List[List[Tuple[str, int]]] = [[]]
    for elt in ret.value.elts:
        if isinstance(elt, ast.Starred) and isinstance(elt.value, ast.Name):
            opts = choices.get(elt.value.id)
            if not opts:
                continue
            variants = [v + opt for v in variants for opt in opts]
        else:
            direct = _rule_tuples(ast.Tuple(elts=[elt], ctx=ast.Load())) \
                if isinstance(elt, ast.Tuple) else None
            if direct:
                variants = [v + direct for v in variants]
    return variants


def _replicated_table(mod: astutil.Module):
    """Module-level ``REPLICATED_LEAVES = ((regex, reason), ...)`` ->
    [(regex, reason, line)]."""
    for node in mod.tree.body:
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == "REPLICATED_LEAVES"
                and isinstance(node.value, (ast.Tuple, ast.List))):
            out = []
            for elt in node.value.elts:
                if isinstance(elt, ast.Tuple) and len(elt.elts) == 2:
                    rx = astutil.str_const(elt.elts[0])
                    reason = astutil.str_const(elt.elts[1])
                    if rx is not None:
                        out.append((rx, reason or "", elt.lineno))
            return out
    return []


def _coverage_findings(ctx: Context) -> List[Finding]:
    findings: List[Finding] = []
    corpus = _leaf_corpus(ctx)
    rules_fn = rules_mod = None
    for mod in ctx.modules:
        for qual, _cls, fn in astutil.walk_functions(mod.tree):
            if qual.split(".")[-1] == "tp_partition_rules":
                rules_fn, rules_mod = fn, mod
                break
        if rules_fn:
            break
    if corpus is None or rules_fn is None:
        return findings
    paths, corpus_rel = corpus
    variants = _rule_variants(rules_fn)
    replicated = _replicated_table(rules_mod)

    for rx, reason, line in replicated:
        if not reason.strip():
            findings.append(Finding(
                "spmd-replicated-no-reason", rules_mod.rel, line, rx,
                f"REPLICATED_LEAVES entry `{rx}` has no reason — explicit "
                "replication must say why the leaf stays whole"))

    for path in sorted(paths):
        covered = any(
            re.search(rx, path)
            for variant in variants
            for rx, _line in variant if rx not in CATCHALL)
        covered = covered or any(
            re.search(rx, path) for rx, _r, _l in replicated)
        if not covered:
            findings.append(Finding(
                "spmd-catchall-leaf", corpus_rel, paths[path], path,
                f"model leaf `{path}` matches no non-catch-all "
                "tp_partition_rules regex and no REPLICATED_LEAVES entry — "
                "it replicates by fall-through, not by decision"))

    # Shadowing: per variant, which rule wins first for each path.
    first_wins: Dict[Tuple[str, int], bool] = {}
    matches_any: Dict[Tuple[str, int], bool] = {}
    for variant in variants:
        for path in paths:
            winner = next(((rx, line) for rx, line in variant
                           if re.search(rx, path)), None)
            for rx, line in variant:
                if rx in CATCHALL:
                    continue
                hit = bool(re.search(rx, path))
                matches_any[(rx, line)] = matches_any.get(
                    (rx, line), False) or hit
                first_wins[(rx, line)] = first_wins.get(
                    (rx, line), False) or ((rx, line) == winner)
    for (rx, line), wins in sorted(first_wins.items(),
                                   key=lambda kv: kv[0][1]):
        if wins:
            continue
        kind = ("shadowed by an earlier rule"
                if matches_any.get((rx, line)) else
                "matches no model leaf at all (dead)")
        findings.append(Finding(
            "spmd-rule-shadowed", rules_mod.rel, line, rx,
            f"partition rule `{rx}` is never the first match for any "
            f"model leaf in any config variant — {kind}; first-regex-wins "
            "makes it unreachable"))
    return findings


# ---------------------------------------------------------------------------
# Axis binding: collectives must be reachable from an SPMD-traced root
# ---------------------------------------------------------------------------

def _spmd_roots(mods: List[astutil.Module], graph: astutil.CallGraph):
    """(root keys, lexically-bound lambda nodes) from shard_map/pmap call
    sites. A bare-Name first argument matches every same-module def of
    that name — the factory idiom (``body = _ring_body(...)`` closing over
    a nested ``def body``) resolves by name, deliberately over-approximate
    in the safe direction (fewer false unbound findings)."""
    roots: Set[Tuple[str, str]] = set()
    bound_lambdas: Set[int] = set()
    for mod in mods:
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call)
                    and astutil.terminal_attr(node) in SPMD_WRAPPERS):
                continue
            target = node.args[0] if node.args else next(
                (kw.value for kw in node.keywords if kw.arg == "f"), None)
            if (isinstance(target, ast.Call)
                    and astutil.terminal_attr(target) == "partial"
                    and target.args):
                target = target.args[0]
            if isinstance(target, ast.Lambda):
                for sub in ast.walk(target):
                    bound_lambdas.add(id(sub))
            elif isinstance(target, ast.Name):
                for key in graph.funcs:
                    if (key[0] == mod.rel
                            and key[1].split(".")[-1] == target.id):
                        roots.add(key)
            elif isinstance(target, ast.Attribute):
                owner = astutil.is_self_attr(target)
                if owner:
                    for key in graph.funcs:
                        if key[1].split(".")[-1] == owner:
                            roots.add(key)
    return roots, bound_lambdas


def _axis_findings(ctx: Context, graph: astutil.CallGraph) -> List[Finding]:
    findings: List[Finding] = []
    roots, bound_lambdas = _spmd_roots(ctx.modules, graph)
    reachable = graph.reachable(roots)
    for (rel, qual), (fn, _cls) in graph.funcs.items():
        if (rel, qual) in reachable:
            continue
        for node in astutil.scope_walk(fn):
            if not (isinstance(node, ast.Call)
                    and astutil.terminal_attr(node) in COLLECTIVES):
                continue
            if id(node) in bound_lambdas:
                continue
            axis = next(
                (s for s in ([astutil.str_const(a) for a in node.args]
                             + [astutil.str_const(kw.value)
                                for kw in node.keywords
                                if kw.arg in ("axis_name", "axis")])
                 if s is not None), None)
            if axis is None:
                continue                     # caller-bound axis: exempt
            coll = astutil.terminal_attr(node)
            findings.append(Finding(
                "spmd-axis-unbound", rel, node.lineno,
                f"{qual}:{coll}:{axis}",
                f"collective `{coll}` names axis '{axis}' but `{qual}` is "
                "not reachable from any shard_map/pmap-traced function — "
                "an unbound axis name fails only at trace time on-TPU"))
    return findings


# ---------------------------------------------------------------------------
# Donation discipline
# ---------------------------------------------------------------------------

def _donate_set(call: ast.Call) -> Optional[Set[int]]:
    """donate_argnums from a jit-ish call, or engine_donation(a, b)."""
    name = astutil.terminal_attr(call)
    if name == "engine_donation":
        out = set()
        for a in call.args:
            if isinstance(a, ast.Constant) and isinstance(a.value, int):
                out.add(a.value)
        return out or None
    for kw in call.keywords:
        if kw.arg != "donate_argnums":
            continue
        v = kw.value
        elts = v.elts if isinstance(v, (ast.Tuple, ast.List)) else [v]
        out = set()
        for e in elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, int):
                out.add(e.value)
        return out or None
    return None


def _donation_census(mods: List[astutil.Module]):
    """(names, attrs): bare callable name -> donate set (decorators and
    jit-call assignments) and attribute name -> donate set (constructor-
    kwarg wiring like ``RingDecoder(_step=step)`` and ``self._step =
    jax.jit(...)`` stores). Split so a bare call never matches through an
    unrelated method of the same name. Conflicting sets for one name drop
    the name (ambiguous beats wrong)."""
    names: Dict[str, Set[int]] = {}
    attrs: Dict[str, Set[int]] = {}
    conflicted: Set[Tuple[int, str]] = set()

    def put(census: Dict[str, Set[int]], name: str, dset: Set[int]):
        tag = (id(census), name)
        if tag in conflicted:
            return
        if name in census and census[name] != dset:
            del census[name]
            conflicted.add(tag)
            return
        census[name] = dset

    for mod in mods:
        for qual, _cls, fn in astutil.walk_functions(mod.tree):
            for dec in getattr(fn, "decorator_list", []):
                if not isinstance(dec, ast.Call):
                    continue
                dset = _donate_set(dec)
                if dset:
                    put(names, fn.name, dset)
        for node in ast.walk(mod.tree):
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.value, ast.Call)):
                dset = _donate_set(node.value)
                if not dset:
                    continue
                t = node.targets[0]
                if isinstance(t, ast.Name):
                    put(names, t.id, dset)
                else:
                    attr = astutil.is_self_attr(t)
                    if attr:
                        put(attrs, attr, dset)
    # Constructor kwargs aliasing a donating callable to an attribute
    # (RingDecoder(_step=step) -> self._step donates like step).
    for mod in mods:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            for kw in node.keywords:
                if (kw.arg and isinstance(kw.value, ast.Name)
                        and kw.value.id in names):
                    put(attrs, kw.arg, names[kw.value.id])
    return names, attrs


def _donation_findings(ctx: Context,
                       graph: astutil.CallGraph) -> List[Finding]:
    findings: List[Finding] = []
    name_census, attr_census = _donation_census(ctx.modules)
    if not (name_census or attr_census):
        return findings
    for (rel, qual), (fn, _cls) in graph.funcs.items():
        parents = None
        for node in astutil.scope_walk(fn):
            if not isinstance(node, ast.Call):
                continue
            callee = astutil.terminal_attr(node)
            if isinstance(node.func, ast.Name):
                dset = name_census.get(callee)
            else:
                dset = attr_census.get(callee)
            if not dset:
                continue
            if parents is None:
                parents = astutil.enclosing_map(fn)
            # The call's own assignment targets (rebinding counts as the
            # donation-safe pattern) and loop context.
            targets: Set[str] = set()
            in_loop = False
            cur = node
            while cur in parents:
                cur = parents[cur]
                if isinstance(cur, (ast.For, ast.While)):
                    in_loop = True
                if isinstance(cur, ast.Assign):
                    for t in cur.targets:
                        for el in ([t.elts] if isinstance(
                                t, (ast.Tuple, ast.List)) else [[t]]):
                            targets.update(e.id for e in el
                                           if isinstance(e, ast.Name))
            donated_names = {node.args[p].id: p for p in dset
                            if p < len(node.args)
                            and isinstance(node.args[p], ast.Name)}
            # use-after-donate
            for n, p in donated_names.items():
                if in_loop and n not in targets:
                    stored_in_fn = any(
                        isinstance(x, ast.Name) and x.id == n
                        and isinstance(x.ctx, ast.Store)
                        for x in astutil.scope_walk(fn))
                    if not stored_in_fn:
                        findings.append(Finding(
                            "spmd-use-after-donate", rel, node.lineno,
                            f"{qual}:{n}",
                            f"`{n}` is donated at position {p} of "
                            f"`{callee}` inside a loop but never rebound — "
                            "the next iteration reads a donated buffer"))
                    continue
                loads_after = sorted(
                    x.lineno for x in astutil.scope_walk(fn)
                    if isinstance(x, ast.Name) and x.id == n
                    and isinstance(x.ctx, ast.Load)
                    and x.lineno > node.lineno)
                stores = sorted(
                    x.lineno for x in astutil.scope_walk(fn)
                    if isinstance(x, ast.Name) and x.id == n
                    and isinstance(x.ctx, ast.Store))
                for ll in loads_after:
                    if not any(node.lineno <= s <= ll for s in stores):
                        findings.append(Finding(
                            "spmd-use-after-donate", rel, node.lineno,
                            f"{qual}:{n}",
                            f"`{n}` is donated at position {p} of "
                            f"`{callee}` but read again at line {ll} — "
                            "a donated buffer is garbage on TPU"))
                        break
            # missed-donation: a buffer carried through the loop (arg AND
            # assignment target) at a position the donate set omits.
            if in_loop:
                for p, a in enumerate(node.args):
                    if (isinstance(a, ast.Name) and a.id in targets
                            and p not in dset
                            and a.id not in donated_names):
                        findings.append(Finding(
                            "spmd-missed-donation", rel, node.lineno,
                            f"{qual}:{a.id}",
                            f"`{a.id}` is rebound through `{callee}` every "
                            f"iteration but position {p} is not in its "
                            "donate_argnums — the old buffer survives the "
                            "step (double memory for carried state)"))
    return findings


def analyze(ctx: Context) -> List[Finding]:
    graph = astutil.CallGraph(ctx.modules)
    findings = _coverage_findings(ctx)
    findings += _axis_findings(ctx, graph)
    findings += _donation_findings(ctx, graph)
    return findings
