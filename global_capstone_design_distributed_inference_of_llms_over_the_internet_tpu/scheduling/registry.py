"""Placement registry: the service-discovery layer (DHT-schema mirror).

The reference's control plane is a Kademlia DHT (``src/dht_utils.py``) storing
three kinds of records:

  * ``mini_petals:stage{N}``  -> {subkey=peer_id: (value, expiration)} — one
    record per pipeline stage, many servers per stage (``src/main.py:517-527``);
  * ``petals:module:<model>:block_i`` -> same, one record per transformer
    block, used by load balancing + module routing (``src/dht_utils.py:82-133``);
  * ``petals:server:<model>:<peer_id>`` -> server info blob
    (``src/dht_utils.py:34-79``).

On a TPU pod the ICI topology is static, so the hot path needs no discovery at
all (SURVEY.md §2.3); this registry exists for the *elastic multi-host* story:
servers register/heartbeat with a TTL, dead servers expire, clients discover
and load balancing reads coverage. Single-process implementation with the same
record schema; a multi-host deployment points every process at one registry
service (see runtime.dcn) — the schema is the contract, the backend is
swappable.

TTL/liveness semantics preserved: records expire TTL seconds after their last
refresh (reference default 45s, refreshed every TTL/3 — ``src/main.py:520-537``);
discovery prefers the newest records and picks randomly among the 5 freshest
(``src/rpc_transport.py:337-344``).
"""

from __future__ import annotations

import dataclasses
import random
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

from ..telemetry import events as _ev

DEFAULT_TTL = 45.0          # src/main.py:524
DISCOVERY_POOL = 5          # random among 5 newest, src/rpc_transport.py:337-344


class ServerState:
    """Lifecycle states (``src/load_balancing.py:17-21``)."""

    JOINING = "joining"
    ONLINE = "online"
    OFFLINE = "offline"


@dataclasses.dataclass
class ServerRecord:
    """One server's registration (the DHT value at ``src/dht_utils.py:57-67``)."""

    peer_id: str
    start_block: int
    end_block: int
    throughput: float = 1.0
    state: str = ServerState.ONLINE
    final_stage: bool = False
    # Which model this server's span belongs to. Every reference DHT key
    # embeds the model name (``src/dht_utils.py:20-31``,
    # ``petals/server/server.py:738-744``) so multiple models can share one
    # control plane; records with different models never cross-route. None =
    # single-model swarm (matches any query — the pre-multi-model schema).
    model: Optional[str] = None
    # Serving engine capability: "session" (per-session executor — the full
    # protocol incl. beam/speculative/replay) or "batched" (continuous
    # slot-batched decode — plain prefill/decode only, but one compiled step
    # serves every concurrent session). Clients prefer batched peers for
    # plain sessions and per-session peers for the exotic verbs; the
    # reference's serving runtime is batch-first throughout
    # (petals/server/server.py:557-671).
    engine: str = "session"
    # engine="sp": the advertised long-context admission limit (prompt +
    # generated tokens) — prefix KV shards across the server's mesh, so this
    # scales with its device count. None for other engines.
    max_context: Optional[int] = None
    stage_index: Optional[int] = None      # fixed-split mode stage number
    cache_tokens_left: Optional[int] = None  # petals/server/server.py:721
    address: Optional[str] = None          # "host:port" for the TCP data plane
    # Measured RTTs (seconds) to likely next-hop peers, published with each
    # heartbeat — the _ping_next_servers signal (petals/server/server.py:760-767)
    # consumed by scheduling.routing's latency-aware planner.
    next_server_rtts: Optional[Dict[str, float]] = None
    # NAT relay data plane (petals/server/reachability.py): a server that
    # fails the dial-back vote attaches to a reachable volunteer and sets
    # relay_via to that volunteer's peer_id. Its `address` stays its OWN
    # advertised (unreachable) address; clients resolve relay_via -> the
    # volunteer's record and dial the volunteer instead, stamping frames
    # with relay_to so the volunteer forwards verbatim.
    relay_via: Optional[str] = None
    # Volunteer capability: how many relayed peers this server is willing to
    # forward for (0/None = does not volunteer). Attach requests beyond this
    # are shed with an error frame so load spreads across volunteers.
    relay_capacity: Optional[int] = None
    timestamp: float = dataclasses.field(default_factory=time.monotonic)
    expires_at: float = 0.0

    def expired(self, now: Optional[float] = None) -> bool:
        return (now or time.monotonic()) >= self.expires_at


# Wire schema for ServerRecord: the field set shipped by the registry
# service's register/list verbs AND by gossip deltas. Owned here (beside the
# dataclass) so every control-plane surface — runtime.net's RegistryServer,
# the gossip mirrors, the peers-cache file — serializes identically.
# `timestamp`/`expires_at` are deliberately absent: they are time.monotonic()
# values, meaningless across hosts; freshness crosses the wire as RELATIVE
# age/TTL-remaining and is re-anchored on receipt.
REC_FIELDS = ("peer_id", "start_block", "end_block", "throughput", "state",
              "final_stage", "stage_index", "cache_tokens_left", "address",
              "next_server_rtts", "model", "engine", "max_context",
              "relay_via", "relay_capacity")


def rec_to_dict(rec: "ServerRecord") -> dict:
    return {f: getattr(rec, f) for f in REC_FIELDS}


def dict_to_rec(d: dict) -> "ServerRecord":
    vals = {f: d.get(f) for f in REC_FIELDS}
    if vals.get("engine") is None:      # record from a pre-engine peer
        vals["engine"] = "session"
    return ServerRecord(**vals)


def _model_ok(rec: ServerRecord, model: Optional[str]) -> bool:
    """Model filter for discovery/coverage queries: a query for model M sees
    M's records plus legacy untagged ones; a query with no model sees all
    (single-model swarm). Mirrors the reference's model-prefixed DHT keys
    (``src/dht_utils.py:20-31``) — two models on one registry must never
    cross-route."""
    return model is None or rec.model is None or rec.model == model


class PlacementRegistry:
    """In-process registry with TTL liveness. Thread-safe."""

    def __init__(self, ttl: float = DEFAULT_TTL, rng: Optional[random.Random] = None):
        self.ttl = ttl
        self._lock = threading.Lock()
        self._servers: Dict[str, ServerRecord] = {}
        # Seeded default: choose_server tie-breaks must replay identically.
        self._rng = rng or random.Random(0)

    # -- registration / heartbeat ------------------------------------------

    def register(self, record: ServerRecord, ttl: Optional[float] = None) -> None:
        """Register or refresh a server (covers both ``register_server_on_dht``
        and ``register_blocks_on_dht`` — block coverage is derived from the
        span, there is no separate per-block write to keep consistent)."""
        now = time.monotonic()
        record.timestamp = now
        record.expires_at = now + (ttl if ttl is not None else self.ttl)
        with self._lock:
            self._servers[record.peer_id] = record

    def heartbeat(self, peer_id: str, throughput: Optional[float] = None,
                  cache_tokens_left: Optional[int] = None,
                  next_server_rtts: Optional[Dict[str, float]] = None) -> bool:
        """Refresh TTL (+ optionally throughput, mirroring
        ``update_server_throughput_on_dht``). Returns False if unknown."""
        now = time.monotonic()
        with self._lock:
            rec = self._servers.get(peer_id)
            if rec is None:
                return False
            rec.timestamp = now
            rec.expires_at = now + self.ttl
            if throughput is not None:
                rec.throughput = throughput
            if cache_tokens_left is not None:
                rec.cache_tokens_left = cache_tokens_left
            if next_server_rtts is not None:
                rec.next_server_rtts = dict(next_server_rtts)
            return True

    def unregister(self, peer_id: str) -> None:
        with self._lock:
            self._servers.pop(peer_id, None)

    def set_state(self, peer_id: str, state: str) -> None:
        with self._lock:
            rec = self._servers.get(peer_id)
            if rec is not None:
                rec.state = state

    def age_records(self, seconds: float) -> int:
        """Rewind every record's freshness by `seconds` (timestamp AND
        expiry), as if the registry stopped seeing heartbeats that long ago.
        Fault-injection surface (``runtime.faults`` kind
        ``stale_registry``): models a partitioned/lagging control plane —
        discovery keeps answering from aged records until TTL expiry culls
        them, exactly the staleness window a real outage produces. Returns
        the number of records aged."""
        with self._lock:
            for rec in self._servers.values():
                rec.timestamp -= seconds
                rec.expires_at -= seconds
            return len(self._servers)

    # -- queries ------------------------------------------------------------

    def _live(self, now: Optional[float] = None,
              model: Optional[str] = None) -> List[ServerRecord]:
        now = now or time.monotonic()
        with self._lock:
            # Purge expired entries on read (the DHT does this implicitly).
            dead = [p for p, r in self._servers.items() if r.expired(now)]
            for p in dead:
                del self._servers[p]
            live = [r for r in self._servers.values()
                    if _model_ok(r, model)]
        for p in dead:
            _ev.emit("registry_expired", peer=p)
        return live

    def live_servers(self, model: Optional[str] = None) -> List[ServerRecord]:
        return self._live(model=model)

    def get(self, peer_id: str) -> Optional[ServerRecord]:
        with self._lock:
            rec = self._servers.get(peer_id)
            if rec is not None and rec.expired():
                del self._servers[peer_id]
                rec = None
                expired = True
            else:
                expired = False
        if expired:
            _ev.emit("registry_expired", peer=peer_id)
        return rec

    def discover_stage(self, stage_index: int,
                       exclude: Sequence[str] = (),
                       model: Optional[str] = None,
                       prefer_engine: Optional[str] = None,
                       avoid_engine=None,
                       min_context: Optional[int] = None,
                       affinity: Optional[str] = None) -> Optional[str]:
        """Pick a server for a fixed-split stage: random among the 5 newest
        live candidates, excluding known-failed peers
        (``src/rpc_transport.py:270-353``). `prefer_engine` narrows to that
        engine when any such candidate exists (soft); `avoid_engine` (one
        name or a sequence) drops those candidates unless nothing else
        remains (a session that a batched/sp peer would refuse should not be
        routed to one). `affinity` (a prompt-head digest) replaces the
        random choice with a rendezvous hash — see `_pick_newest`."""
        cands = [
            r for r in self._live(model=model)
            if r.stage_index == stage_index and r.peer_id not in exclude
            and r.state == ServerState.ONLINE
        ]
        if min_context is not None:
            # An sp peer advertising less context than the session needs
            # WILL refuse its prefill — hard-drop those.
            cands = [r for r in cands
                     if r.engine != "sp" or r.max_context is None
                     or r.max_context >= min_context]
        if avoid_engine is not None:
            avoid = ((avoid_engine,) if isinstance(avoid_engine, str)
                     else tuple(avoid_engine))
            kept = [r for r in cands if r.engine not in avoid]
            if kept:
                cands = kept
        if prefer_engine is not None:
            preferred = [r for r in cands if r.engine == prefer_engine]
            if preferred:
                cands = preferred
        return self._pick_newest(cands, affinity=affinity)

    def discover_block(self, block: int, exclude: Sequence[str] = (),
                       model: Optional[str] = None) -> List[ServerRecord]:
        """All live ONLINE servers covering `block` (module-routing mode)."""
        return [
            r for r in self._live(model=model)
            if r.start_block <= block < r.end_block and r.peer_id not in exclude
            and r.state == ServerState.ONLINE
        ]

    def _pick_newest(self, cands: List[ServerRecord],
                     affinity: Optional[str] = None) -> Optional[str]:
        if not cands:
            return None
        if affinity is not None and len(cands) > 1:
            # Prefix-cache-aware replica choice (no reference counterpart):
            # rendezvous hash over (affinity, peer) — every client holding
            # the same prompt head lands on the SAME replica with zero
            # coordination, so its prefix store actually gets hits across
            # clients; distinct prompt heads spread uniformly. When the
            # chosen replica dies it simply leaves the candidate set and
            # only its share of prompts re-hashes elsewhere. Hashes over
            # ALL live candidates — the freshness-pool restriction below
            # would make the winner depend on heartbeat ordering, breaking
            # cross-client stability exactly when replicas are plentiful.
            import hashlib

            return max(cands, key=lambda r: hashlib.sha1(
                (affinity + r.peer_id).encode()).digest()).peer_id
        cands.sort(key=lambda r: r.timestamp, reverse=True)
        pool = cands[:DISCOVERY_POOL]
        return self._rng.choice(pool).peer_id

    def coverage(self, total_blocks: int,
                 model: Optional[str] = None) -> List[List[ServerRecord]]:
        """Per-block server lists — the shape of ``get_remote_module_infos``
        (``src/dht_utils.py:147-242``); feeds load balancing."""
        live = self._live(model=model)
        return [
            [r for r in live if r.start_block <= b < r.end_block]
            for b in range(total_blocks)
        ]
