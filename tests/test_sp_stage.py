"""Sequence-parallel stage serving (parallel.sp_stage): the KV prefix cache
sharded across the mesh, decode via cross-device softmax combine — asserted
token-identical to the single-device oracle.

The reference has no long-context mechanism beyond single-server chunked
prefill (SURVEY.md §5.7); this engine is the exceed-the-reference
capability: P devices hold P× the context at fixed per-device HBM.
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.models import (
    full_forward,
    gpt2_config,
    init_kv_cache,
    init_params,
    llama_config,
    qwen2_config,
)
from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.models.partition import (
    ROLE_FULL,
    StagePlan,
    StageSpec,
    parse_splits,
    slice_stage_params,
)
from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.parallel.sp_stage import (
    SpStageRunner,
)

P_DEV = 8


def sp_mesh():
    return Mesh(np.array(jax.devices()[:P_DEV]), ("sp",))


def tiny(family="llama"):
    kw = dict(vocab_size=257, hidden_size=64, num_layers=4, num_heads=4,
              max_position_embeddings=256)
    if family == "gpt2":
        return gpt2_config(**kw)
    kw.update(num_kv_heads=2, intermediate_size=128)
    if family == "qwen2":
        return qwen2_config(**kw)
    return llama_config(**kw)


def full_spec(cfg):
    return StageSpec(index=0, role=ROLE_FULL, start=0, end=cfg.num_layers)


def oracle_tokens(cfg, params, prompt, n_new):
    kc, vc = init_kv_cache(cfg, cfg.num_layers, 1, 128)
    ids = jnp.asarray(np.asarray(prompt, np.int32)[None, :])
    logits, kc, vc = full_forward(cfg, params, ids, kc, vc, jnp.int32(0))
    out = [int(jnp.argmax(logits[0, -1]))]
    cur = len(prompt)
    for _ in range(n_new - 1):
        logits, kc, vc = full_forward(
            cfg, params, jnp.asarray([[out[-1]]], jnp.int32), kc, vc,
            jnp.int32(cur))
        out.append(int(jnp.argmax(logits[0, -1])))
        cur += 1
    return out


def sp_generate(runner, prompt, n_new):
    h = runner.prefill(np.asarray(prompt, np.int32)[None, :])
    tok = int(jnp.argmax(runner.logits_at(h, len(prompt) - 1)[0]))
    out = [tok]
    for _ in range(n_new - 1):
        h = runner.decode(jnp.asarray([[out[-1]]], jnp.int32))
        tok = int(jnp.argmax(runner.logits_at(h, 0)[0]))
        out.append(tok)
    return out


def test_sp_full_model_matches_oracle_llama():
    cfg = tiny()
    params = init_params(jax.random.PRNGKey(0), cfg)
    runner = SpStageRunner(cfg, full_spec(cfg), params, sp_mesh())
    prompt = [5, 9, 23, 7, 81, 2, 14, 3, 19, 44, 6, 77, 8, 1, 90, 33,
              12, 4, 56, 21, 9, 100, 41, 2]          # T=24 -> chunk 3
    ref = oracle_tokens(cfg, params, prompt, 6)
    got = sp_generate(runner, prompt, 6)
    assert got == ref


def test_sp_full_model_matches_oracle_gpt2_and_qwen2():
    for family in ("gpt2", "qwen2"):
        cfg = tiny(family)
        params = init_params(jax.random.PRNGKey(1), cfg)
        runner = SpStageRunner(cfg, full_spec(cfg), params, sp_mesh())
        prompt = list(range(7, 7 + 16))               # T=16 -> chunk 2
        ref = oracle_tokens(cfg, params, prompt, 5)
        got = sp_generate(runner, prompt, 5)
        assert got == ref, family


def test_sp_prefix_cache_is_actually_sharded():
    cfg = tiny()
    params = init_params(jax.random.PRNGKey(0), cfg)
    runner = SpStageRunner(cfg, full_spec(cfg), params, sp_mesh())
    runner.prefill(np.arange(32, dtype=np.int32)[None, :] % cfg.vocab_size)
    shards = runner.pk.addressable_shards
    assert len(shards) == P_DEV
    # Each device holds T/P of the sequence axis — the whole point.
    assert shards[0].data.shape[2] == 32 // P_DEV
    # Padded prompt: T=30 pads to 32, real length tracked separately.
    runner.prefill(np.arange(30, dtype=np.int32)[None, :] % cfg.vocab_size)
    assert runner.prefix_pad == 32 and runner.prefix_len == 30


def test_sp_unaligned_prompt_matches_oracle():
    # T=21 pads to 24; the padded garbage KV must be masked out of decode.
    cfg = tiny()
    params = init_params(jax.random.PRNGKey(2), cfg)
    runner = SpStageRunner(cfg, full_spec(cfg), params, sp_mesh())
    prompt = [(7 * i + 3) % cfg.vocab_size for i in range(21)]
    ref = oracle_tokens(cfg, params, prompt, 6)
    got = sp_generate(runner, prompt, 6)
    assert got == ref


def test_sp_two_stage_pipeline_matches_oracle():
    """Two sp runners chained like pipeline stages: stage0 (embed + first
    span) feeds its sequence-sharded hidden into the last stage (span +
    norm + head) — sequence parallelism INSIDE each pipeline stage."""
    cfg = tiny()
    params = init_params(jax.random.PRNGKey(3), cfg)
    plan = StagePlan.from_splits(cfg.num_layers, parse_splits("2"))
    mesh = sp_mesh()
    s0 = SpStageRunner(cfg, plan.stages[0],
                       slice_stage_params(cfg, params, plan.stages[0]), mesh)
    s1 = SpStageRunner(cfg, plan.stages[1],
                       slice_stage_params(cfg, params, plan.stages[1]), mesh)
    prompt = [5, 9, 23, 7, 81, 2, 14, 3, 19, 44, 6, 77, 8, 1, 90, 33]
    ref = oracle_tokens(cfg, params, prompt, 5)

    h0 = s0.prefill(np.asarray(prompt, np.int32)[None, :])
    h1 = s1.prefill(h0)
    tok = int(jnp.argmax(s1.logits_at(h1, len(prompt) - 1)[0]))
    out = [tok]
    for _ in range(4):
        h0 = s0.decode(jnp.asarray([[out[-1]]], jnp.int32))
        h1 = s1.decode(h0)
        tok = int(jnp.argmax(s1.logits_at(h1, 0)[0]))
        out.append(tok)
    assert out == ref


def test_sp_nonunit_final_norm_matches_oracle():
    """Regression: final_norm must be applied exactly ONCE on the sp path.
    Random init sets norm weights to ones, where a double RMSNorm is the
    identity and hides the bug — perturb them like a trained checkpoint."""
    cfg = tiny()
    params = init_params(jax.random.PRNGKey(4), cfg)
    params = dict(params)
    params["final_norm"] = {
        "w": 1.0 + 0.5 * jax.random.normal(jax.random.PRNGKey(9),
                                           params["final_norm"]["w"].shape)}
    runner = SpStageRunner(cfg, full_spec(cfg), params, sp_mesh())
    prompt = [(7 * i + 3) % cfg.vocab_size for i in range(16)]
    assert sp_generate(runner, prompt, 6) == oracle_tokens(cfg, params,
                                                           prompt, 6)


def test_sp_rejects_sliding_window():
    from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.models import (
        mistral_config,
    )

    cfg = mistral_config(vocab_size=257, hidden_size=64, num_layers=2,
                         num_heads=4, num_kv_heads=2, intermediate_size=128,
                         sliding_window=8)
    try:
        SpStageRunner(cfg, full_spec(cfg),
                      init_params(jax.random.PRNGKey(0), cfg), sp_mesh())
    except ValueError as exc:
        assert "sliding" in str(exc)
    else:
        raise AssertionError("sliding-window config must be rejected")


def test_sp_zigzag_layout_matches_oracle():
    """zigzag=True is a pure WORK-BALANCE change (device i holds one early
    + one late half-chunk; the prefix KV lives zigzag-resident): tokens
    must match the oracle exactly — aligned, unaligned, and across the
    prefill/decode boundary."""
    cfg = tiny()
    params = init_params(jax.random.PRNGKey(0), cfg)
    runner = SpStageRunner(cfg, full_spec(cfg), params, sp_mesh(),
                           zigzag=True)
    # T=24: pads to 32 (multiple of 2P=16), zigzag half-chunks of 2.
    prompt = [5, 9, 23, 7, 81, 2, 14, 3, 19, 44, 6, 77, 8, 1, 90, 33,
              12, 4, 56, 21, 9, 100, 41, 2]
    ref = oracle_tokens(cfg, params, prompt, 6)
    got = sp_generate(runner, prompt, 6)
    assert got == ref
    # Unaligned (T=13) exercises the 2P padding path.
    prompt2 = list(range(3, 16))
    ref2 = oracle_tokens(cfg, params, prompt2, 5)
    runner2 = SpStageRunner(cfg, full_spec(cfg), params, sp_mesh(),
                            zigzag=True)
    got2 = sp_generate(runner2, prompt2, 5)
    assert got2 == ref2
