"""Host-offload layer streaming (reference --use_cpu_offload /
--keep_layers_on_gpu, src/llama_partition.py:188-293) — offloaded execution
must be bit-identical to resident execution.
"""

import jax
import jax.numpy as jnp
import numpy as np

from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.models import (
    init_params,
)
from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.models.partition import (
    StagePlan,
    parse_splits,
    slice_stage_params,
)
from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.ops.sampling import (
    SamplingParams,
)
from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.runtime.client import (
    PipelineClient,
    make_server_record,
)
from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.runtime.executor import (
    StageExecutor,
)
from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.runtime.messages import (
    StageRequest,
)
from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.runtime.transport import (
    LocalTransport,
)
from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.scheduling.registry import (
    PlacementRegistry,
)

from test_runtime_pipeline import oracle_generate, tiny_cfg


def _pair(cfg, params, role="mid", keep=0):
    """(resident executor, offloaded executor) for the same span."""
    plan = StagePlan.from_splits(cfg.num_layers, parse_splits("2,6"))
    spec = {"first": plan.stages[0], "mid": plan.stages[1],
            "last": plan.stages[2]}[role]
    sp = slice_stage_params(cfg, params, spec)
    res = StageExecutor(cfg, spec, sp, peer_id="res")
    off = StageExecutor(cfg, spec, sp, peer_id="off", offload=True,
                        keep_layers_resident=keep)
    return res, off


def _run(ex, hid, seq_len, cur_len, prefill, ids=False):
    return ex.forward(StageRequest(
        session_id="s", hidden=jnp.asarray(hid), seq_len=seq_len,
        cur_len=cur_len, is_prefill=prefill, max_length=64))


def test_offloaded_segment_matches_resident():
    cfg = tiny_cfg()
    params = init_params(jax.random.PRNGKey(0), cfg)
    rng = np.random.default_rng(0)
    hid = rng.standard_normal((1, 10, cfg.hidden_size)).astype(np.float32)
    step = rng.standard_normal((1, 1, cfg.hidden_size)).astype(np.float32)

    for keep in (0, 2, 99):  # 99 -> fully resident via the offload path
        res, off = _pair(cfg, params, "mid", keep=keep)
        r1 = _run(res, hid, 10, 0, True)
        o1 = _run(off, hid, 10, 0, True)
        np.testing.assert_allclose(np.asarray(o1.hidden),
                                   np.asarray(r1.hidden),
                                   atol=1e-5, rtol=1e-5)
        r2 = _run(res, step, 1, 10, False)
        o2 = _run(off, step, 1, 10, False)
        np.testing.assert_allclose(np.asarray(o2.hidden),
                                   np.asarray(r2.hidden),
                                   atol=1e-5, rtol=1e-5)


def test_offloaded_first_and_last_roles():
    """Embedding entry (stage0) and head exit (last) work offloaded."""
    cfg = tiny_cfg("gpt2")  # learned positions: rope=None path too
    params = init_params(jax.random.PRNGKey(1), cfg)
    ids = np.asarray([[5, 9, 23, 7]], np.int32)

    res, off = _pair(cfg, params, "first", keep=1)
    r = _run(res, ids, 4, 0, True)
    o = _run(off, ids, 4, 0, True)
    np.testing.assert_allclose(np.asarray(o.hidden), np.asarray(r.hidden),
                               atol=1e-5, rtol=1e-5)

    rng = np.random.default_rng(1)
    hid = rng.standard_normal((1, 4, cfg.hidden_size)).astype(np.float32)
    res, off = _pair(cfg, params, "last")
    r = _run(res, hid, 4, 0, True)
    o = _run(off, hid, 4, 0, True)
    assert o.token_id == r.token_id


def test_offloaded_pipeline_matches_oracle():
    """Full pipeline where every server streams its layers from host."""
    cfg = tiny_cfg()
    params = init_params(jax.random.PRNGKey(0), cfg)
    plan = StagePlan.from_splits(cfg.num_layers, parse_splits("2,4,6"))
    transport = LocalTransport()
    import random as _random

    registry = PlacementRegistry(rng=_random.Random(0))
    for spec in plan.stages[1:]:
        peer = f"off-s{spec.index}"
        ex = StageExecutor(cfg, spec, slice_stage_params(cfg, params, spec),
                           peer_id=peer, offload=True, keep_layers_resident=1)
        transport.add_peer(peer, ex)
        registry.register(make_server_record(peer, spec))
    stage0 = StageExecutor(cfg, plan.stages[0],
                           slice_stage_params(cfg, params, plan.stages[0]),
                           peer_id="client-local")
    client = PipelineClient(cfg, plan, stage0, transport, registry,
                            settle_seconds=0.0)
    res = client.generate([5, 9, 23, 7, 81], max_new_tokens=6,
                          sampling=SamplingParams(temperature=0.0))
    ref = oracle_generate(cfg, params, [5, 9, 23, 7, 81], 6,
                          SamplingParams(temperature=0.0))
    assert res.tokens == ref
