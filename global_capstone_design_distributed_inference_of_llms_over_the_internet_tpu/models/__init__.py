from .config import (
    ModelConfig,
    PRESETS,
    get_config,
    gemma2_config,
    gemma_config,
    gpt2_config,
    llama_config,
    mistral_config,
    mixtral_config,
    qwen2_config,
)
from .transformer import (
    embed_tokens,
    full_forward,
    init_kv_cache,
    init_params,
    layer_forward,
    lm_head,
    stack_forward,
)
from .hf_import import config_from_hf, convert_state_dict, import_hf_model

__all__ = [
    "ModelConfig", "PRESETS", "get_config", "gemma2_config", "gemma_config",
    "gpt2_config",
    "llama_config", "mistral_config", "mixtral_config", "qwen2_config",
    "embed_tokens",
    "full_forward",
    "init_kv_cache", "init_params", "layer_forward", "lm_head", "stack_forward",
    "config_from_hf", "convert_state_dict", "import_hf_model",
]
