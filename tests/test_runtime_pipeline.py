"""End-to-end runtime: distributed pipeline == single-process oracle.

This is the in-process integration rig the reference lacked (SURVEY.md §4 —
its 'test' was ``scripts/run_all.py`` spawning real subprocesses and a human
comparing logs). Here the whole 4-stage pipeline runs in one process over
`LocalTransport` and every token is asserted against the unpartitioned
`full_forward` oracle (the ``scripts/single_gpu_check.py`` role, automated).
"""

import random

import jax
import jax.numpy as jnp
import numpy as np

from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.models import (
    full_forward,
    gpt2_config,
    init_kv_cache,
    init_params,
    llama_config,
)
from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.models.partition import (
    StagePlan,
    parse_splits,
    slice_stage_params,
)
from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.ops.sampling import (
    RECENT_WINDOW,
    SamplingParams,
    sample_token,
)
from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.runtime.client import (
    PipelineClient,
    make_server_record,
)
from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.runtime.executor import (
    StageExecutor,
)
from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.runtime.transport import (
    LocalTransport,
)
from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.scheduling.registry import (
    PlacementRegistry,
)


def tiny_cfg(family="llama"):
    if family == "gpt2":
        return gpt2_config(vocab_size=257, hidden_size=64, num_layers=8,
                           num_heads=4, max_position_embeddings=256)
    if family == "qwen2":
        from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.models import (
            qwen2_config,
        )

        return qwen2_config(vocab_size=257, hidden_size=64, num_layers=8,
                            num_heads=4, num_kv_heads=2, intermediate_size=128,
                            max_position_embeddings=256)
    if family == "gemma2":
        from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.models import (
            gemma2_config,
        )

        # Small softcaps so dropping them would change tokens (the
        # production 50/30 sit in tanh's linear region on tiny models);
        # window=4 actually truncates at these sequence lengths.
        return gemma2_config(vocab_size=257, hidden_size=64, num_layers=4,
                             num_heads=4, num_kv_heads=2,
                             intermediate_size=128, head_dim=32,
                             sliding_window=4, query_pre_attn_scalar=16.0,
                             attn_softcap=2.0, final_softcap=3.0,
                             max_position_embeddings=256)
    return llama_config(vocab_size=257, hidden_size=64, num_layers=8,
                        num_heads=4, num_kv_heads=2, intermediate_size=128,
                        max_position_embeddings=256)


def build_cluster(cfg, splits="3,6", replicas=1, seed=0):
    params = init_params(jax.random.PRNGKey(seed), cfg)
    plan = StagePlan.from_splits(cfg.num_layers, parse_splits(splits))
    transport = LocalTransport()
    registry = PlacementRegistry(rng=random.Random(seed))
    for spec in plan.stages[1:]:
        for r in range(replicas):
            peer = f"peer-s{spec.index}-r{r}"
            ex = StageExecutor(cfg, spec, slice_stage_params(cfg, params, spec),
                               peer_id=peer)
            transport.add_peer(peer, ex)
            registry.register(make_server_record(peer, spec))
    stage0 = StageExecutor(cfg, plan.stages[0],
                           slice_stage_params(cfg, params, plan.stages[0]),
                           peer_id="client-local")
    client = PipelineClient(cfg, plan, stage0, transport, registry,
                            settle_seconds=0.0, seed=seed)
    return client, transport, registry, params, plan


def oracle_generate(cfg, params, prompt_ids, max_new_tokens, sampling, seed=0,
                    max_len=256):
    """Unpartitioned reference loop with identical sampling semantics."""
    kc, vc = init_kv_cache(cfg, cfg.num_layers, 1, max_len)
    ids = jnp.asarray(np.asarray(prompt_ids, np.int32)[None, :])
    generated = []
    cache_len = jnp.int32(0)
    logits, kc, vc = full_forward(cfg, params, ids, kc, vc, cache_len)
    cur_len = len(prompt_ids)

    def pick(logits_last, step):
        recent = np.zeros((RECENT_WINDOW,), np.int32)
        n = min(len(generated), RECENT_WINDOW)
        if n:
            recent[:n] = np.asarray(generated[-n:], np.int32)
        return int(sample_token(
            jax.random.PRNGKey(seed + step),
            logits_last,
            jnp.asarray(recent), jnp.asarray(n, jnp.int32),
            jnp.asarray(sampling.temperature, jnp.float32),
            jnp.asarray(sampling.top_p, jnp.float32),
            jnp.asarray(sampling.top_k, jnp.int32),
            jnp.asarray(sampling.repetition_penalty, jnp.float32),
        ))

    generated.append(pick(logits[0, cur_len - 1], 0))
    for step in range(1, max_new_tokens):
        if len(generated) >= 5 and len(set(generated[-5:])) == 1:
            break
        nxt = jnp.asarray([[generated[-1]]], jnp.int32)
        logits, kc, vc = full_forward(cfg, params, nxt, kc, vc, jnp.int32(cur_len))
        generated.append(pick(logits[0, 0], step))
        cur_len += 1
    return generated


def test_pipeline_greedy_matches_oracle():
    cfg = tiny_cfg()
    client, _, _, params, _ = build_cluster(cfg, splits="2,4,6")
    sampling = SamplingParams(temperature=0.0)
    prompt = [5, 9, 23, 7, 81]
    res = client.generate(prompt, max_new_tokens=8, sampling=sampling)
    ref = oracle_generate(cfg, params, prompt, 8, sampling)
    assert res.tokens == ref
    assert res.ttft_s > 0
    assert set(client.last_prefill_stage_times) == {"stage1", "stage2", "stage3"}


def test_pipeline_qwen2_matches_oracle():
    # Qwen2 (llama + q/k/v biases) through the full distributed pipeline.
    cfg = tiny_cfg("qwen2")
    client, _, _, params, _ = build_cluster(cfg, splits="3,6")
    sampling = SamplingParams(temperature=0.0)
    prompt = [5, 9, 23, 7, 81]
    res = client.generate(prompt, max_new_tokens=8, sampling=sampling)
    assert res.tokens == oracle_generate(cfg, params, prompt, 8, sampling)


def test_pipeline_sampled_matches_oracle():
    cfg = tiny_cfg("gpt2")
    client, _, _, params, _ = build_cluster(cfg, splits="4")
    sampling = SamplingParams(temperature=0.8, top_p=0.9, top_k=20,
                              repetition_penalty=1.5)
    prompt = [11, 42, 7]
    res = client.generate(prompt, max_new_tokens=10, sampling=sampling)
    ref = oracle_generate(cfg, params, prompt, 10, sampling)
    assert res.tokens == ref


def test_failover_mid_generation_preserves_tokens():
    """Kill the pinned stage-2 server mid-decode; the client must fail over to
    the replica, replay the journal, and produce IDENTICAL tokens (the
    reference's manual kill_stage.py protocol, automated with assertions)."""
    cfg = tiny_cfg()
    client, transport, _, params, _ = build_cluster(cfg, splits="2,4,6", replicas=2)
    sampling = SamplingParams(temperature=0.0)
    prompt = [5, 9, 23, 7, 81]

    # Kill the pinned stage-2 peer after the 3rd decode step.
    seen_decode_steps = [0]
    pinned = {}

    def on_call(peer_id, req):
        if not req.is_prefill and not req.is_replay and "s2" in peer_id:
            seen_decode_steps[0] += 1
            pinned.setdefault("peer", peer_id)
            if seen_decode_steps[0] == 3:
                transport.kill(peer_id)

    transport.on_call = on_call
    res = client.generate(prompt, max_new_tokens=8, sampling=sampling)
    ref = oracle_generate(cfg, params, prompt, 8, sampling)
    assert res.tokens == ref
    assert client.recoveries >= 1
    # The replacement actually served traffic.
    killed = pinned["peer"]
    others = [p for p in transport.peers() if "s2" in p and p != killed]
    assert any(transport.executor(p).requests_served > 0 for p in others)


def test_failover_total_outage_raises():
    cfg = tiny_cfg()
    client, transport, _, _, _ = build_cluster(cfg, splits="2,4,6", replicas=1)
    for p in transport.peers():
        if "s3" in p:
            transport.kill(p)
    try:
        client.generate([1, 2, 3], max_new_tokens=4,
                        sampling=SamplingParams(temperature=0.0))
        raised = False
    except RuntimeError:
        raised = True
    assert raised


def test_transient_flake_recovers_without_replacement_pool():
    """fail_next models a transient network partition: same peer pool, the
    retry loop must eventually succeed via the replica."""
    cfg = tiny_cfg()
    client, transport, _, params, _ = build_cluster(cfg, splits="2,4,6", replicas=2)
    # Flake every stage-1 peer once: first call fails, rediscovery picks the
    # replica (also flaked once) -> second attempt inside recovery succeeds.
    for p in transport.peers():
        if "s1" in p:
            transport.fail_next(p, 1)
    res = client.generate([5, 9, 23], max_new_tokens=6,
                          sampling=SamplingParams(temperature=0.0))
    ref = oracle_generate(cfg, params, [5, 9, 23], 6,
                          SamplingParams(temperature=0.0))
    assert res.tokens == ref


def test_module_routing_covers_pipeline():
    """Module-mode routing: greedy max-end_block cover (rpc_transport.py:393-493)."""
    cfg = tiny_cfg()
    client, transport, registry, params, plan = build_cluster(cfg, splits="2,4,6")
    client.use_module_routing = True
    hops = client.route(refresh=True)
    assert [(h.start_block, h.end_block) for h in hops] == [(2, 4), (4, 6), (6, 8)]
    assert hops[-1].expect_token
    res = client.generate([5, 9, 23], max_new_tokens=5,
                          sampling=SamplingParams(temperature=0.0))
    ref = oracle_generate(cfg, params, [5, 9, 23], 5,
                          SamplingParams(temperature=0.0))
    assert res.tokens == ref


def test_repeat_stop():
    cfg = tiny_cfg()
    client, _, _, _, _ = build_cluster(cfg)
    # Force degenerate repetition by zero temperature on a tiny model with a
    # fixed-point argmax: not guaranteed, so instead assert the stop logic via
    # the result flag when it happens; otherwise max_tokens.
    res = client.generate([3, 3, 3], max_new_tokens=12,
                          sampling=SamplingParams(temperature=0.0))
    assert res.stopped_by in ("repeat", "max_tokens", "eos")
    assert len(res.tokens) <= 12


def test_remote_sessions_freed_after_generation():
    """Regression: every generate() must release its KV lease on all remote
    peers — otherwise repeated generations exhaust the server arenas."""
    cfg = tiny_cfg()
    client, transport, _, _, _ = build_cluster(cfg, splits="2,4,6")
    for _ in range(3):
        client.generate([5, 9, 23], max_new_tokens=3,
                        sampling=SamplingParams(temperature=0.0))
    for p in transport.peers():
        assert transport.executor(p).arena.active_sessions() == ()
    assert client.stage0.arena.active_sessions() == ()
