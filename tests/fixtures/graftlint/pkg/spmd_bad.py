"""Seeded SPMD sharding-discipline violations (phase 3 positive controls).

Every spmd-* rule fires here; the clean shapes (a covered leaf, a
reasoned replicated entry, a shard_map-reachable collective, a rebinding
donation caller) prove the rules don't fire on the sanctioned idioms.
NEVER imported — parsed only.
"""

import jax


def init_layer_params(key, cfg):
    p = {
        "attn": {"wq": 1, "wk": 1, "wv": 1, "wo": 1},
        "mlp": {"wi": 1, "wo": 1, "ln": 1},
        # spmd-catchall-leaf: matches no rule and no REPLICATED_LEAVES row.
        "rope": {"freqs": 1},
    }
    return p


def tp_partition_rules(cfg, axis="tp"):
    attn = (
        (r"attn/(wq|wk|wv)$", ("col", axis)),
        # spmd-rule-shadowed: the rule above always matches attn/wq first.
        (r"attn/wq$", ("shadowed",)),
        # spmd-rule-shadowed (dead): no corpus leaf matches at all.
        (r"attn/ghost$", ("dead",)),
        (r"attn/wo$", ("row", axis)),
    )
    mlp = (
        (r"mlp/(wi|wo)$", ("col", axis)),
    )
    return (*attn, *mlp, (r".*", ()))


REPLICATED_LEAVES = (
    # spmd-replicated-no-reason: explicit replication with the why missing.
    (r"mlp/ln$", ""),
)


# --- axis binding ----------------------------------------------------------

def _shard_body(x):
    # Reachable from the shard_map below: sanctioned, must NOT fire.
    return jax.lax.psum(x, "tp")


def build_sharded(mesh):
    return jax.shard_map(_shard_body, mesh=mesh, in_specs=None,
                         out_specs=None)


def orphan_collective(x):
    # spmd-axis-unbound: never reachable from any shard_map/pmap root.
    return jax.lax.psum(x, "tp")


# --- donation discipline ---------------------------------------------------

def _step_impl(cache, x):
    return cache + x


step = jax.jit(_step_impl, donate_argnums=(0,))
step2 = jax.jit(_step_impl, donate_argnums=(1,))


def leaky_reuse(cache, x):
    out = step(cache, x)
    # spmd-use-after-donate: cache was donated to `step` above.
    return out + cache


def decode_no_donate(cache, xs):
    for x in xs:
        # spmd-missed-donation: cache is rebound every iteration but
        # position 0 is not in step2's donate set.
        cache = step2(cache, x)
    return cache


def decode_donating(cache, xs):
    for x in xs:
        # Sanctioned: the donated position is the rebound carry.
        cache = step(cache, x)
    return cache
