"""int8 matmul with the per-channel scale folded into the epilogue (the
round-7 int8 decode lever).

The round-5 int8 path materialized a full bf16 weight per layer before
each matmul (``dequant_tree`` -> ``(q * s).astype(bf16)`` -> ``x @ w``):
HBM sees the int8 read AND the bf16 write+read of the materialized
weight, which is why int8 decode sat at 0.65 of sustained bandwidth
while reading half the bytes of bf16. The fix is to never materialize:

    y = (x @ q) * s            # q int8 streams straight into the dot,
                               # one f32 multiply per OUTPUT element

which is exact per output channel — scaling a column after the
K-reduction is algebraically identical to scaling the column's weights
before it; the only difference from the materialize path is floating-
point accumulation order (the same contract as ops.nf4_kernel).

Two execution paths, selected per shape:

  * Pallas kernel (TPU decode shapes): streams the int8 tile from HBM,
    widens to the activation dtype in VMEM (|q| <= 127 is exact in
    bf16), feeds the MXU, applies the scale row to the f32 accumulator
    before writeback. Grid = N tiles of ONE launch, full-K stripes —
    the same aggregated-launch layout as ops.nf4_kernel.
  * XLA mixed-dtype dot (everything else, and all of CPU CI):
    ``lax.dot_general`` takes an int8 rhs with f32 accumulation
    directly, so even the fallback never materializes a scaled weight.

`int8_dot` is dispatched from models.transformer._dot when
models.quant.int8_fold_enabled() leaves 2-D QuantizedTensor leaves
packed (default ON; INT8_FOLD=0 restores dequant-materialize). Token
parity with the materialize path is pinned by tests/test_int8_kernel.py
and the serving parity suites.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..models.quant import QuantizedTensor

TILE_N = 128

# Tests flip this to run the kernel through the Pallas interpreter on the
# CPU backend (slow, exact semantics) — the kernel itself targets TPU.
_INTERPRET = False

# Trace-time dispatch counter: incremented once per kernel-path call SITE
# per trace (under lax.scan the body traces once for all layers), so
# tests can pin "launch sites per decode step" without running on-chip.
_launches = 0


def _vmem_bytes(m: int, k: int, tn: int, x_bytes: int) -> int:
    """Per-program VMEM footprint estimate, double-buffered: the x block
    [m, k], the int8 weight tile [k, tn], its widened copy [k, tn] in the
    activation dtype, the (sublane-padded) scale row [8, tn] f32, and the
    out tile [m, tn] f32."""
    one = (m * k * x_bytes + k * tn + k * tn * x_bytes
           + 8 * tn * 4 + m * tn * 4)
    return 2 * one


def _tile_n(n: int, k: int, m: int, x_bytes: int) -> int:
    """Widest N tile that divides N AND fits the VMEM budget — same
    policy as ops.nf4_kernel._tile_n: wider tiles cut grid steps per
    launch; the budget guard falls back to 128 rather than fail a shape
    that used to serve (e.g. a large-K fused wd at a big prefill m)."""
    budget = 12 * 1024 * 1024          # ~16 MB/core minus headroom
    for tn in (512, 256):
        if n % tn == 0 and _vmem_bytes(m, k, tn, x_bytes) <= budget:
            return tn
    return TILE_N


@functools.lru_cache(maxsize=64)
def _make_kernel(m: int, k: int, n: int, out_dtype: str,
                 interpret: bool = False):
    from jax.experimental import pallas as pl

    tn = _tile_n(n, k, m, jnp.dtype(out_dtype).itemsize)

    def kernel(x_ref, q_ref, s_ref, out_ref):
        # int32 FIRST (Mosaic has no vector i8->float cast), then the
        # activation dtype: +-127 is exact in bf16, so the MXU sees the
        # true int8 values at bf16 feed rate.
        w = q_ref[:].astype(jnp.int32).astype(x_ref.dtype)
        acc = jnp.dot(x_ref[:], w, preferred_element_type=jnp.float32)
        # Scale epilogue: one f32 row [1, tn] broadcast over the m rows
        # of the accumulator — per OUTPUT element, not per weight.
        out_ref[:] = (acc * s_ref[:]).astype(out_ref.dtype)

    @jax.jit
    def fn(x, q, s):
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((m, n), jnp.dtype(out_dtype)),
            grid=(n // tn,),
            in_specs=[
                pl.BlockSpec((m, k), lambda j: (0, 0)),
                pl.BlockSpec((k, tn), lambda j: (0, j)),
                pl.BlockSpec((1, tn), lambda j: (0, j)),
            ],
            out_specs=pl.BlockSpec((m, tn), lambda j: (0, j)),
            interpret=interpret,
        )(x, q, s)

    return fn


def _supported(m: int, w: QuantizedTensor) -> bool:
    k, n = w.q.shape[-2], w.q.shape[-1]
    assert m % 8 == 0, "caller pads rows to a multiple of 8"
    return (w.q.ndim == 2                 # one layer's weight, not a stack
            and k % 128 == 0              # x lane dim / q sublane tiling
            and n % TILE_N == 0
            and (jax.default_backend() == "tpu" or _INTERPRET))


def int8_dot(x: jnp.ndarray, w: QuantizedTensor) -> jnp.ndarray:
    """x [..., K] @ int8 weight [K, N] (scale folded into the epilogue)
    -> [..., N] in x.dtype.

    Pallas kernel when the shape qualifies (see `_supported`); XLA
    mixed-dtype dot_general otherwise — BOTH stream the int8 bytes and
    scale the accumulator, so enabling the fold never changes which
    shapes serve and never materializes a scaled weight."""
    global _launches
    lead = x.shape[:-1]
    k = x.shape[-1]
    x2 = x.reshape(-1, k)
    m = x2.shape[0]
    m_pad = -(-max(m, 8) // 8) * 8
    if _supported(m_pad, w):
        _launches += 1
        if m_pad != m:
            x2 = jnp.pad(x2, ((0, m_pad - m), (0, 0)))
        fn = _make_kernel(m_pad, k, w.q.shape[-1], str(x.dtype),
                          interpret=_INTERPRET)
        out = fn(x2, w.q, w.s.astype(jnp.float32))
        return out[:m].reshape(*lead, -1)
    acc = jax.lax.dot_general(
        x2, w.q, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    return (acc * w.s).astype(x.dtype).reshape(*lead, -1)
