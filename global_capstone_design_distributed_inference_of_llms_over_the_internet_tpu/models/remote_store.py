"""Remote per-span weight fetch with a bounded, digest-verified disk cache.

VERDICT r2 item 5 / reference parity: Petals servers download ONLY the
checkpoint shards containing their span's parameters and manage/evict the
disk cache (``petals/server/from_pretrained.py:81-128`` — per-block shard
filtering against the HF index; ``:189-213`` — free-space-driven cache
eviction). This module is the TPU-build equivalent over a plain HTTP store
(any static file server; a local fixture in tests — this sandbox has zero
egress, but the capability is the contract):

  * the store layout is exactly an HF checkpoint directory: ``config.json``,
    ``model.safetensors.index.json`` (or a single ``model.safetensors``),
    shard files, and optionally ``digests.json`` ({filename: sha256});
  * ``shards_for_span`` filters the index's weight_map to the files covering
    ``[start, end)`` for a stage role — the reference's ``block_prefix``
    filter generalized to span + role (embed/head);
  * fetched shards land in a local cache directory with LRU accounting; once
    the cache exceeds ``max_cache_bytes``, least-recently-USED shards not
    needed by the current span are deleted (an elastic re-span keeps only
    what it still serves);
  * every fetched file is sha256-verified against ``digests.json`` when the
    store publishes one — a truncated/corrupted download fails loudly, never
    parses.

``load_stage`` then defers to the local streaming path
(``hf_import.LazyCheckpoint``/``convert_state_dict``) over the cache dir, so
remote and local checkpoints share one conversion code path.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import re
import threading
import time
import urllib.request
from typing import Dict, List, Optional, Set, Tuple

from .config import ModelConfig

logger = logging.getLogger(__name__)

INDEX = "model.safetensors.index.json"
SINGLE = "model.safetensors"
DIGESTS = "digests.json"

# Layer-scoped key patterns across supported families (hf_import layouts),
# with and without the base-model prefix (LazyCheckpoint alias rule).
_LAYER_RE = re.compile(
    r"^(?:transformer\.)?h\.(\d+)\.|^(?:model\.)?layers\.(\d+)\.")


def _layer_of(key: str) -> Optional[int]:
    m = _LAYER_RE.match(key)
    if m is None:
        return None
    return int(m.group(1) if m.group(1) is not None else m.group(2))


class DigestMismatch(RuntimeError):
    """A fetched shard's sha256 does not match the store's digests.json."""


class RemoteShardStore:
    """Span-scoped shard fetcher over HTTP with a bounded LRU disk cache."""

    def __init__(self, base_url: str, cache_dir: str,
                 max_cache_bytes: Optional[int] = None,
                 timeout: float = 60.0):
        self.base_url = base_url.rstrip("/")
        self.cache_dir = cache_dir
        self.max_cache_bytes = max_cache_bytes
        self.timeout = timeout
        os.makedirs(cache_dir, exist_ok=True)
        self.fetches: List[str] = []     # every remote GET, in order (tests)
        self._digests: Optional[Dict[str, str]] = None
        self._digests_403_until = 0.0
        self._weight_map: Optional[Dict[str, str]] = None
        # One lock serializes fetch/evict/load within the process: a store
        # is memoized and shared by every serving role (elastic servers
        # re-span on background threads), and thread A's eviction must not
        # delete shards thread B fetched but has not read yet. Cross-process
        # sharers of one cache dir are protected by the eviction GRACE
        # period below (files younger than evict_grace_s are never evicted),
        # which covers the other process's fetch->read window.
        self._op_lock = threading.RLock()
        self.evict_grace_s = 300.0
        # filename -> last-use WALL-CLOCK time (time.time, not monotonic:
        # stamps are persisted and compared across restarts/boots, and a
        # boot-relative clock would sort post-reboot touches BELOW ancient
        # pre-reboot ones, inverting eviction); persisted so LRU survives
        # server restarts (the reference tracks blocks via file atime).
        self._state_path = os.path.join(cache_dir, ".lru_state.json")
        try:
            with open(self._state_path) as f:
                self._lru: Dict[str, float] = dict(json.load(f))
        except (OSError, ValueError):
            self._lru = {}

    # -- transport ---------------------------------------------------------

    def _get(self, name: str) -> bytes:
        url = f"{self.base_url}/{name}"
        self.fetches.append(name)
        with urllib.request.urlopen(url, timeout=self.timeout) as r:
            return r.read()

    def _fetch_to_cache(self, name: str, verify: bool = True) -> str:
        """Download `name` into the cache (skipping if present), verify its
        digest, bump its LRU stamp, and return the local path."""
        local = os.path.join(self.cache_dir, name)
        if not os.path.exists(local):
            data = self._get(name)
            if verify:
                want = self.digests().get(name)
                if want is not None:
                    got = hashlib.sha256(data).hexdigest()
                    if got != want:
                        raise DigestMismatch(
                            f"{name}: sha256 {got} != published {want}")
            # Per-process-AND-thread temp name + atomic rename: several
            # server processes legitimately share one cache dir (a
            # multi-stage host) and several threads of one process share
            # the store, and no two concurrent fetchers of the same shard
            # may interleave writes into one temp file. Either winner's
            # bytes are digest-identical.
            tmp = f"{local}.part.{os.getpid()}.{threading.get_ident()}"
            try:
                with open(tmp, "wb") as f:
                    f.write(data)
                os.replace(tmp, local)  # never a torn shard under its name
            finally:
                if os.path.exists(tmp):
                    os.remove(tmp)
            # Persist after each actual DOWNLOAD (the slow path — one write
            # per fetched shard, same IO the network dwarfs): a co-hosted
            # process's _evict must see our recency during a long multi-
            # shard span fetch, not only at the end. Cache hits stay
            # in-memory-only (the fast path the batching exists for).
            self._touch(name)
            self._persist_lru()
            return local
        self._touch(name)
        return local

    # -- store metadata ----------------------------------------------------

    # How long a 403 on digests.json is treated as "absent" before the next
    # re-probe (bounds probe/log volume to ~1 per TTL, not 1 per shard).
    DIGEST_403_TTL_S = 60.0

    def digests(self) -> Dict[str, str]:
        with self._op_lock:
            if (self._digests is None
                    and time.monotonic() < self._digests_403_until):
                return {}
            if self._digests is None:
                import urllib.error

                try:
                    self._digests = json.loads(self._get(DIGESTS))
                except urllib.error.HTTPError as exc:
                    # 404/410 are the store SAYING the file is absent —
                    # cacheable. A transient transport error (timeout,
                    # reset, 5xx) propagates UN-cached: memoizing {} there
                    # would silently disable verification for the whole
                    # process on a store that does publish digests.
                    if exc.code in (404, 410):
                        logger.warning("store publishes no %s; shards are "
                                       "fetched UNVERIFIED", DIGESTS)
                        self._digests = {}
                    elif exc.code == 403:
                        # Forbidden is ambiguous: S3/GCS static hosting
                        # without list permission answers 403 for absent
                        # keys, but 403 on a store that DOES publish
                        # digests.json means an auth misconfiguration —
                        # memoizing it forever would silently disable
                        # sha256 verification for the process lifetime.
                        # Degrade with a short TTL (error-level) so a span
                        # load probes once, the operator sees a repeating
                        # error across operations, and a fixed ACL
                        # recovers without a restart.
                        logger.error(
                            "store answered 403 for %s; treating as absent "
                            "for the next %.0fs — shards are UNVERIFIED "
                            "until the store stops forbidding the digest "
                            "file (fix the ACL or delete the file to get a "
                            "clean 404)", DIGESTS, self.DIGEST_403_TTL_S)
                        self._digests_403_until = (
                            time.monotonic() + self.DIGEST_403_TTL_S)
                        return {}
                    else:
                        raise
            return self._digests

    def weight_map(self) -> Dict[str, str]:
        """key -> shard filename (downloads the index, small)."""
        with self._op_lock:
            if self._weight_map is not None:
                return self._weight_map
            try:
                local = self._fetch_to_cache(INDEX)
                try:
                    with open(local) as f:
                        wm = json.load(f)["weight_map"]
                    if not isinstance(wm, dict):
                        raise ValueError("weight_map is not a mapping")
                    self._weight_map = dict(wm)
                    self._persist_lru()
                    return self._weight_map
                except (ValueError, KeyError) as exc:
                    # Present-but-malformed index (e.g. a misconfigured
                    # host answering 200 with an error page): drop the
                    # cached copy so a retry refetches instead of failing
                    # forever, then try the single-file layout. Name the
                    # real culprit — the fallback's own failure would
                    # otherwise blame model.safetensors.
                    logger.warning(
                        "%s is present but malformed (%s: %s); dropping "
                        "the cached copy and trying the single-file "
                        "layout", INDEX, type(exc).__name__, exc)
                    try:
                        os.remove(local)
                    except OSError:
                        pass
            except OSError:
                pass
            # Single-file checkpoint: every key lives in model.safetensors.
            self._fetch_to_cache(SINGLE)
            from safetensors import safe_open

            with safe_open(os.path.join(self.cache_dir, SINGLE),
                           framework="flax") as f:
                self._weight_map = {k: SINGLE for k in f.keys()}
            self._persist_lru()
            return self._weight_map

    # Tokenizer files a checkpoint MAY publish (best-effort: absence is
    # normal; clients fall back to the byte tokenizer only when none load).
    TOKENIZER_FILES = ("tokenizer.json", "tokenizer_config.json",
                       "special_tokens_map.json", "tokenizer.model",
                       "vocab.json", "merges.txt")

    def fetch_config(self) -> str:
        """Fetch config.json + any published tokenizer files; returns the
        cache dir, which is then a loadable local checkpoint prefix."""
        with self._op_lock:
            self._fetch_to_cache("config.json")
            for name in self.TOKENIZER_FILES:
                try:
                    self._fetch_to_cache(name)
                except OSError:
                    pass
            self._persist_lru()
            return self.cache_dir

    # -- span logic --------------------------------------------------------

    def shards_for_span(self, start: int, end: int, *, is_first: bool,
                        is_last: bool) -> List[str]:
        """Shard files containing any parameter the span's role needs — the
        per-block filter of ``from_pretrained.py:100-108`` over [start,end)."""
        needed: Set[str] = set()
        for key, fname in self.weight_map().items():
            layer = _layer_of(key)
            if layer is not None:
                if start <= layer < end:
                    needed.add(fname)
            elif is_first or is_last:
                # Non-layer tensors: embeddings (first), final norm + head
                # (last). Embeddings also serve tied heads; fetching the
                # handful of non-layer tensors for either boundary role is
                # exact enough at shard granularity.
                needed.add(fname)
        return sorted(needed)

    def ensure_span(self, start: int, end: int, *, is_first: bool,
                    is_last: bool) -> List[str]:
        """Fetch (or reuse) every shard the span needs; evict LRU excess
        beyond the byte budget. Returns the local shard paths."""
        with self._op_lock:
            names = self.shards_for_span(start, end, is_first=is_first,
                                         is_last=is_last)
            paths = [self._fetch_to_cache(n) for n in names]
            self._evict(keep=set(names))
            self._persist_lru()
            return paths

    def load_stage(self, cfg: ModelConfig, spec, dtype=None):
        """Fetch the span's shards then stream-convert them via the local
        per-stage path (one conversion code path for local + remote).

        Holds the op lock across fetch AND convert so a concurrent span's
        eviction cannot delete these shards between download and read."""
        import numpy as np

        from .hf_import import load_stage_checkpoint

        with self._op_lock:
            self.fetch_config()
            self.ensure_span(spec.start, spec.end, is_first=spec.is_first,
                             is_last=spec.is_last)
            return load_stage_checkpoint(self.cache_dir, cfg, spec,
                                         dtype=dtype or np.float32)

    # -- cache management --------------------------------------------------

    def _touch(self, name: str) -> None:
        """In-memory recency bump only — cheap enough for per-shard calls.
        The disk persist is batched: one ``_persist_lru`` per public
        fetch operation, not one read-merge-rewrite of the whole state file
        per touch (which made span loads O(shards × state-size) in file IO)."""
        self._lru[name] = time.time()

    def _persist_lru(self) -> None:
        try:
            # Merge-on-write: other PROCESSES sharing this cache dir write
            # their own stamps to the same file; blind-rewriting from this
            # process's view would zero their recency and make _evict
            # delete their in-use shards first. Newest stamp per key wins;
            # the write itself is atomic (temp + replace).
            try:
                with open(self._state_path) as f:
                    disk = dict(json.load(f))
            except (OSError, ValueError):
                disk = {}
            for k, v in disk.items():
                if k not in self._lru and not os.path.exists(
                        os.path.join(self.cache_dir, k)):
                    # Evicted (by us or a co-hosted process) and no backing
                    # file: do NOT resurrect the stamp, or the state file
                    # grows one entry per shard ever fetched.
                    continue
                if isinstance(v, (int, float)) and v > self._lru.get(k, 0.0):
                    self._lru[k] = float(v)
            tmp = (f"{self._state_path}.part.{os.getpid()}"
                   f".{threading.get_ident()}")
            with open(tmp, "w") as f:
                json.dump(self._lru, f)
            os.replace(tmp, self._state_path)
        except OSError:  # pragma: no cover — cache still works, LRU degrades
            pass

    def cache_bytes(self) -> int:
        total = 0
        for fname in os.listdir(self.cache_dir):
            if fname.endswith(".safetensors"):
                total += os.path.getsize(os.path.join(self.cache_dir, fname))
        return total

    def _evict(self, keep: Set[str]) -> None:
        """Delete least-recently-used shards (never `keep` — the span being
        served) until the cache fits the budget
        (``from_pretrained.py:189-213`` semantics)."""
        if self.max_cache_bytes is None:
            return
        excess = self.cache_bytes() - self.max_cache_bytes
        if excess <= 0:
            return
        # Publish our in-memory touches AND merge other processes' stamps
        # from disk before choosing victims: deciding on a stale private
        # view could evict a shard a co-hosted process touched after our
        # last merge (its only other shield is the mtime grace period).
        self._persist_lru()
        now = time.time()
        cands = []
        for f in os.listdir(self.cache_dir):
            if not f.endswith(".safetensors") or f in keep:
                continue
            try:
                age = now - os.path.getmtime(os.path.join(self.cache_dir, f))
            except OSError:
                continue
            # Grace period: a file another PROCESS just fetched (sharing
            # this cache dir) is still inside its fetch->read window; its
            # recency is visible to us only via mtime.
            if age < self.evict_grace_s:
                continue
            cands.append(f)
        cands.sort(key=lambda f: self._lru.get(f, 0.0))
        for fname in cands:
            if excess <= 0:
                break
            path = os.path.join(self.cache_dir, fname)
            try:
                size = os.path.getsize(path)
                os.remove(path)
            except OSError:
                continue
            self._lru.pop(fname, None)
            excess -= size
            logger.info("evicted cached shard %s (%.1f MiB)", fname,
                        size / 2**20)
        if excess > 0:
            # The CURRENT span alone exceeds the budget: serve it anyway
            # (evicting it would break the server), but say so.
            logger.warning(
                "weight cache over budget by %.1f MiB even after eviction "
                "(the current span needs more than max_cache_bytes)",
                excess / 2**20)
