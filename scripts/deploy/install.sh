#!/usr/bin/env bash
# Install systemd units for the registry and/or a stage server on this host
# (the runnable analogue of the reference's deploy playbook: unit files +
# auto-update timer). Usage:
#
#   sudo scripts/deploy/install.sh registry        # control-plane host
#   sudo scripts/deploy/install.sh server          # stage-server host
#   sudo scripts/deploy/install.sh autoupdate      # hourly git-pull+restart
set -euo pipefail

ROLE="${1:?usage: install.sh registry|server|autoupdate}"
REPO="$(cd "$(dirname "$0")/../.." && pwd)"
UNIT_DIR="${MPT_UNIT_DIR:-/etc/systemd/system}"
mkdir -p /etc/mpt

case "$ROLE" in
registry)
    [ -f /etc/mpt/registry.env ] || cat > /etc/mpt/registry.env <<'EOF'
MPT_REGISTRY_PORT=31330
MPT_TTL=45
EOF
    cat > "$UNIT_DIR/mpt-registry.service" <<EOF
[Unit]
Description=mini-petals-tpu registry (control plane)
After=network-online.target

[Service]
ExecStart=$REPO/scripts/deploy/registry.sh
Restart=always
RestartSec=5

[Install]
WantedBy=multi-user.target
EOF
    systemctl daemon-reload
    systemctl enable --now mpt-registry
    ;;
server)
    [ -f /etc/mpt/server.env ] || cat > /etc/mpt/server.env <<'EOF'
MPT_REGISTRY=127.0.0.1:31330
MPT_MODEL=gpt2
MPT_ROLE=elastic
MPT_RPC_PORT=31331
EOF
    cat > "$UNIT_DIR/mpt-server.service" <<EOF
[Unit]
Description=mini-petals-tpu stage server
After=network-online.target

[Service]
ExecStart=$REPO/scripts/deploy/serve.sh
Restart=always
RestartSec=5

[Install]
WantedBy=multi-user.target
EOF
    systemctl daemon-reload
    systemctl enable --now mpt-server
    ;;
autoupdate)
    cat > "$UNIT_DIR/mpt-autoupdate.service" <<EOF
[Unit]
Description=mini-petals-tpu auto-update (git pull + restart)

[Service]
Type=oneshot
ExecStart=$REPO/scripts/deploy/update.sh
EOF
    cat > "$UNIT_DIR/mpt-autoupdate.timer" <<'EOF'
[Unit]
Description=hourly mini-petals-tpu auto-update

[Timer]
OnCalendar=hourly
RandomizedDelaySec=600

[Install]
WantedBy=timers.target
EOF
    systemctl daemon-reload
    systemctl enable --now mpt-autoupdate.timer
    ;;
*)
    echo "unknown role $ROLE" >&2
    exit 2
    ;;
esac
echo "[install.sh] $ROLE installed"
