"""Seeded wire-verb drift (parsed by graftlint, never run)."""


class PhantomServer:
    def _dispatch(self, sock, verb, header):
        if verb == "phantom_verb":   # no doc row, no test, no fault rule
            return {"ok": True}
        return None
