"""graftlint: repo-native static analysis for the swarm codebase.

Three analyzer families over the package's ASTs, unified under one driver
and one finding format (docs/STATIC_ANALYSIS.md):

  * **Lock discipline** (`locks.py`): per class, infer the attributes the
    class guards with its own ``threading.Lock``s, then flag accesses of
    those attributes outside any lock, blocking calls made while a lock is
    held, and cross-class lock-acquisition cycles (deadlock candidates).
  * **JAX hygiene** (`jax_hygiene.py`): host-sync idioms and ``os.environ``
    reads inside functions reachable from ``jit``/``scan``/``shard_map``
    bodies (stale-flag + recompile hazards), and ``jax.debug.callback``
    sites not gated by a trace-time enablement check.
  * **Drift invariants** (`dispatch.py`, `env_flags.py`, `legacy.py`):
    every wire verb dispatched server-side needs a PROTOCOL.md row, chaos
    coverage, and a test mention; every ``os.environ`` read needs a
    ``utils/flags.py`` catalog entry; plus the four original ``check_*``
    scripts (bare prints, metrics/docs drift, CLI-mode docs, quant
    coverage) re-homed as analyzers.

Intentional findings are suppressed via ``graftlint_baseline.json`` at the
repo root — every entry must carry a reason string, and stale entries fail
the run, so the baseline can only shrink unless someone argues in writing.

Run it:  ``python -m scripts.graftlint [--json]``  (tier-1 runs the same
driver through tests/test_graftlint.py).
"""

from .core import (  # noqa: F401
    ALL_ANALYZERS,
    Baseline,
    BaselineError,
    Context,
    Finding,
    build_context,
    run_analyzers,
)
