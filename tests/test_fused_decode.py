"""Fused multi-step decode (runtime.fused_decode): token parity with the
per-step full_forward oracle — the bench's engine must generate exactly
what serving generates (greedy)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.models import (
    full_forward,
    init_kv_cache,
    init_params,
)
from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.runtime.fused_decode import (
    make_fused_decode,
)

from test_runtime_pipeline import tiny_cfg


@pytest.mark.parametrize("family", ["llama", "gpt2", "gemma2"])
@pytest.mark.parametrize("batch", [1, 4])
def test_fused_decode_matches_oracle(family, batch):
    cfg = tiny_cfg(family)
    params = init_params(jax.random.PRNGKey(0), cfg)
    prefill, steps, max_len = 5, 7, 32
    rng = np.random.default_rng(3)
    prompts = rng.integers(0, cfg.vocab_size, (batch, prefill)).astype(np.int32)

    # oracle: per-step full_forward greedy, one row at a time
    want = []
    for b in range(batch):
        kc, vc = init_kv_cache(cfg, cfg.num_layers, 1, max_len)
        logits, kc, vc = full_forward(cfg, params, jnp.asarray(prompts[b:b+1]),
                                      kc, vc, jnp.int32(0))
        toks = [int(jnp.argmax(logits[0, -1]))]
        cur = prefill
        for _ in range(steps - 1):
            logits, kc, vc = full_forward(
                cfg, params, jnp.asarray([[toks[-1]]], jnp.int32), kc, vc,
                jnp.int32(cur))
            toks.append(int(jnp.argmax(logits[0, -1])))
            cur += 1
        want.append(toks)

    # fused: one program for all steps, all rows
    kc, vc = init_kv_cache(cfg, cfg.num_layers, batch, max_len)
    logits, kc, vc = full_forward(cfg, params, jnp.asarray(prompts), kc, vc,
                                  jnp.int32(0))
    tok = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
    first = [int(t) for t in np.asarray(tok)]
    fn = make_fused_decode(cfg, steps - 1, batch)
    toks, kc, vc = fn(params, tok, kc, vc, jnp.int32(prefill),
                      jnp.int32(steps - 1))
    got = np.concatenate([np.asarray(first)[None], np.asarray(toks)], axis=0)
    for b in range(batch):
        assert list(got[:, b]) == want[b], b


def test_fused_decode_quantized_runs():
    from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.models.quant import (
        quantize_params,
    )

    cfg = tiny_cfg()
    params = quantize_params(init_params(jax.random.PRNGKey(1), cfg), "int8")
    kc, vc = init_kv_cache(cfg, cfg.num_layers, 2, 16)
    fn = make_fused_decode(cfg, 3, 2)
    toks, _, _ = fn(params, jnp.zeros((2,), jnp.int32), kc, vc,
                    jnp.int32(1), jnp.int32(3))
    assert np.asarray(toks).shape == (3, 2)


def test_fused_sampled_decode_matches_per_token_oracle():
    """make_fused_sample_decode folds the FULL sampler into the scan with
    the per-token oracle's exact key schedule (PRNGKey(seed+step)) — output
    must be bit-identical to stepping full_forward + sample_token by
    hand."""
    from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.ops.sampling import (
        RECENT_WINDOW,
        make_recent_buffer,
        push_recent,
        sample_token,
    )
    from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.runtime.fused_decode import (
        make_fused_sample_decode,
    )

    cfg = tiny_cfg()
    params = init_params(jax.random.PRNGKey(4), cfg)
    prompt = [5, 9, 23, 7]
    seed, steps = 77, 9
    sp = (jnp.asarray(0.9, jnp.float32), jnp.asarray(0.95, jnp.float32),
          jnp.asarray(40, jnp.int32), jnp.asarray(1.4, jnp.float32))

    # per-token oracle
    kc, vc = init_kv_cache(cfg, cfg.num_layers, 1, 32)
    ids = jnp.asarray(np.asarray(prompt, np.int32)[None, :])
    logits, kc, vc = full_forward(cfg, params, ids, kc, vc, jnp.int32(0))
    want = []
    for step in range(steps):
        recent = np.zeros((RECENT_WINDOW,), np.int32)
        n = min(len(want), RECENT_WINDOW)
        if n:
            recent[:n] = np.asarray(want[-n:], np.int32)
        src = logits[0, -1] if step == 0 else logits[0, 0]
        tok = int(sample_token(jax.random.PRNGKey(seed + step), src,
                               jnp.asarray(recent), jnp.asarray(n, jnp.int32),
                               *sp))
        want.append(tok)
        if step < steps - 1:
            logits, kc, vc = full_forward(
                cfg, params, jnp.asarray([[tok]], jnp.int32), kc, vc,
                jnp.int32(len(prompt) + step))

    # fused: first token by hand (schedule step 0), rest in ONE program
    kc, vc = init_kv_cache(cfg, cfg.num_layers, 1, 32)
    logits, kc, vc = full_forward(cfg, params, ids, kc, vc, jnp.int32(0))
    recent, nvalid = make_recent_buffer()
    tok0 = sample_token(jax.random.PRNGKey(seed), logits[0, -1], recent,
                        nvalid, *sp)
    recent, nvalid = push_recent(recent, nvalid, tok0)
    fn = make_fused_sample_decode(cfg, steps - 1)
    toks, kc, vc, recent, nvalid = fn(
        params, tok0, kc, vc, jnp.int32(len(prompt)), jnp.int32(steps - 1),
        jnp.int32(seed + 1), recent, nvalid, *sp)
    got = [int(tok0)] + [int(t) for t in np.asarray(toks[: steps - 1])]
    assert got == want
