"""Import HuggingFace checkpoints into the stacked-layer JAX param layout.

Capability-parity with two reference paths:
  * full-checkpoint load + prune to a stage span (``src/llama_partition.py:477-550``
    loads the whole HF model then deletes layers outside [start, end));
  * per-block weight streaming (``petals/server/from_pretrained.py:81-128``
    downloads only the shards containing one block's params).

Here both are the same operation: ``convert_state_dict(..., layer_range)``
touches only the tensors a stage needs, so a stage never materializes the full
model in host memory.

Weight-layout notes:
  * GPT-2 uses Conv1D ([in, out]) — imported as-is; its fused c_attn is split
    into wq/wk/wv.
  * LLaMA-family nn.Linear weights are [out, in] — imported transposed.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, Optional, Tuple

from collections.abc import Mapping

import jax
import jax.numpy as jnp
import numpy as np

from .config import (
    ModelConfig,
    gpt2_config,
    llama_config,
    mistral_config,
    mixtral_config,
    qwen2_config,
)

Params = Dict[str, Any]


def _np(t) -> np.ndarray:
    """torch.Tensor | np.ndarray -> np.ndarray (float32 staging)."""
    if hasattr(t, "detach"):
        t = t.detach().to("cpu")
        try:
            import torch

            if t.dtype == torch.bfloat16:
                t = t.float()
        except Exception:
            pass
        t = t.numpy()
    return np.asarray(t)


def config_from_hf(hf_cfg) -> ModelConfig:
    """Build a ModelConfig from a transformers PretrainedConfig."""
    mt = hf_cfg.model_type
    if mt == "gpt2":
        return gpt2_config(
            vocab_size=hf_cfg.vocab_size,
            hidden_size=hf_cfg.n_embd,
            num_layers=hf_cfg.n_layer,
            num_heads=hf_cfg.n_head,
            max_position_embeddings=hf_cfg.n_positions,
            intermediate_size=getattr(hf_cfg, "n_inner", None) or 4 * hf_cfg.n_embd,
            norm_eps=getattr(hf_cfg, "layer_norm_epsilon", 1e-5),
        )
    common = dict(
        vocab_size=hf_cfg.vocab_size,
        hidden_size=hf_cfg.hidden_size,
        num_layers=hf_cfg.num_hidden_layers,
        num_heads=hf_cfg.num_attention_heads,
        num_kv_heads=getattr(hf_cfg, "num_key_value_heads", hf_cfg.num_attention_heads),
        intermediate_size=hf_cfg.intermediate_size,
        max_position_embeddings=hf_cfg.max_position_embeddings,
        rope_theta=getattr(hf_cfg, "rope_theta", 10000.0),
        tie_word_embeddings=getattr(hf_cfg, "tie_word_embeddings", False),
        norm_eps=getattr(hf_cfg, "rms_norm_eps", 1e-5),
    )
    rs = getattr(hf_cfg, "rope_scaling", None)
    if rs and mt != "llama":
        # Only the llama3 remap is implemented; any other family shipping
        # rope_scaling (e.g. yarn on long-context qwen2) would get silently
        # wrong positions past the base window — fail loudly instead.
        rtype = rs.get("rope_type", rs.get("type"))
        if rtype not in (None, "default"):
            raise ValueError(
                f"{mt} checkpoint carries rope_scaling type {rtype!r} — "
                "unsupported (llama3-type scaling on llama only)")
    if mt == "llama":
        cfg = llama_config(**common)
        if rs:
            rtype = rs.get("rope_type", rs.get("type"))
            if rtype == "llama3":
                # Llama-3.1/3.2 frequency remap (ops.rotary llama3 rule).
                import dataclasses

                cfg = dataclasses.replace(cfg, rope_scaling=(
                    float(rs["factor"]),
                    float(rs.get("low_freq_factor", 1.0)),
                    float(rs.get("high_freq_factor", 4.0)),
                    int(rs.get("original_max_position_embeddings", 8192)),
                ))
            elif rtype not in (None, "default"):
                # Linear/dynamic-NTK etc. would silently change positions —
                # fail loudly rather than generate subtly wrong long-context.
                raise ValueError(
                    f"unsupported rope_scaling type {rtype!r} "
                    "(supported: llama3)")
        return cfg
    if mt == "qwen2":
        common["norm_eps"] = getattr(hf_cfg, "rms_norm_eps", 1e-6)
        cfg = qwen2_config(**common)
        # Qwen2 configs carry sliding_window but only apply it when
        # use_sliding_window is set (HF Qwen2Config semantics). HF further
        # runs FULL attention for layers < max_window_layers and windowed
        # attention only above; our window is global, so only the uniform
        # cases map — a mixed checkpoint must fail LOUDLY, not silently
        # diverge past the window.
        if getattr(hf_cfg, "use_sliding_window", False):
            import dataclasses

            mwl = getattr(hf_cfg, "max_window_layers",
                          hf_cfg.num_hidden_layers)
            if mwl <= 0:  # every layer windowed
                cfg = dataclasses.replace(
                    cfg, sliding_window=getattr(hf_cfg, "sliding_window", None))
            elif mwl < hf_cfg.num_hidden_layers:  # mixed full/windowed
                raise ValueError(
                    "qwen2 checkpoint uses per-layer sliding windows "
                    f"(max_window_layers={mwl} of "
                    f"{hf_cfg.num_hidden_layers} layers) — unsupported")
            # mwl >= num layers: no layer is windowed; keep full attention.
        return cfg
    if mt == "mistral":
        return mistral_config(
            sliding_window=getattr(hf_cfg, "sliding_window", None), **common
        )
    if mt == "gemma":
        from .config import gemma_config

        # HF GemmaConfig historically defaulted hidden_act to "gelu" while
        # checkpoints run gelu_pytorch_tanh (transformers#29402); both map
        # to the tanh approximation here. norm_eps/tie_word_embeddings ride
        # in via `common` (GemmaConfig always defines both attributes).
        return gemma_config(head_dim=hf_cfg.head_dim, **common)
    if mt == "gemma2":
        from .config import gemma2_config

        return gemma2_config(
            head_dim=hf_cfg.head_dim,
            query_pre_attn_scalar=float(
                getattr(hf_cfg, "query_pre_attn_scalar", 0.0) or 0.0),
            attn_softcap=float(
                getattr(hf_cfg, "attn_logit_softcapping", 0.0) or 0.0),
            final_softcap=float(
                getattr(hf_cfg, "final_logit_softcapping", 0.0) or 0.0),
            sliding_window=int(getattr(hf_cfg, "sliding_window", 0) or 0),
            **common)
    if mt == "mixtral":
        cfg = mixtral_config(
            num_experts=hf_cfg.num_local_experts,
            num_experts_per_tok=hf_cfg.num_experts_per_tok,
            **common,
        )
        sw = getattr(hf_cfg, "sliding_window", None)
        if sw is not None:
            import dataclasses

            cfg = dataclasses.replace(cfg, sliding_window=sw)
        return cfg
    # Mirrors the reference's model_type guard (src/llama_partition.py:82-83).
    raise ValueError(
        f"unsupported model_type: {mt} "
        "(expected gpt2/llama/mistral/mixtral/qwen2/gemma)")


def _gpt2_layer(sd: Mapping[str, Any], i: int) -> Params:
    pre = f"transformer.h.{i}."
    c_attn_w = _np(sd[pre + "attn.c_attn.weight"])  # [D, 3D]
    c_attn_b = _np(sd[pre + "attn.c_attn.bias"])  # [3D]
    wq, wk, wv = np.split(c_attn_w, 3, axis=1)
    bq, bk, bv = np.split(c_attn_b, 3, axis=0)
    return {
        "ln1": {"w": _np(sd[pre + "ln_1.weight"]), "b": _np(sd[pre + "ln_1.bias"])},
        "ln2": {"w": _np(sd[pre + "ln_2.weight"]), "b": _np(sd[pre + "ln_2.bias"])},
        "attn": {
            "wq": wq, "wk": wk, "wv": wv,
            "bq": bq, "bk": bk, "bv": bv,
            "wo": _np(sd[pre + "attn.c_proj.weight"]),
            "bo": _np(sd[pre + "attn.c_proj.bias"]),
        },
        "mlp": {
            "wi": _np(sd[pre + "mlp.c_fc.weight"]),
            "bi": _np(sd[pre + "mlp.c_fc.bias"]),
            "wo": _np(sd[pre + "mlp.c_proj.weight"]),
            "bo": _np(sd[pre + "mlp.c_proj.bias"]),
        },
    }


def _llama_layer(sd: Mapping[str, Any], i: int, cfg: ModelConfig) -> Params:
    pre = f"model.layers.{i}."
    p: Params = {
        "ln1": {"w": _np(sd[pre + "input_layernorm.weight"])},
        "attn": {
            "wq": _np(sd[pre + "self_attn.q_proj.weight"]).T,
            "wk": _np(sd[pre + "self_attn.k_proj.weight"]).T,
            "wv": _np(sd[pre + "self_attn.v_proj.weight"]).T,
            "wo": _np(sd[pre + "self_attn.o_proj.weight"]).T,
        },
    }
    if cfg.post_norms:
        # gemma2 sandwich norms: HF's "post_attention_layernorm" is the
        # POST-attn norm (our ln3); the pre-MLP norm is
        # "pre_feedforward_layernorm" (our ln2).
        p["ln2"] = {"w": _np(sd[pre + "pre_feedforward_layernorm.weight"])}
        p["ln3"] = {"w": _np(sd[pre + "post_attention_layernorm.weight"])}
        p["ln4"] = {"w": _np(sd[pre + "post_feedforward_layernorm.weight"])}
    else:
        p["ln2"] = {"w": _np(sd[pre + "post_attention_layernorm.weight"])}
    if cfg.altern_window:
        # even layers windowed, odd global (HF Gemma2Attention layer_idx
        # rule) — the traced per-layer window leaf.
        p["window"] = np.int32(cfg.altern_window if i % 2 == 0 else 0)
    if cfg.attn_qkv_bias:  # qwen2: q/k/v biases, no o bias
        p["attn"]["bq"] = _np(sd[pre + "self_attn.q_proj.bias"])
        p["attn"]["bk"] = _np(sd[pre + "self_attn.k_proj.bias"])
        p["attn"]["bv"] = _np(sd[pre + "self_attn.v_proj.bias"])
    if cfg.is_moe:
        gate = _np(sd[pre + "block_sparse_moe.gate.weight"]).T  # [D, E]
        wg = np.stack([
            _np(sd[pre + f"block_sparse_moe.experts.{e}.w1.weight"]).T
            for e in range(cfg.num_experts)
        ])
        wu = np.stack([
            _np(sd[pre + f"block_sparse_moe.experts.{e}.w3.weight"]).T
            for e in range(cfg.num_experts)
        ])
        wd = np.stack([
            _np(sd[pre + f"block_sparse_moe.experts.{e}.w2.weight"]).T
            for e in range(cfg.num_experts)
        ])
        p["mlp"] = {"router": gate, "wg": wg, "wu": wu, "wd": wd}
    else:
        p["mlp"] = {
            "wg": _np(sd[pre + "mlp.gate_proj.weight"]).T,
            "wu": _np(sd[pre + "mlp.up_proj.weight"]).T,
            "wd": _np(sd[pre + "mlp.down_proj.weight"]).T,
        }
    return p


def _stack(layer_params: Iterable[Params]) -> Params:
    layer_params = list(layer_params)
    return jax.tree.map(lambda *xs: np.stack(xs), *layer_params)


def convert_state_dict(
    cfg: ModelConfig,
    sd: Mapping[str, Any],
    dtype=np.float32,
    layer_range: Optional[Tuple[int, int]] = None,
    include_embed: bool = True,
    include_head: bool = True,
) -> Params:
    """Convert an HF state_dict to the stacked JAX layout.

    layer_range=(start, end) keeps only that span of layers; include_embed /
    include_head control whether embedding and final-norm+lm_head tensors are
    materialized (mirrors the stage-role pruning of
    ``src/llama_partition.py:506-525``).
    """
    start, end = layer_range if layer_range is not None else (0, cfg.num_layers)
    is_gpt2 = cfg.model_type == "gpt2"

    if is_gpt2:
        layers = [_gpt2_layer(sd, i) for i in range(start, end)]
    else:
        layers = [_llama_layer(sd, i, cfg) for i in range(start, end)]

    params: Params = {}
    if layers:
        # Cast FLOAT leaves only: the gemma2 per-layer "window" leaf is
        # int32 position arithmetic — sweeping it to bf16 would mis-mask
        # keys past position ~256 (bf16 integers lose exactness there).
        params["layers"] = jax.tree.map(
            lambda x: (jnp.asarray(x, dtype)
                       if np.issubdtype(np.asarray(x).dtype, np.floating)
                       else jnp.asarray(x)),
            _stack(layers)
        )

    if include_embed:
        if is_gpt2:
            embed = {
                "wte": _np(sd["transformer.wte.weight"]),
                "wpe": _np(sd["transformer.wpe.weight"]),
            }
        else:
            embed = {"wte": _np(sd["model.embed_tokens.weight"])}
        params["embed"] = {k: jnp.asarray(v, dtype) for k, v in embed.items()}

    if include_head:
        if is_gpt2:
            params["final_norm"] = {
                "w": jnp.asarray(_np(sd["transformer.ln_f.weight"]), dtype),
                "b": jnp.asarray(_np(sd["transformer.ln_f.bias"]), dtype),
            }
        else:
            params["final_norm"] = {
                "w": jnp.asarray(_np(sd["model.norm.weight"]), dtype)
            }
        if not cfg.tie_word_embeddings:
            head = sd.get("lm_head.weight")
            if head is not None:
                params["lm_head"] = {"w": jnp.asarray(_np(head).T, dtype)}
            else:
                # checkpoint ties embeddings even if config says otherwise
                key = "transformer.wte.weight" if is_gpt2 else "model.embed_tokens.weight"
                params["lm_head"] = {"w": jnp.asarray(_np(sd[key]).T, dtype)}
        if cfg.tie_word_embeddings and not include_embed:
            # a last-stage shard with tied embeddings still needs wte for the head
            key = "transformer.wte.weight" if is_gpt2 else "model.embed_tokens.weight"
            params["embed"] = {"wte": jnp.asarray(_np(sd[key]), dtype)}

    return params


def import_hf_model(hf_model, dtype=np.float32) -> Tuple[ModelConfig, Params]:
    """Convert an in-memory transformers model (e.g. the test oracle)."""
    cfg = config_from_hf(hf_model.config)
    return cfg, convert_state_dict(cfg, hf_model.state_dict(), dtype)


# ---------------------------------------------------------------------------
# Per-stage checkpoint streaming (petals/server/from_pretrained.py:81-128):
# a stage server reads ONLY the safetensors shards containing its span's
# tensors — the full model is never materialized on any single host.
# ---------------------------------------------------------------------------

class LazyCheckpoint(Mapping):
    """Lazy Mapping over a local HF checkpoint directory.

    Keys resolve through the safetensors index (``model.safetensors.index
    .json`` for sharded checkpoints, the single ``model.safetensors``
    otherwise); a tensor's bytes are read only when ``convert_state_dict``
    actually touches its key, and only from the shard that holds it —
    the TPU-native analogue of the reference's per-block shard filtering
    (``petals/server/from_pretrained.py:100-108``). ``.opened`` records
    which shard files were read (observable in tests: a middle stage must
    not touch the embedding/head shards)."""

    def __init__(self, path: str):
        import json
        import os

        self.path = path
        self.opened: set = set()
        self._files: Dict[str, Any] = {}  # shard -> cached safe_open handle
        self._weight_map: Dict[str, str] = {}
        index = os.path.join(path, "model.safetensors.index.json")
        single = os.path.join(path, "model.safetensors")
        if os.path.exists(index):
            with open(index) as f:
                self._weight_map = dict(json.load(f)["weight_map"])
        elif os.path.exists(single):
            from safetensors import safe_open

            with safe_open(single, framework="flax") as f:
                self._weight_map = {k: "model.safetensors" for k in f.keys()}
        else:
            raise FileNotFoundError(
                f"no model.safetensors[.index.json] under {path} "
                "(only safetensors checkpoints support per-stage streaming)"
            )
        # Official GPT-2-era checkpoints (and any save of the BASE model)
        # store keys without the LM-head wrapper prefix ('h.0...', 'wte...'
        # instead of 'transformer.h.0...'); llama equivalents drop 'model.'.
        # Alias the prefixed names convert_state_dict asks for onto them.
        self._alias: Dict[str, str] = {}
        for prefix in ("transformer.", "model."):
            if not any(k.startswith(prefix) for k in self._weight_map):
                self._alias.update(
                    {prefix + k: k for k in self._weight_map}
                )

    def _shard(self, fname: str):
        import os

        handle = self._files.get(fname)
        if handle is None:
            from safetensors import safe_open

            # framework="flax" handles every HF dtype incl. bfloat16 (the
            # "np" framework rejects bf16). Handles are cached per shard —
            # reopening per tensor would reparse the header every time.
            handle = safe_open(os.path.join(self.path, fname),
                               framework="flax")
            self._files[fname] = handle
        return handle

    def __getitem__(self, key: str) -> np.ndarray:
        key = self._alias.get(key, key)
        fname = self._weight_map[key]
        self.opened.add(fname)
        # Pin the materialization to host memory: on a TPU host the flax
        # framework would otherwise bounce every tensor through HBM.
        with jax.default_device(jax.devices("cpu")[0]):
            t = self._shard(fname).get_tensor(key)
        return np.asarray(t)

    def __iter__(self):
        return iter(self._weight_map)

    def __len__(self) -> int:
        return len(self._weight_map)


def config_from_checkpoint(path: str) -> ModelConfig:
    from transformers import AutoConfig

    return config_from_hf(AutoConfig.from_pretrained(path, local_files_only=True))


def load_stage_checkpoint(path: str, cfg: ModelConfig, spec,
                          dtype=np.float32) -> Params:
    """Load exactly one stage's parameters from a local HF checkpoint,
    reading only the shards its span touches (never the full model).
    `spec` is a ``models.partition.StageSpec``."""
    sd = LazyCheckpoint(path)
    return convert_state_dict(
        cfg, sd, dtype,
        layer_range=(spec.start, spec.end),
        include_embed=spec.is_first,
        include_head=spec.is_last,
    )
