"""Hot-path phase profiler: where does one token's wall time actually go?

bench.py answers that question offline; this module answers it LIVE. A
`PhaseProfiler` brackets the serving hot path into named phases —

  * ``gateway_queue`` — admission to first pipeline step (serving/gateway.py)
  * ``burst_build``   — host-side burst argument prep (``_burst_prep``)
  * ``dispatch``      — issuing the jitted burst program (host returns as soon
                        as XLA enqueues; this is pure host overhead)
  * ``device``        — dispatch to results-ready, fenced via
                        ``block_until_ready`` so it measures the accelerator,
                        not the host's willingness to look away
  * ``readback``      — device buffers to host tokens (``_burst_collect``)
  * ``socket``        — client-observed request/response turnaround per hop
  * ``server``        — the whole serving boundary (validate + forward +
                        respond, runtime/transport.py)

— into per-phase aggregates, mirrored into the catalog histogram
``server_phase_seconds{phase}`` whenever the metrics registry is enabled.

On top of the phases it keeps the **device bubble-fraction** gauge: the
fraction of wall time the accelerator sat idle between burst dispatches.
Each ``device_interval(dispatch_t, ready_t)`` charges ``busy`` time from
``max(dispatch_t, previous_ready_t)`` to ``ready_t`` — so overlapped
(double-buffered) dispatches, where the next program is enqueued before the
previous one drains, correctly count as zero bubble, while a host stall
between rounds shows up as idle device time. This is the live meter for the
ROADMAP question "is the serving path device-bound or host-bound".

Default OFF, exactly like the metrics registry: every bracket site checks one
attribute and allocates nothing when disabled (``--profile_phases`` flips it).
Measuring the ``device`` phase requires fencing the dispatch, which trades
away the burst engine's dispatch/compute overlap — that fidelity cost is the
reason the profiler is a separate switch from ``--telemetry`` instead of
riding it.

The module also owns the compact **stats digest** each stage server gossips
for ``--mode top`` (``DIGEST_FIELDS`` + ``stats_digest()``): tok/s, queue
depth, breaker opens, cache hit ratio, bubble fraction — small enough to ride
a gossip record, rich enough to render a whole-swarm table with no registry.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional, Tuple

from . import catalog
from .metrics import MetricsRegistry, get_registry

# Phases bracketed on the serving hot path (display order).
PHASES: Tuple[str, ...] = (
    "gateway_queue",
    "burst_build",
    "dispatch",
    "device",
    "readback",
    "socket",
    "server",
)

# Fields of the stats digest a stage server publishes over gossip for
# ``--mode top``. scripts/check_metrics_documented.py pins this tuple against
# the digest table in docs/OBSERVABILITY.md, so the view and its docs cannot
# drift.
DIGEST_FIELDS: Tuple[str, ...] = (
    "tok_s",
    "tokens_total",
    "queue_depth",
    "breaker_open",
    "cache_hit_ratio",
    "bubble_frac",
    "moe_drop_frac",
    "moe_hot_share",
    "uptime_s",
)


class _PhaseStat:
    __slots__ = ("count", "total_s", "max_s")

    def __init__(self):
        self.count = 0
        self.total_s = 0.0
        self.max_s = 0.0


class _NoopBracket:
    """Shared inert context manager: the disabled profiler's ``phase()``
    returns this one object, so a dark bracket site costs one attribute
    check and zero allocation."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        return None


_NOOP_BRACKET = _NoopBracket()


class _Bracket:
    """One live phase bracket (``with prof.phase("dispatch"):``)."""

    __slots__ = ("_prof", "_name", "_t0")

    def __init__(self, prof: "PhaseProfiler", name: str):
        self._prof = prof
        self._name = name
        self._t0 = time.perf_counter()

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        self._prof.observe(self._name, time.perf_counter() - self._t0)
        return None


class PhaseProfiler:
    """Per-phase wall-time aggregator + device bubble accounting.

    Thread-safe; all mutators early-return when disabled. ``observe`` mirrors
    into the catalog's ``server_phase_seconds`` histogram, which itself
    no-ops unless the metrics registry is enabled — so the profiler works
    standalone (``snapshot()``) and feeds Prometheus when both are on.
    """

    def __init__(self, enabled: bool = False,
                 registry: Optional[MetricsRegistry] = None):
        self.enabled = bool(enabled)
        self._registry = registry
        self._lock = threading.Lock()
        self._stats: Dict[str, _PhaseStat] = {}
        self._hist_cache: Dict[str, object] = {}
        # Device bubble accounting (see device_interval).
        self._last_ready: Optional[float] = None
        self._busy_s = 0.0
        self._wall_s = 0.0
        self._intervals = 0

    # -- enablement ---------------------------------------------------------

    def set_enabled(self, on: bool) -> None:
        self.enabled = bool(on)

    # -- phase brackets -----------------------------------------------------

    def phase(self, name: str):
        """Context manager timing one phase occurrence. Disabled: returns the
        shared no-op bracket."""
        if not self.enabled:
            return _NOOP_BRACKET
        return _Bracket(self, name)

    def observe(self, name: str, seconds: float) -> None:
        """Record one phase occurrence of `seconds` wall time."""
        if not self.enabled:
            return
        if seconds < 0.0:
            seconds = 0.0
        with self._lock:
            st = self._stats.get(name)
            if st is None:
                st = self._stats[name] = _PhaseStat()
            st.count += 1
            st.total_s += seconds
            if seconds > st.max_s:
                st.max_s = seconds
            hist = self._hist_cache.get(name)
            if hist is None:
                reg = self._registry if self._registry is not None \
                    else get_registry()
                hist = catalog.get("server_phase_seconds",
                                   reg).labels(phase=name)
                self._hist_cache[name] = hist
        hist.observe(seconds)

    # -- device bubble accounting -------------------------------------------

    def device_interval(self, dispatch_t: float, ready_t: float) -> None:
        """Account one fenced dispatch: program issued at `dispatch_t`,
        results ready at `ready_t` (both ``time.perf_counter()``).

        Busy time is charged from ``max(dispatch_t, previous ready_t)`` to
        ``ready_t``: an overlapped dispatch (issued before the previous
        program drained) contributes no idle time, while a gap between the
        previous ready and this dispatch is a bubble — wall time the device
        spent waiting on the host."""
        if not self.enabled:
            return
        self.observe("device", ready_t - dispatch_t)
        with self._lock:
            anchor = self._last_ready
            if anchor is None or anchor > ready_t:
                anchor = dispatch_t
            wall = max(0.0, ready_t - anchor)
            busy = max(0.0, ready_t - max(dispatch_t, anchor))
            self._wall_s += wall
            self._busy_s += busy
            self._intervals += 1
            self._last_ready = ready_t

    def bubble_fraction(self) -> float:
        """Fraction of wall time the device sat idle between dispatches
        (0..1). Zero until at least two intervals have been accounted."""
        with self._lock:
            if self._wall_s <= 0.0:
                return 0.0
            return max(0.0, 1.0 - self._busy_s / self._wall_s)

    # -- reading ------------------------------------------------------------

    def snapshot(self) -> Dict[str, Dict[str, float]]:
        """Per-phase aggregates: {phase: {count, total_s, mean_s, max_s}}."""
        with self._lock:
            out = {}
            for name, st in self._stats.items():
                out[name] = {
                    "count": float(st.count),
                    "total_s": st.total_s,
                    "mean_s": st.total_s / st.count if st.count else 0.0,
                    "max_s": st.max_s,
                }
            return out

    def reset(self) -> None:
        with self._lock:
            self._stats.clear()
            self._last_ready = None
            self._busy_s = 0.0
            self._wall_s = 0.0
            self._intervals = 0


# -- process-global profiler (default OFF, like the metrics registry) --------

_GLOBAL = PhaseProfiler(enabled=False)


def get_profiler() -> PhaseProfiler:
    return _GLOBAL


def enable_phase_profiling() -> None:
    """Flip the global profiler on (``--profile_phases``) and wire the
    bubble-fraction gauge so a metrics scrape reads it live."""
    _GLOBAL.set_enabled(True)
    catalog.get("server_device_bubble_ratio").set_function(
        _GLOBAL.bubble_fraction)


def disable_phase_profiling() -> None:
    _GLOBAL.set_enabled(False)


# -- swarm stats digest (gossiped for --mode top) -----------------------------


class _RateMeter:
    """Rolling rate between successive reads of a monotonic total."""

    __slots__ = ("_t", "_v")

    def __init__(self):
        self._t: Optional[float] = None
        self._v = 0.0

    def rate(self, value: float) -> float:
        now = time.monotonic()
        prev_t, prev_v = self._t, self._v
        self._t, self._v = now, value
        if prev_t is None or now <= prev_t:
            return 0.0
        return max(0.0, (value - prev_v) / (now - prev_t))


_TOK_RATE = _RateMeter()


def _metric_sum(reg: MetricsRegistry, name: str,
                only_label: Optional[Tuple[str, str]] = None) -> float:
    """Sum an (optionally labeled) family's current values; 0.0 when the
    family was never touched."""
    fam = reg.get(name)
    if fam is None:
        return 0.0
    children = fam.children() if hasattr(fam, "children") else (fam,)
    total = 0.0
    for child in children:
        if only_label is not None and only_label not in child.labels:
            continue
        try:
            total += float(child.value)
        except Exception:
            continue
    return total


def stats_digest(registry: Optional[MetricsRegistry] = None,
                 profiler: Optional[PhaseProfiler] = None,
                 rate_meter: Optional[_RateMeter] = None
                 ) -> Dict[str, float]:
    """Assemble the compact per-server digest gossiped for ``--mode top``.

    Every key in DIGEST_FIELDS is always present (zeros when the registry is
    disabled or a family untouched), so the top renderer never branches on
    missing columns."""
    reg = registry if registry is not None else get_registry()
    prof = profiler if profiler is not None else get_profiler()
    meter = rate_meter if rate_meter is not None else _TOK_RATE

    tokens = (_metric_sum(reg, "server_tokens_total")
              + _metric_sum(reg, "gateway_tokens_served_total"))
    hits = _metric_sum(reg, "server_prefix_cache_hits_total")
    misses = _metric_sum(reg, "server_prefix_cache_misses_total")
    lookups = hits + misses
    # Sparse MoE dispatch health (models/moe.py): drop fraction over this
    # process's lifetime, hottest expert's share of the last dispatch.
    # Zero for dense models — the columns render "-"-free but inert.
    routed = _metric_sum(reg, "moe_tokens_total")
    dropped = _metric_sum(reg, "moe_dropped_total")
    return {
        "tok_s": round(meter.rate(tokens), 2),
        "tokens_total": tokens,
        "queue_depth": (_metric_sum(reg, "server_task_queue_depth")
                        + _metric_sum(reg, "gateway_queue_depth")),
        "breaker_open": _metric_sum(reg, "client_breaker_transitions_total",
                                    only_label=("state", "open")),
        "cache_hit_ratio": (hits / lookups) if lookups else 0.0,
        "bubble_frac": round(prof.bubble_fraction(), 4),
        "moe_drop_frac": round((dropped / routed) if routed else 0.0, 4),
        "moe_hot_share": round(_metric_sum(reg, "moe_max_expert_share"), 4),
        "uptime_s": round(reg.uptime_s(), 1),
    }
