"""CLI flag surface + mode smoke runs (reference src/main.py:775-838 parity)."""

import pytest

from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.main import (
    ByteTokenizer,
    build_parser,
    main,
)


def test_reference_flag_surface_present():
    """Every reference flag that still makes sense on TPU must parse."""
    p = build_parser()
    args = p.parse_args([
        "--model", "gpt2", "--splits", "10,20,30", "--stage", "0",
        "--dtype", "bfloat16", "--prompt", "x", "--max_new_tokens", "4",
        "--temperature", "0.5", "--top_p", "0.8", "--top_k", "10",
        "--request_timeout", "30", "--use_load_balancing",
        "--num_blocks", "8", "--total_blocks", "32",
        "--balance_quality", "0.75", "--mean_balance_check_period", "120",
        "--network_bandwidth_mbps", "100",
    ])
    assert args.splits == "10,20,30"
    assert args.use_load_balancing
    assert args.balance_quality == 0.75


def test_byte_tokenizer_roundtrip():
    t = ByteTokenizer()
    assert t.decode(t.encode("hello")) == "hello"


@pytest.mark.parametrize("mode_args", [
    ["--mode", "local", "--splits", "3,6,9"],
    ["--mode", "local", "--use_load_balancing", "--num_servers", "2",
     "--splits", "3"],
    ["--mode", "oracle"],
    ["--mode", "fused", "--num_stages", "2"],
    ["--mode", "fused", "--tp", "2", "--num_stages", "2"],
    ["--mode", "fused", "--num_stages", "2", "--ring_sessions", "3",
     "--prompt", "hi||there||you"],
])
def test_cli_modes_run(mode_args, capsys):
    rc = main(mode_args + [
        "--model", "gpt2", "--max_new_tokens", "3", "--temperature", "0",
        "--prompt", "hi",
    ])
    assert rc == 0 or rc is None
    out = capsys.readouterr().out
    assert "TTFT" in out and "tokens/s" in out


def test_ring_sessions_cli_matches_single_session_fused(capsys):
    """--ring_sessions must be a SCHEDULING change only: each session's
    text equals what single-session fused mode generates for its prompt."""
    common = ["--model", "gpt2", "--max_new_tokens", "4",
              "--temperature", "0"]
    singles = []
    for p in ("hi", "yo"):
        rc = main(["--mode", "fused", "--num_stages", "2",
                   "--prompt", p] + common)
        assert rc == 0 or rc is None
        out = capsys.readouterr().out
        gen = out.split("===")[1:]           # "Generation (...)" block
        text = out.split("===")[2].splitlines()[1]
        singles.append(text)

    rc = main(["--mode", "fused", "--num_stages", "2",
               "--ring_sessions", "2", "--prompt", "hi||yo"] + common)
    assert rc == 0 or rc is None
    out = capsys.readouterr().out
    blocks = out.split("=== Session ")[1:]
    ring_texts = [b.splitlines()[1] for b in blocks]
    assert ring_texts == singles, (
        f"ring sessions diverged from single-session fused: "
        f"{ring_texts} vs {singles}")


def test_quantized_fused_matches_quantized_oracle(capsys):
    """--quant int8 serves through the fused pipeline AND the oracle with
    identical quantization, so their greedy outputs must agree (the
    int8-vs-full-precision delta is the model's business; the engines'
    parity is ours)."""
    common = ["--model", "gpt2", "--max_new_tokens", "5",
              "--temperature", "0", "--prompt", "hi", "--quant", "int8"]
    rc = main(["--mode", "oracle"] + common)
    assert rc == 0 or rc is None
    oracle_text = capsys.readouterr().out.split("===")[2].splitlines()[1]

    rc = main(["--mode", "fused", "--num_stages", "2"] + common)
    assert rc == 0 or rc is None
    fused_text = capsys.readouterr().out.split("===")[2].splitlines()[1]
    assert fused_text == oracle_text


def test_quant_with_tp_rejected_on_fused_path():
    """--quant x --tp would silently replicate quantized leaves over the
    tp axis (the psum then scales every projection by tp) — must refuse
    loudly, mirroring the TP stage engine's own guard."""
    with pytest.raises(SystemExit, match="quant.*tp"):
        main(["--mode", "fused", "--num_stages", "2", "--tp", "2",
              "--quant", "int8", "--model", "gpt2", "--prompt", "hi",
              "--max_new_tokens", "2", "--temperature", "0"])


def test_ring_sessions_speculative_cli_matches_plain_ring(capsys):
    """--ring_sessions x --speculative_k compose: drafted tokens ride the
    rotation and greedy output is token-identical to the non-speculative
    ring (the speculative invariant), with the acceptance stat printed."""
    common = ["--mode", "fused", "--num_stages", "2", "--ring_sessions", "2",
              "--model", "gpt2", "--max_new_tokens", "6",
              "--temperature", "0", "--prompt", "hi||yo"]
    rc = main(common)
    assert rc == 0 or rc is None
    plain = [b.splitlines()[1] for b in
             capsys.readouterr().out.split("=== Session ")[1:]]

    rc = main(common + ["--speculative_k", "3"])
    assert rc == 0 or rc is None
    out = capsys.readouterr().out
    spec = [b.splitlines()[1] for b in out.split("=== Session ")[1:]]
    assert spec == plain, (
        f"speculative ring diverged from plain ring: {spec} vs {plain}")
    assert "Speculative:" in out and "rounds" in out


@pytest.mark.parity
def test_fused_sampled_cli_matches_oracle(capsys):
    """Single-session --mode fused with temperature > 0 runs the full
    sampler on the pipeline's logits with the oracle key schedule —
    text equals --mode oracle at the same seed."""
    common = ["--model", "gpt2", "--max_new_tokens", "5", "--prompt", "hi",
              "--temperature", "0.8", "--top_p", "0.9", "--top_k", "20",
              "--repetition_penalty", "1.3", "--seed", "29"]
    rc = main(["--mode", "oracle"] + common)
    assert rc == 0 or rc is None
    oracle_text = capsys.readouterr().out.split("===")[2].splitlines()[1]
    rc = main(["--mode", "fused", "--num_stages", "2"] + common)
    assert rc == 0 or rc is None
    fused_text = capsys.readouterr().out.split("===")[2].splitlines()[1]
    assert fused_text == oracle_text


@pytest.mark.parity
def test_ring_sessions_sampled_cli_matches_oracle(capsys):
    """temperature > 0 ring serving runs the FULL reference sampler inside
    the rotation: each session's text must equal --mode oracle (the fused
    sampled engine) for its prompt at the same seed."""
    common = ["--model", "gpt2", "--max_new_tokens", "5",
              "--temperature", "0.8", "--top_p", "0.9", "--top_k", "20",
              "--repetition_penalty", "1.3", "--seed", "17"]
    singles = []
    for p in ("hi", "yo"):
        rc = main(["--mode", "oracle", "--prompt", p] + common)
        assert rc == 0 or rc is None
        out = capsys.readouterr().out
        singles.append(out.split("===")[2].splitlines()[1])

    rc = main(["--mode", "fused", "--num_stages", "2",
               "--ring_sessions", "2", "--prompt", "hi||yo"] + common)
    assert rc == 0 or rc is None
    out = capsys.readouterr().out
    blocks = out.split("=== Session ")[1:]
    ring_texts = [b.splitlines()[1] for b in blocks]
    assert ring_texts == singles, (
        f"sampled ring sessions diverged from the oracle sampler: "
        f"{ring_texts} vs {singles}")


def test_metrics_and_status_exit_nonzero_on_unreachable_server(capsys):
    """A registered-but-dead server must not scrape clean: --mode metrics
    exits 1 and --mode status exits 2, each naming the unreachable peer on
    stderr so cron/CI notices even when other peers answered."""
    import socket

    from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.runtime.net import (
        RegistryServer,
        RemoteRegistry,
    )
    from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.scheduling.registry import (
        ServerRecord,
    )

    # Grab a free port and release it: a registered address nothing listens
    # on (the just-crashed-server shape).
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    dead_addr = f"127.0.0.1:{s.getsockname()[1]}"
    s.close()

    srv = RegistryServer(port=0)
    srv.start()
    try:
        remote = RemoteRegistry(srv.address)
        remote.register(ServerRecord(
            peer_id="dead-peer", start_block=0, end_block=8,
            final_stage=True, address=dead_addr))

        rc = main(["--mode", "metrics", "--registry_addr", srv.address])
        captured = capsys.readouterr()
        assert rc == 1
        assert "dead-peer" in captured.err
        assert "unreachable" in captured.err

        rc = main(["--mode", "status", "--registry_addr", srv.address,
                   "--total_blocks", "8"])
        captured = capsys.readouterr()
        assert rc == 2                          # coverage fine, probe dead
        assert "dead-peer" in captured.err
        assert dead_addr in captured.err
        assert "unreachable" in captured.err
    finally:
        srv.stop()


def test_status_mode_coverage_summary(capsys):
    """--mode status prints live records + the per-block coverage summary
    (the reference's get_remote_module_infos log, src/dht_utils.py:227-240)
    and exits 2 when blocks are uncovered."""
    from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.runtime.net import (
        RegistryServer,
        RemoteRegistry,
    )
    from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.scheduling.registry import (
        ServerRecord,
    )

    srv = RegistryServer(port=0)
    srv.start()
    try:
        remote = RemoteRegistry(srv.address)
        remote.register(ServerRecord(
            peer_id="a", start_block=0, end_block=4, throughput=2.0,
            next_server_rtts={"b": 0.012}))
        remote.register(ServerRecord(
            peer_id="b", start_block=4, end_block=8, final_stage=True))
        rc = main(["--mode", "status", "--registry_addr", srv.address,
                   "--total_blocks", "8"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "2 live server(s)" in out
        assert "[0,8)x1" in out  # both spans serve 1 replica -> one run
        assert "b:12.0ms" in out
        # Now an uncovered hole -> exit 2 and an UNCOVERED marker.
        remote.unregister("b")
        rc = main(["--mode", "status", "--registry_addr", srv.address,
                   "--total_blocks", "8"])
        out = capsys.readouterr().out
        assert rc == 2
        assert "UNCOVERED" in out
        # Without --total_blocks the range shrinks to the live records —
        # but a swarm with no live FINAL stage must still read unhealthy
        # (the dead-tail case the inferred total would otherwise mask).
        rc = main(["--mode", "status", "--registry_addr", srv.address])
        out = capsys.readouterr().out
        assert rc == 2
        assert "no live FINAL-stage server" in out
        assert "inferred" in out  # the reliability warning
    finally:
        srv.stop()
