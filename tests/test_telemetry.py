"""Telemetry subsystem: registry semantics, exposition format, tracing.

Four concerns, matching ISSUE 1's test checklist:

  * histogram bucket-edge placement (`le` is inclusive, Prometheus
    semantics) and interpolated quantiles;
  * counter/gauge/histogram thread-safety under concurrent mutation;
  * the text exposition's exact golden output (any drift here breaks real
    scrapers, so the assertion is byte-for-byte);
  * cross-stage trace propagation through a REAL in-process 2-remote-hop
    pipeline — one client span and one server span per stage hop, all on
    one trace_id, timestamps nested, reconstructable end-to-end.
"""

import threading

import jax

from test_runtime_pipeline import build_cluster, tiny_cfg

from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu import (
    telemetry,
)
from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.ops.sampling import (
    SamplingParams,
)
from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.telemetry import (
    MetricsRegistry,
    Tracer,
    catalog,
    exposition,
    get_tracer,
    reconstruct,
)


# -- histogram semantics ------------------------------------------------------

def test_histogram_bucket_edges():
    reg = MetricsRegistry(enabled=True)
    h = reg.histogram("lat", "", buckets=(1.0, 2.0, 5.0))
    # A value exactly equal to an upper bound belongs to that bucket
    # (le="1.0" INCLUDES 1.0 — Prometheus cumulative semantics).
    for v in (0.5, 1.0, 1.5, 2.0, 5.0, 7.0):
        h.observe(v)
    assert h.bucket_counts() == [2, 4, 5, 6]   # cumulative, +Inf last
    assert h.count == 6
    assert abs(h.sum - 17.0) < 1e-9


def test_histogram_quantiles():
    reg = MetricsRegistry(enabled=True)
    h = reg.histogram("lat", "", buckets=(1.0, 2.0, 4.0))
    assert h.quantile(0.5) is None             # empty histogram
    for _ in range(10):
        h.observe(1.5)                         # all mass in (1, 2]
    q = h.quantile(0.5)
    assert 1.0 < q <= 2.0                      # interpolated inside bucket
    assert h.quantile(1.0) == 2.0
    h.observe(100.0)                           # lands in +Inf: clamps
    assert h.quantile(1.0) == 4.0              # last finite bound


def test_disabled_registry_is_noop():
    reg = MetricsRegistry(enabled=False)
    c = reg.counter("c", "")
    g = reg.gauge("g", "")
    h = reg.histogram("h", "", buckets=(1.0,))
    c.inc(5)
    g.set(3)
    h.observe(0.5)
    assert c.value == 0.0 and g.value == 0.0 and h.count == 0
    reg.enable()
    c.inc(5)
    assert c.value == 5.0                      # same handle, flag flipped


# -- concurrency --------------------------------------------------------------

def test_counter_gauge_histogram_concurrency():
    reg = MetricsRegistry(enabled=True)
    c = reg.counter("reqs_total", "", labels=("k",)).labels(k="x")
    g = reg.gauge("occ", "")
    h = reg.histogram("lat", "", buckets=(0.5, 1.0))
    n_threads, n_iters = 8, 2000

    def work():
        for _ in range(n_iters):
            c.inc()
            g.inc(2.0)
            g.dec(1.0)
            h.observe(0.7)

    threads = [threading.Thread(target=work) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    total = n_threads * n_iters
    assert c.value == float(total)
    assert abs(g.value - total) < 1e-6
    assert h.count == total
    assert h.bucket_counts() == [0, total, total]


# -- exposition format --------------------------------------------------------

def test_exposition_golden_output():
    reg = MetricsRegistry(enabled=True)
    reg.counter("requests_total", "Requests.",
                labels=("outcome",)).labels(outcome="ok").inc(2)
    reg.gauge("occupancy", "Occupancy.").set(0.5)
    h = reg.histogram("lat_seconds", "Latency.", buckets=(0.1, 1.0))
    for v in (0.05, 0.5, 5.0):
        h.observe(v)
    assert exposition.render(reg) == (
        "# HELP lat_seconds Latency.\n"
        "# TYPE lat_seconds histogram\n"
        'lat_seconds_bucket{le="0.1"} 1\n'
        'lat_seconds_bucket{le="1"} 2\n'
        'lat_seconds_bucket{le="+Inf"} 3\n'
        "lat_seconds_sum 5.55\n"
        "lat_seconds_count 3\n"
        "# HELP occupancy Occupancy.\n"
        "# TYPE occupancy gauge\n"
        "occupancy 0.5\n"
        "# HELP requests_total Requests.\n"
        "# TYPE requests_total counter\n"
        'requests_total{outcome="ok"} 2\n'
    )


def test_register_all_exposes_required_families():
    """The acceptance scrape must show every catalogued family even with zero
    traffic (register_all materializes the schema on enable())."""
    reg = MetricsRegistry(enabled=True)
    catalog.register_all(reg)
    text = exposition.render(reg)
    for name in ("server_step_latency_seconds", "server_tokens_total",
                 "server_kv_occupancy_ratio", "server_prefix_cache_hits_total",
                 "client_retries_total"):
        assert f"# TYPE {name} " in text
    # Every catalogued name appears (the check_metrics_documented contract).
    for name in catalog.all_names():
        assert f"# HELP {name} " in text


def test_summary_aggregate():
    reg = MetricsRegistry(enabled=True)
    step = catalog.get("server_step_latency_seconds", reg)
    for _ in range(10):
        step.labels(phase="decode").observe(0.004)
    catalog.get("server_prefix_cache_hits_total", reg).inc(3)
    catalog.get("server_prefix_cache_misses_total", reg).inc(1)
    s = exposition.summary(reg)
    assert s["steps_total"] == 10
    assert s["steps_per_s"] > 0
    assert 1.0 <= s["step_p50_ms"] <= 10.0
    assert s["cache_hit_rate"] == 0.75


# -- tracing ------------------------------------------------------------------

def test_wire_context_roundtrip():
    tr = Tracer(enabled=True)
    root = tr.start_span("pipeline_step", kind="client")
    ctx = root.wire_context(hop=2)
    assert set(ctx) == {"trace_id", "parent", "hop"}
    assert ctx["trace_id"] == root.trace_id
    assert ctx["parent"] == root.span_id
    assert ctx["hop"] == 2
    srv = tr.span_from_wire(ctx, "server_forward", kind="server")
    assert srv.trace_id == root.trace_id
    assert srv.parent_id == root.span_id
    srv.end()
    root.end()
    wire = srv.to_wire()
    assert wire["trace_id"] == root.trace_id
    assert wire["start_s"] <= wire["end_s"]


def test_disabled_tracer_is_noop():
    tr = Tracer(enabled=False)
    s = tr.start_span("x")
    assert not s
    assert s.wire_context(0) is None and s.to_wire() is None
    assert tr.span_from_wire({"trace_id": "t", "parent": "p", "hop": 0},
                             "y") is not None
    assert tr.spans() == ()


def test_trace_propagation_two_stage_pipeline():
    """Decode steps through a REAL 2-remote-hop in-process pipeline must
    yield one reconstructable trace per step: a client root, one client span
    per hop, and one SERVER span per hop (recorded by LocalTransport at the
    serving boundary), all sharing the trace_id, with server timestamps
    nested inside the client hop's window."""
    telemetry.enable()
    tracer = get_tracer()
    tracer.clear()
    try:
        cfg = tiny_cfg()
        client, _, _, _, _ = build_cluster(cfg, splits="3,6")
        client.generate([5, 9, 23, 7, 81], max_new_tokens=3,
                        sampling=SamplingParams(temperature=0.0))
        traces = reconstruct(tracer.spans())
        decode_traces = []
        prefill_traces = []
        for tid, spans in traces.items():
            roots = [s for s in spans if s.name == "pipeline_step"]
            assert len(roots) == 1, "one root span per pipeline step"
            if roots[0].attrs.get("phase") == "decode":
                decode_traces.append((roots[0], spans))
            else:
                prefill_traces.append((roots[0], spans))
        assert len(prefill_traces) == 1
        assert len(decode_traces) >= 1      # >=1 decode step ran

        # Prefill covers the client-local stage0 hop too.
        _, pspans = prefill_traces[0]
        assert any(s.name == "hop:stage0" for s in pspans)

        for root, spans in decode_traces:
            hops = {s.name: s for s in spans
                    if s.kind == "client" and s.name.startswith("hop:")}
            servers = [s for s in spans if s.name == "server_forward"]
            assert set(hops) == {"hop:stage1", "hop:stage2"}
            assert len(servers) == 2, "one server span per stage hop"
            for s in spans:
                assert s.end_s is not None and s.end_s >= s.start_s
                if s is not root:
                    assert s.parent_id == root.span_id
            # Server-side work sits inside the client hop's wall window
            # (same process, same clock) and identifies its serving peer;
            # the client hop also carries the server's reported span.
            by_peer = {s.attrs.get("peer"): s for s in servers}
            for hop in hops.values():
                srv = by_peer[hop.attrs["peer"]]
                assert hop.start_s <= srv.start_s
                assert srv.end_s <= hop.end_s
                assert hop.attrs["server"]["span_id"] == srv.span_id
    finally:
        telemetry.disable()
        tracer.clear()


def test_tcp_metrics_verb_and_trace_over_wire():
    """The `metrics` wire verb returns a real exposition, `info` embeds the
    telemetry aggregate, and trace context/span summaries survive the framed
    TCP round trip (header keys, not just in-process object passing)."""
    from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.models import (
        init_params,
    )
    from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.models.partition import (
        StagePlan,
        parse_splits,
        slice_stage_params,
    )
    from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.runtime.client import (
        PipelineClient,
        make_server_record,
    )
    from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.runtime.executor import (
        StageExecutor,
    )
    from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.runtime.net import (
        RegistryServer,
        RemoteRegistry,
        TcpStageServer,
        TcpTransport,
    )

    telemetry.enable()
    tracer = get_tracer()
    tracer.clear()
    cfg = tiny_cfg()
    params = init_params(jax.random.PRNGKey(0), cfg)
    plan = StagePlan.from_splits(cfg.num_layers, parse_splits("4"))
    reg_server = RegistryServer()
    reg_server.start()
    servers = []
    try:
        spec = plan.stages[1]
        ex = StageExecutor(cfg, spec, slice_stage_params(cfg, params, spec),
                           peer_id="tcp-tele-s1")
        srv = TcpStageServer(ex, wire_dtype="f32")
        srv.start()
        rec = make_server_record("tcp-tele-s1", spec)
        rec.address = srv.address
        reg_server.registry.register(rec)
        servers.append(srv)

        registry = RemoteRegistry(reg_server.address)
        transport = TcpTransport(registry, wire_dtype="f32")
        stage0 = StageExecutor(cfg, plan.stages[0],
                               slice_stage_params(cfg, params, plan.stages[0]),
                               peer_id="client-local")
        client = PipelineClient(cfg, plan, stage0, transport, registry,
                                settle_seconds=0.0, seed=0)
        client.generate([5, 9, 23], max_new_tokens=2,
                        sampling=SamplingParams(temperature=0.0))

        # metrics verb: a real exposition with serving-boundary traffic.
        text = transport.metrics_text("tcp-tele-s1")
        assert "# TYPE server_step_latency_seconds histogram" in text
        assert 'server_requests_total{outcome="ok"}' in text

        # info verb: the compact aggregate rides the introspection frame.
        inf = transport.info("tcp-tele-s1")
        assert inf["telemetry"]["steps_total"] >= 1
        assert inf["telemetry"]["step_p50_ms"] is not None

        # Client hop spans carry the server's span summary decoded from the
        # TCP response frame's `span` header key.
        hop_spans = [s for s in tracer.spans()
                     if s.kind == "client" and s.name == "hop:stage1"]
        assert hop_spans
        wired = [s.attrs.get("server") for s in hop_spans
                 if isinstance(s.attrs.get("server"), dict)]
        assert wired, "no server span summary came back over the wire"
        for w in wired:
            assert w["name"] == "server_forward"
            assert w["start_s"] <= w["end_s"]
        transport.close()
    finally:
        telemetry.disable()
        tracer.clear()
        for s in servers:
            s.stop()
        reg_server.stop()
