"""Multi-host (DCN) layer: jax.distributed bring-up + cross-host meshes.

SURVEY.md §7.1 layer 7 — the reference's "over the Internet" story maps to
multi-pod/multi-host TPU: processes on different hosts form ONE JAX
multi-controller cluster, meshes span every host's devices, and XLA inserts
the cross-host (DCN) transfers wherever a sharding crosses a process
boundary. That replaces the reference's WAN data plane (libp2p RPC between
machines, ``src/rpc_transport.py``) for co-scheduled deployments; the framed
TCP swarm (runtime.net) remains the ELASTIC path where membership churns.

Division of labor:

  * control plane  — PlacementRegistry / RegistryServer (TTL liveness,
    elastic membership; scheduling.registry).
  * co-scheduled data plane — THIS module: `initialize()` forms the cluster,
    `global_mesh()` / `multihost_pipeline_mesh()` build device meshes whose
    axes span hosts, and the existing pjit/shard_map code (parallel.pipeline,
    parallel.tensor_parallel, parallel.ring_attention) runs on them
    UNCHANGED — multi-controller SPMD, every process executes the same
    program on its shard.
  * elastic data plane — framed TCP (runtime.net) between independent
    single-host processes.

CPU testing: a 2-process cluster over loopback with gloo collectives
(tests/test_dcn.py) exercises real cross-process psum/ppermute — the
in-process analogue the reference never had for its multi-machine setup
(SURVEY.md §4 "multi-node without a cluster: not simulated").
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Optional, Sequence, Tuple

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class DcnConfig:
    """One process's slot in the multi-host cluster.

    Mirrors the reference's bootstrap contract (every server needs the DHT
    initial peer, ``--dht_initial_peers``): every process needs the
    coordinator address and its own rank."""

    coordinator_address: str          # "host:port" of process 0's coordinator
    num_processes: int
    process_id: int
    # Tests / virtual clusters: force an n-device CPU host platform in THIS
    # process before the backend initializes (None = use real devices).
    cpu_devices_per_process: Optional[int] = None


def initialize(cfg: DcnConfig) -> None:
    """Form (or join) the cluster. Must run before the JAX backend
    initializes; afterwards `jax.devices()` is GLOBAL (all hosts) while
    `jax.local_devices()` is this process's slice."""
    if cfg.cpu_devices_per_process:
        from ..utils.platform import force_cpu_devices

        force_cpu_devices(cfg.cpu_devices_per_process, hard=True)
    import jax

    jax.distributed.initialize(
        coordinator_address=cfg.coordinator_address,
        num_processes=cfg.num_processes,
        process_id=cfg.process_id,
    )
    logger.info("dcn: process %d/%d up, %d local / %d global devices",
                jax.process_index(), jax.process_count(),
                jax.local_device_count(), jax.device_count())


def shutdown() -> None:
    import jax

    jax.distributed.shutdown()


def global_mesh(axis_names: Sequence[str] = ("dp",),
                axis_sizes: Optional[Sequence[int]] = None):
    """A mesh over ALL processes' devices (process-major order, so slicing
    the FIRST axis across hosts keeps each host's shard local and pushes
    only that axis's collectives onto DCN — the layout §2.3 prescribes:
    collectives ride ICI within a host, DCN only across)."""
    import jax
    import numpy as np
    from jax.sharding import Mesh

    devs = np.asarray(jax.devices())
    if axis_sizes is None:
        axis_sizes = (len(devs),) + (1,) * (len(axis_names) - 1)
    return Mesh(devs.reshape(tuple(axis_sizes)), tuple(axis_names))


def multihost_pipeline_mesh(num_stages: int, tp: int = 1):
    """("stage", "tp") mesh spanning hosts, stage-major: consecutive stages
    pack onto one host first, so only the stage boundaries that cross hosts
    pay DCN latency (the reference's per-hop WAN cost, paid at most
    (num_hosts - 1) times instead of (num_stages - 1))."""
    import jax
    import numpy as np
    from jax.sharding import Mesh

    devs = np.asarray(jax.devices())
    if num_stages * tp != len(devs):
        raise ValueError(
            f"mesh wants {num_stages}x{tp} devices, cluster has {len(devs)}")
    return Mesh(devs.reshape(num_stages, tp), ("stage", "tp"))


def sanity_check() -> Tuple[float, float]:
    """Cross-host collective smoke test: (psum of (process_id+1) over all
    devices, expected). Equal iff the cluster's data plane really spans
    processes — run on every host after initialize()."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P
    from jax.experimental.shard_map import shard_map

    mesh = global_mesh(("dp",))
    n_local = jax.local_device_count()
    local = np.full((n_local, 1), float(jax.process_index() + 1), np.float32)
    arr = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P("dp")), local)

    @jax.jit
    def f(x):
        return shard_map(lambda s: jax.lax.psum(s, "dp"),
                         mesh=mesh, in_specs=P("dp"), out_specs=P())(x)

    got = float(np.asarray(jax.device_get(f(arr).addressable_shards[0].data))[0, 0])
    # Expected sum from each device's OWNER process — exact on heterogeneous
    # clusters too (processes may contribute different device counts).
    want = float(sum(d.process_index + 1 for d in jax.devices()))
    return got, want


def ring_shift() -> bool:
    """Cross-host ppermute smoke test: shift one value around the global
    device ring (the pipeline's hop primitive, over DCN where the ring
    crosses processes). Returns True when every local shard received its
    predecessor's value."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P
    from jax.experimental.shard_map import shard_map

    mesh = global_mesh(("dp",))
    n = jax.device_count()
    # Each shard's value = its GLOBAL row index, derived from the shard's own
    # index (not process_index * local_count, which assumes every process
    # contributes the same device count — false on heterogeneous clusters).
    arr = jax.make_array_from_callback(
        (n, 1), NamedSharding(mesh, P("dp")),
        lambda idx: np.asarray(
            [[float(i)] for i in range(idx[0].start or 0,
                                       idx[0].stop if idx[0].stop is not None
                                       else n)],
            np.float32))

    @jax.jit
    def f(x):
        perm = [(i, (i + 1) % n) for i in range(n)]
        return shard_map(lambda s: jax.lax.ppermute(s, "dp", perm),
                         mesh=mesh, in_specs=P("dp"), out_specs=P("dp"))(x)

    out = f(arr)
    ok = True
    for shard in out.addressable_shards:
        got = float(np.asarray(jax.device_get(shard.data))[0, 0])
        want = float((shard.index[0].start - 1) % n)
        ok = ok and got == want
    return ok
