"""Multi-tenant serving gateway (docs/SERVING.md): admission control,
weighted-fair scheduling, and SLO-aware load shedding.

Five concerns:

  * admission primitives — token-bucket refill under an injected clock,
    tenant-config validation, the --tenants JSON parser (nested + flat);
  * the three shed gates in order (queue_full / concurrency / rate), each
    a typed non-retryable Overloaded carrying an honest retry_after_s,
    and the invariant that a full queue never charges a tenant's bucket;
  * weighted fairness — DRR realizes exact weight ratios over rotations,
    idle tenants bank no credit, and the FairQueue orders within a tenant
    by earliest deadline first (FIFO ties, deadline-less last);
  * priority threading — the gateway's per-tenant priority rides
    StageRequest over real TCP into the server's task-pool prioritizer,
    replacing DummyTaskPrioritizer's inference constant; oversized work
    comes back as typed, permanent TaskRejected (not a retryable stage
    error), and the server_task_queue_depth gauge tracks the backlog;
  * the acceptance e2e: the in-process overload soak — 4:1 served-token
    fairness, baseline-identical tokens for every admitted request, all
    three shed reasons fired, and the doctor reconstructing the refusals.
    (The multi-process variant — real OS processes for registry, stages,
    gateway, and submitter — is marked slow.)
"""

import pathlib
import socket
import subprocess
import sys
import time

import jax
import jax.numpy as jnp
import pytest

from test_runtime_pipeline import tiny_cfg

from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu import (
    telemetry,
)
from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.main import (
    overload_soak,
)
from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.models import (
    init_params,
)
from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.models.partition import (
    StagePlan,
    parse_splits,
    slice_stage_params,
)
from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.runtime.client import (
    make_server_record,
)
from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.runtime.executor import (
    StageExecutor,
)
from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.runtime.messages import (
    StageRequest,
)
from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.runtime.net import (
    RegistryServer,
    RemoteRegistry,
    TcpStageServer,
    TcpTransport,
)
from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.runtime.task_pool import (
    DummyTaskPrioritizer,
    PrioritizedTaskPool,
    StageRuntime,
    TaskPrioritizerBase,
    TaskRejected,
)
from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.serving import (
    AdmissionController,
    DeficitRoundRobin,
    FairQueue,
    Overloaded,
    TenantConfig,
    TokenBucket,
    parse_tenants_config,
)

REPO = pathlib.Path(__file__).resolve().parents[1]


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


# -- token bucket -------------------------------------------------------------

def test_token_bucket_starts_full_and_refills():
    clk = FakeClock()
    b = TokenBucket(rate=2.0, burst=4.0, now=clk)
    assert b.tokens == 4.0                      # first burst is admitted
    for _ in range(4):
        assert b.try_take(1.0)
    assert not b.try_take(1.0)                  # drained
    assert b.time_until(1.0) == pytest.approx(0.5)   # 1 token at 2/s
    clk.t += 1.0
    assert b.tokens == pytest.approx(2.0)       # refilled rate*dt
    assert b.try_take(2.0)
    clk.t += 100.0
    assert b.tokens == 4.0                      # capped at burst


def test_tenant_config_validation():
    for bad in (dict(weight=0), dict(rate=0), dict(burst=-1),
                dict(max_concurrency=0)):
        with pytest.raises(ValueError):
            TenantConfig("t", **bad)


def test_parse_tenants_config_nested_and_flat():
    tenants, qd, ma = parse_tenants_config(
        {"tenants": {"gold": {"weight": 4, "rate": 20},
                     "bronze": {}},
         "max_queue_depth": 7, "max_active": 3})
    assert set(tenants) == {"gold", "bronze"}
    assert tenants["gold"].weight == 4 and tenants["gold"].rate == 20
    assert (qd, ma) == (7, 3)
    tenants, qd, ma = parse_tenants_config({"solo": {"weight": 2}})
    assert set(tenants) == {"solo"} and (qd, ma) == (64, 8)


# -- admission gates ----------------------------------------------------------

def test_admission_gate_order_and_retry_after():
    clk = FakeClock()
    ac = AdmissionController(
        {"t": TenantConfig("t", rate=1.0, burst=2.0, max_concurrency=1)},
        max_queue_depth=2, now=clk)

    # Gate 1: global watermark, checked FIRST — the refusal must not charge
    # the tenant's bucket (the later admits below still have their burst).
    with pytest.raises(Overloaded) as ei:
        ac.try_admit("t", queue_depth=2)
    assert ei.value.reason == "queue_full" and ei.value.retry_after_s > 0
    assert ac.inflight("t") == 0

    # Gate 2: per-tenant concurrency (queued + generating).
    ac.try_admit("t", queue_depth=0)
    with pytest.raises(Overloaded) as ei:
        ac.try_admit("t", queue_depth=0)
    assert ei.value.reason == "concurrency"
    ac.release("t")
    assert ac.inflight("t") == 0

    # Gate 3: the token bucket. One burst token is left (queue_full charged
    # nothing); after it, the refusal's retry_after_s is the honest refill
    # time at rate=1/s, and advancing the clock that far admits again.
    ac.try_admit("t", queue_depth=0)
    ac.release("t")
    with pytest.raises(Overloaded) as ei:
        ac.try_admit("t", queue_depth=0)
    assert ei.value.reason == "rate"
    assert ei.value.retry_after_s == pytest.approx(1.0)
    clk.t += 1.0
    ac.try_admit("t", queue_depth=0)


def test_admission_unknown_tenant_is_keyerror():
    ac = AdmissionController({"t": TenantConfig("t")})
    with pytest.raises(KeyError):
        ac.try_admit("nope", queue_depth=0)


def test_overloaded_outside_retryable_taxonomy():
    """Overloaded (like permanent TaskRejected) must never look like the
    connection/timeout errors the client failover path retries."""
    exc = Overloaded("full", 0.25, tenant="t", reason="queue_full")
    assert isinstance(exc, RuntimeError)
    assert not isinstance(exc, (ConnectionError, TimeoutError))
    assert exc.retry_after_s == 0.25 and exc.tenant == "t"


# -- weighted fairness --------------------------------------------------------

def test_drr_realizes_weight_ratios():
    drr = DeficitRoundRobin({"gold": 4.0, "bronze": 1.0})
    picks = [drr.pick({"gold", "bronze"}) for _ in range(50)]
    assert picks.count("gold") == 40 and picks.count("bronze") == 10
    drr3 = DeficitRoundRobin({"a": 3.0, "b": 2.0, "c": 1.0})
    picks = [drr3.pick({"a", "b", "c"}) for _ in range(60)]
    assert (picks.count("a"), picks.count("b"), picks.count("c")) \
        == (30, 20, 10)


def test_drr_idle_tenant_banks_no_credit():
    drr = DeficitRoundRobin({"gold": 4.0, "bronze": 1.0})
    for _ in range(40):                      # gold idle: bronze owns the pipe
        assert drr.pick({"bronze"}) == "bronze"
    # Reactivated gold gets its weighted share, NOT a 40-pick catch-up burst.
    picks = [drr.pick({"gold", "bronze"}) for _ in range(50)]
    assert picks.count("gold") == 40 and picks.count("bronze") == 10


def test_drr_validation_and_idle():
    with pytest.raises(ValueError):
        DeficitRoundRobin({})
    with pytest.raises(ValueError):
        DeficitRoundRobin({"t": 0.0})
    drr = DeficitRoundRobin({"t": 1.0})
    assert drr.pick(set()) is None
    assert drr.pick({"unknown"}) is None     # foreign tenants are ignored


def test_fair_queue_edf_within_tenant():
    q = FairQueue({"t": 1.0})
    assert q.push("t", "late", deadline_at=30.0) == 1
    assert q.push("t", "no-deadline-1") == 2
    assert q.push("t", "soon", deadline_at=10.0) == 3
    assert q.push("t", "no-deadline-2") == 4
    order = [q.try_pop()[1] for _ in range(4)]
    # Earliest deadline first; deadline-less last, FIFO among themselves.
    assert order == ["soon", "late", "no-deadline-1", "no-deadline-2"]
    assert q.try_pop() is None


def test_fair_queue_depths_unknown_tenant_and_drain():
    q = FairQueue({"a": 1.0, "b": 1.0})
    with pytest.raises(KeyError):
        q.push("nope", "x")
    q.push("a", 1)
    q.push("a", 2)
    q.push("b", 3)
    assert q.depth() == 3 and q.depths() == {"a": 2, "b": 1}
    drained = sorted(q.drain())
    assert drained == [("a", 1), ("a", 2), ("b", 3)] and q.depth() == 0


def test_fair_queue_pop_interleaves_by_weight():
    q = FairQueue({"gold": 4.0, "bronze": 1.0})
    for i in range(10):
        q.push("gold", f"g{i}")
        q.push("bronze", f"b{i}")
    first10 = [q.pop(timeout=1.0)[0] for _ in range(10)]
    assert first10.count("gold") == 8 and first10.count("bronze") == 2


# -- task-pool watermarks + priority threading --------------------------------

def test_pool_watermark_validation_and_cli_threading():
    with pytest.raises(ValueError):
        PrioritizedTaskPool("p", high_water=4, low_water=5)
    rt = StageRuntime(high_water=32, low_water=4)
    assert all(p.high_water == 32 and p.low_water == 4
               for p in rt.pools.values())


def test_queue_depth_gauge_tracks_backlog():
    telemetry.enable()
    try:
        rt = StageRuntime()
        for _ in range(3):
            rt.submit("inference", lambda: None)
        g = telemetry.catalog.get("server_task_queue_depth")
        assert g.labels(pool="inference").value == 3.0
        while rt.run_once():
            pass
        assert g.labels(pool="inference").value == 0.0
    finally:
        telemetry.disable()


def test_priority_kwarg_replaces_inference_constant():
    p = DummyTaskPrioritizer()
    assert p.prioritize("inference", 1) == 1.0          # reference constant
    assert p.prioritize("inference", 1, priority=0.25) == 0.25
    assert p.prioritize("forward", 1, priority=0.25) == 2.0  # only inference
    # And the runtime orders by it: a gold-tenant step (priority 1/4) must
    # run before an earlier-submitted default-priority step.
    rt = StageRuntime()
    order = []
    rt.submit("inference", lambda: order.append("default"))
    rt.submit("inference", lambda: order.append("gold"), priority=0.25)
    while rt.run_once():
        pass
    assert order == ["gold", "default"]


@pytest.fixture(scope="module")
def wire():
    """One registry + one runtime-backed stage server over real TCP, with a
    recording prioritizer (max_batch_size tiny so oversized work is easy)."""

    class Recorder(TaskPrioritizerBase):
        def __init__(self):
            self.calls = []
            self._inner = DummyTaskPrioritizer()

        def prioritize(self, kind, size, **kwargs):
            self.calls.append((kind, size, dict(kwargs)))
            return self._inner.prioritize(kind, size, **kwargs)

    cfg = tiny_cfg()
    params = init_params(jax.random.PRNGKey(0), cfg)
    plan = StagePlan.from_splits(cfg.num_layers, parse_splits("4"))
    rec_prio = Recorder()
    reg_server = RegistryServer()
    reg_server.start()
    spec = plan.stages[1]
    ex = StageExecutor(cfg, spec, slice_stage_params(cfg, params, spec),
                       peer_id="serving-s1")
    srv = TcpStageServer(ex, wire_dtype="f32",
                         runtime=StageRuntime(prioritizer=rec_prio,
                                              max_batch_size=4))
    srv.start()
    rec = make_server_record(ex.peer_id, spec)
    rec.address = srv.address
    reg_server.registry.register(rec)
    reg = RemoteRegistry(reg_server.address)
    yield {"cfg": cfg, "reg": reg, "peer": ex.peer_id, "prio": rec_prio}
    srv.stop()
    reg_server.stop()


def _prefill(cfg, session_id, seq_len, priority=None):
    return StageRequest(
        session_id=session_id,
        hidden=jnp.zeros((1, seq_len, cfg.hidden_size), jnp.float32),
        seq_len=seq_len, cur_len=0, is_prefill=True, max_length=16,
        priority=priority)


def test_oversized_task_is_typed_permanent_rejection(wire):
    """size > max_batch_size must surface as TaskRejected(permanent=True)
    on the CLIENT — not as a retryable stage error that burns the retry
    budget on work that can never succeed anywhere."""
    tx = TcpTransport(wire["reg"], wire_dtype="f32")
    try:
        with pytest.raises(TaskRejected) as ei:
            tx.call(wire["peer"], _prefill(wire["cfg"], "oversize", 5))
        assert ei.value.permanent
        assert not isinstance(ei.value, (ConnectionError, TimeoutError))
    finally:
        tx.close()


def test_gateway_priority_reaches_server_prioritizer(wire):
    """StageRequest.priority rides the wire into the task pool, replacing
    DummyTaskPrioritizer's inference constant (1.0) with 1/tenant_weight."""
    tx = TcpTransport(wire["reg"], wire_dtype="f32")
    try:
        wire["prio"].calls.clear()
        tx.call(wire["peer"], _prefill(wire["cfg"], "prio-gold", 2,
                                       priority=0.25))
        tx.call(wire["peer"], _prefill(wire["cfg"], "prio-default", 2))
        inf = [kw for kind, _, kw in wire["prio"].calls
               if kind == "inference"]
        assert inf[0].get("priority") == 0.25    # gateway-stamped
        assert inf[1].get("priority") is None    # no gateway: constant
    finally:
        tx.close()


# -- acceptance e2e: the overload soak ----------------------------------------

def test_overload_soak_fairness_tokens_and_shedding():
    cfg = tiny_cfg()
    params = init_params(jax.random.PRNGKey(0), cfg)
    res = overload_soak(cfg, params, prompt_ids=[1, 2, 3, 4, 5],
                        max_new_tokens=6, seed=0, splits=(3, 5),
                        wire_dtype="f32", request_timeout=30.0,
                        requests_per_tenant=2)
    assert res["ok"], res["problems"]
    assert res["gold_served"] > 0 and res["bronze_served"] > 0
    # All three admission gates fired, each with an honest retry hint.
    assert set(res["shed_reasons"]) == {"rate", "concurrency", "queue_full"}
    assert all(v > 0 for v in res["shed_reasons"].values())
    # The doctor reconstructed the refusals from the event ring.
    assert res["shed_chains"] >= 1


def test_overload_soak_burst_granularity():
    """The soak with --burst 4: gateway sessions decode in 4-tick jitted
    bursts against a full-span batched peer (the sequential no-gateway
    baseline stays per-step — it is the token oracle), sessions join/
    leave at burst boundaries, and the DRR is charged N tokens per pick,
    so the served-token fairness window still tracks the 4:1 weights at
    burst granularity."""
    cfg = tiny_cfg()
    params = init_params(jax.random.PRNGKey(0), cfg)
    res = overload_soak(cfg, params, prompt_ids=[1, 2, 3, 4, 5],
                        max_new_tokens=6, seed=0, splits=(3, 5),
                        wire_dtype="f32", request_timeout=30.0,
                        requests_per_tenant=2, burst=4)
    assert res["ok"], res["problems"]
    assert res["burst"] == 4
    assert res["gold_served"] > 0 and res["bronze_served"] > 0
    # Burst scheduling must not break the admission gates either.
    assert set(res["shed_reasons"]) == {"rate", "concurrency", "queue_full"}


@pytest.mark.slow
def test_gateway_multiprocess_drill():
    """Full-fidelity serving path: registry, stage servers, gateway, and a
    submitting tenant as separate OS processes over real sockets."""
    import os

    MAIN = ("global_capstone_design_distributed_inference_of_llms_over_the"
            "_internet_tpu.main")
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env.setdefault("PALLAS_AXON_POOL_IPS", "")
    reg_port, gw_port = 31471, 31472
    reg_addr = f"127.0.0.1:{reg_port}"
    procs = []

    def spawn(role_args):
        proc = subprocess.Popen(
            [sys.executable, "-m", MAIN, "--model", "gpt2"] + role_args,
            cwd=REPO, env=env, stdout=subprocess.DEVNULL,
            stderr=subprocess.STDOUT)
        procs.append(proc)
        return proc

    def wait_port(port, deadline_s=120.0):
        deadline = time.time() + deadline_s
        while time.time() < deadline:
            for proc in procs:
                assert proc.poll() is None, \
                    f"a swarm process exited early (rc={proc.returncode})"
            try:
                socket.create_connection(("127.0.0.1", port),
                                         timeout=1.0).close()
                return
            except OSError:
                time.sleep(0.5)
        raise AssertionError(f"port {port} never came up")

    try:
        spawn(["--mode", "registry", "--registry_port", str(reg_port)])
        wait_port(reg_port)
        for stage in (1, 2):
            spawn(["--mode", "serve", "--splits", "4,8",
                   "--stage", str(stage), "--registry_addr", reg_addr])
        deadline = time.time() + 180
        while time.time() < deadline:
            try:
                if len(RemoteRegistry(reg_addr).live_servers()) >= 2:
                    break
            except OSError:
                pass
            time.sleep(1.0)
        else:
            raise AssertionError("stage servers never registered")
        spawn(["--mode", "gateway", "--splits", "4,8",
               "--registry_addr", reg_addr, "--rpc_port", str(gw_port),
               "--tenants", '{"gold": {"weight": 4}, "bronze": {}}'])
        wait_port(gw_port)
        rc = subprocess.call(
            [sys.executable, "-m", MAIN, "--model", "gpt2",
             "--mode", "submit", "--gateway_addr", f"127.0.0.1:{gw_port}",
             "--tenant", "gold", "--prompt", "hello", "--max_new_tokens",
             "8", "--submit_requests", "2", "--deadline_s", "120"],
            cwd=REPO, env=env, timeout=300)
        assert rc == 0
    finally:
        for proc in procs:
            proc.terminate()
        for proc in procs:
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
