"""Benchmark: steady-state decode throughput on the real chip.

Prints ONE JSON line {"metric", "value", "unit", "vs_baseline"}.

Workload: gpt2 (124M, the reference's primary config — README.md:46-53) in
bfloat16, batch 8, 64-token prefill, 64 fused greedy decode steps.

Methodology notes (both matter on tunneled/async backends):
  * The WHOLE decode runs as ONE jitted lax.scan program — the TPU-idiomatic
    equivalent of the reference's CUDA-graph decode path
    (petals/llama/cuda_graphs.py): zero per-step host round trips, XLA
    replays one compiled while-loop.
  * Timing is closed by FETCHING the final tokens to the host
    (np.asarray), not block_until_ready(): on tunneled backends
    block_until_ready can return before device completion, which silently
    turns the measurement into dispatch throughput. The final tokens
    data-depend on every step, so their arrival bounds real completion.
  * Best of 3 runs with DISTINCT prompts per run (identical inputs can be
    served from caches on some backends).

The reference publishes no numbers (BASELINE.md), so vs_baseline compares
against the previous round's own recording (BENCH_r*.json) when present,
else 1.0.
"""

import glob
import json
import re
import time

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.models import (
    full_forward,
    get_config,
    init_kv_cache,
    init_params,
)

BATCH = 8
PREFILL = 64
DECODE_STEPS = 64
# Cache bucket: smallest power-of-two holding prefill + decode — matches
# the runtime's bucket policy (runtime/kv_cache.py DEFAULT_BUCKETS), so the
# bench exercises the same shapes serving does. (128 holds 64+64 exactly;
# the previous 256 doubled per-step attention-cache traffic for nothing —
# measured 3002 -> 3397 tok/s on the v5e chip.)
MAX_LEN = 128
assert PREFILL + DECODE_STEPS <= MAX_LEN


def main():
    cfg = get_config("gpt2")
    params = init_params(jax.random.PRNGKey(0), cfg, dtype=jnp.bfloat16)

    @partial(jax.jit, donate_argnums=(2, 3))
    def prefill(params, ids, kc, vc):
        logits, kc, vc = full_forward(cfg, params, ids, kc, vc, jnp.int32(0))
        return jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32), kc, vc

    @partial(jax.jit, donate_argnums=(2, 3))
    def decode_all(params, tok, kc, vc):
        def body(carry, _):
            tok, kc, vc, cl = carry
            logits, kc, vc = full_forward(cfg, params, tok[:, None], kc, vc, cl)
            tok = jnp.argmax(logits[:, 0], axis=-1).astype(jnp.int32)
            return (tok, kc, vc, cl + 1), tok

        (tok, kc, vc, _), toks = jax.lax.scan(
            body, (tok, kc, vc, jnp.int32(PREFILL)), None,
            length=DECODE_STEPS)
        return toks, kc, vc

    def run(seed: int) -> float:
        ids = jax.random.randint(jax.random.PRNGKey(seed),
                                 (BATCH, PREFILL), 0, cfg.vocab_size,
                                 jnp.int32)
        kc, vc = init_kv_cache(cfg, cfg.num_layers, BATCH, MAX_LEN,
                               dtype=jnp.bfloat16)
        tok, kc, vc = prefill(params, ids, kc, vc)
        np.asarray(tok)  # hard sync: prefill fully done before the clock
        t0 = time.perf_counter()
        toks, kc, vc = decode_all(params, tok, kc, vc)
        np.asarray(toks[-1])  # hard sync: final step's tokens on host
        return time.perf_counter() - t0

    run(999)  # compile
    dt = min(run(s) for s in (1, 2, 3))
    tokens_per_s = BATCH * DECODE_STEPS / dt

    prev = None
    for path in sorted(glob.glob("BENCH_r*.json"),
                       key=lambda p: int(re.search(r"r(\d+)", p).group(1))):
        try:
            with open(path) as f:
                rec = json.load(f)
            if rec.get("unit") == "tokens/s":
                prev = rec.get("value")
        except Exception:
            pass
    vs = tokens_per_s / prev if prev else 1.0

    print(json.dumps({
        "metric": "gpt2_bf16_b8_decode_throughput",
        "value": round(tokens_per_s, 2),
        "unit": "tokens/s",
        "vs_baseline": round(vs, 3),
    }))


if __name__ == "__main__":
    main()
