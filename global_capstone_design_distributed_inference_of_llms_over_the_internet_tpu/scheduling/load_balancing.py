"""Load balancing: span placement over the block axis (Petals Appendix D).

Behavior-parity port of BOTH objective variants the reference carries
(deliberately divergent — see the comparison at ``src/load_balancing.py:181-195``):

  * ``objective="weakest"`` — the mini-Petals variant
    (``src/load_balancing.py:175-209``): place the span over the window that
    minimizes (window min, window mean, start index) — fill the most
    bottlenecked segment first. Supports a ``min_block`` floor protecting the
    client-local layer prefix (``src/main.py:338-339``).
  * ``objective="minmax"`` — the upstream Petals variant
    (``petals/server/block_selection.py:23-25``): lexicographic comparison of
    the SORTED window throughputs (classic min-max placement).

Rule 1 (`choose_best_blocks`) picks a joining server's span; rule 2
(`should_choose_other_blocks`) periodically simulates "what if I moved, and
everyone then relaxed?" and triggers a re-span when the swarm's bottleneck
throughput would improve by more than ``1/balance_quality``.

Race-avoidance details preserved (SURVEY.md §5.2): deterministic peer
ordering before accumulation (float-sum order stability), the ``(1 + eps)``
self-removal that biases ties toward the current position, the disjoint-
pipeline guard, and the quality eps-guard against rebalance oscillation.
The relaxation loop is capped at 10 iterations for "weakest"
(``src/load_balancing.py:339-355``) and unbounded for "minmax"
(``petals/server/block_selection.py:70-86`` runs ``while moved``) — capped
here too by a large safety bound so a pathological cycle cannot hang a
server's rebalance thread.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..telemetry import catalog as _tm
from ..telemetry import events as _ev
from .registry import ServerRecord, ServerState

EPS = 1e-3

WEAKEST = "weakest"
MINMAX = "minmax"

_MAX_RELAX_ITERS = {WEAKEST: 10, MINMAX: 1000}


@dataclasses.dataclass
class Span:
    """One server's contiguous block span (RemoteSpanInfo analogue)."""

    peer_id: str
    start: int
    end: int
    throughput: float

    @property
    def length(self) -> int:
        return self.end - self.start

    def move_to(self, new_start: int) -> None:
        self.start, self.end = new_start, new_start + self.length


def spans_from_records(records: Sequence[ServerRecord],
                       include_states: Sequence[str] = (
                           ServerState.JOINING, ServerState.ONLINE,
                       )) -> Dict[str, Span]:
    """Build the per-peer span map from registry records.

    The reference reconstructs spans from per-block DHT records
    (``src/load_balancing.py:61-148``), including a quirk where a peer
    advertising disjoint ranges keeps only its LAST span; our registry stores
    one contiguous span per server record, so this is a direct projection —
    a peer registered twice keeps the later record (same last-wins outcome).
    """
    out: Dict[str, Span] = {}
    for r in records:
        if r.state not in include_states:
            continue
        out[r.peer_id] = Span(r.peer_id, r.start_block, r.end_block, r.throughput)
    return out


def compute_block_throughputs(spans: Dict[str, Span], total_blocks: int) -> np.ndarray:
    """Per-block summed throughput. Accumulation order is sorted by peer id so
    identical swarms always produce bit-identical floats — unordered sums
    jitter at the ULP level and cause spurious rebalances
    (``petals/server/block_selection.py:13-16``)."""
    th = np.zeros(total_blocks)
    for span in sorted(spans.values(), key=lambda s: s.peer_id):
        th[span.start:span.end] += span.throughput
    return th


def choose_best_start(
    throughputs: np.ndarray,
    num_blocks: int,
    min_block: int = 0,
    objective: str = WEAKEST,
) -> int:
    """Best start index for a span of num_blocks under the given objective."""
    n = len(throughputs)
    if n < num_blocks:
        return max(0, int(min_block))
    max_i = n - num_blocks
    lo = int(max(0, min(min_block, max_i)))
    windows = range(lo, max_i + 1)
    if objective == WEAKEST:
        return min(
            windows,
            key=lambda i: (
                float(np.min(throughputs[i:i + num_blocks])),
                float(np.mean(throughputs[i:i + num_blocks])),
                i,
            ),
        )
    if objective == MINMAX:
        return min(
            windows,
            key=lambda i: (sorted(throughputs[i:i + num_blocks].tolist()), i),
        )
    raise ValueError(f"unknown objective {objective!r}")


def choose_best_blocks(
    num_blocks: int,
    records: Sequence[ServerRecord],
    total_blocks: int,
    min_block: int = 0,
    objective: str = WEAKEST,
) -> List[int]:
    """Rule 1: a joining server picks the span that best helps the swarm."""
    spans = spans_from_records(records)
    th = compute_block_throughputs(spans, total_blocks)
    start = choose_best_start(th, num_blocks, min_block=min_block,
                              objective=objective)
    return list(range(start, start + num_blocks))


def should_choose_other_blocks(
    local_peer_id: str,
    records: Sequence[ServerRecord],
    total_blocks: int,
    balance_quality: float = 0.75,
    min_block: int = 0,
    objective: str = WEAKEST,
    rng: Optional[np.random.Generator] = None,
) -> bool:
    """Rule 2: should this server re-span? Simulates its own move plus an
    iterative relaxation of every peer, then compares bottleneck throughput.

    balance_quality > 1.0 forces True (debugging hook, both variants).
    """
    _tm.get("scheduler_rebalance_checks_total").inc()
    if balance_quality > 1.0:
        _tm.get("scheduler_rebalance_moves_total").inc()
        _ev.emit("rebalance_recommended", peer=local_peer_id,
                 quality=0.0, threshold=balance_quality)
        return True
    # Seeded default: the re-span coin flip must be reproducible across
    # soak reruns when the server wiring does not inject its own generator.
    rng = rng or np.random.default_rng(0)

    spans = spans_from_records(records)
    th = compute_block_throughputs(spans, total_blocks)

    # Bottleneck is evaluated over the SERVABLE range [min_block, total):
    # with a protected client-local prefix no server ever covers
    # [0, min_block), so the reference's full-range min is pinned at 0 and its
    # rule 2 can never fire when min_block > 0 (``src/load_balancing.py:297``
    # + ``:357-366`` — initial and new throughput both 0). Restricting the
    # window restores the rule's intent; min_block=0 reproduces the reference
    # exactly.
    lo_eval = int(max(0, min(min_block, total_blocks)))

    def bottleneck(a: np.ndarray) -> float:
        view = a[lo_eval:]
        return float(np.min(view)) if len(view) else 0.0

    initial = bottleneck(th)

    if local_peer_id not in spans:
        return False
    local = spans[local_peer_id]

    # Remove own span; (1 + eps) biases ties toward staying put.
    lo = max(0, min(local.start, total_blocks - 1))
    hi = min(local.end, total_blocks)
    if hi > lo:
        th[lo:hi] -= local.throughput * (1 + EPS)

    # Disjoint-pipeline guard: if removing us would zero out some block, a
    # move would disconnect the swarm.
    if initial > EPS and bottleneck(th) <= 0:
        return False

    new_start = choose_best_start(th, local.length, min_block=min_block,
                                  objective=objective)
    if local.start == new_start:
        return False

    th[local.start:local.end] += local.throughput * EPS
    local.move_to(new_start)
    th[local.start:local.end] += local.throughput

    moved, it = True, 0
    while moved and it < _MAX_RELAX_ITERS[objective]:
        it += 1
        order = list(spans.keys())
        rng.shuffle(order)
        moved = False
        for pid in order:
            span = spans[pid]
            th[span.start:span.end] -= span.throughput * (1 + EPS)
            cand = choose_best_start(th, span.length, min_block=min_block,
                                     objective=objective)
            th[span.start:span.end] += span.throughput * EPS
            if span.start != cand:
                span.move_to(cand)
                moved = True
            th[span.start:span.end] += span.throughput

    new = bottleneck(th)
    if new < initial or new < EPS:
        return False
    quality = initial / new
    move = quality < balance_quality - EPS
    if move:
        _tm.get("scheduler_rebalance_moves_total").inc()
        _ev.emit("rebalance_recommended", peer=local_peer_id,
                 quality=round(quality, 4), threshold=balance_quality)
    return move
