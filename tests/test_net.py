"""TCP data plane + registry service over real sockets.

The multi-host story the reference ran on libp2p/Kademlia, exercised here
with real TCP servers on localhost: framed wire protocol with CRC, bf16
payload compression, registry-mediated discovery, failover across server
processes, and the rpc_info introspection verb.
"""

import random
import socket

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu import (
    native,
)
from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.models import (
    init_params,
)
from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.models.partition import (
    StagePlan,
    parse_splits,
    slice_stage_params,
)
from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.ops.sampling import (
    SamplingParams,
)
from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.runtime.client import (
    PipelineClient,
    make_server_record,
)
from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.runtime.executor import (
    StageExecutor,
)
from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.runtime.net import (
    RegistryServer,
    RemoteRegistry,
    TcpStageServer,
    TcpTransport,
    _encode_tensor,
    _decode_tensor,
)

from test_runtime_pipeline import oracle_generate, tiny_cfg


@pytest.fixture
def swarm(request):
    """Registry server + per-stage TCP servers (replicas), torn down after."""
    cfg = tiny_cfg()
    params = init_params(jax.random.PRNGKey(0), cfg)
    plan = StagePlan.from_splits(cfg.num_layers, parse_splits("2,4,6"))

    reg_server = RegistryServer()
    reg_server.start()
    servers = []
    replicas = getattr(request, "param", 1)
    for spec in plan.stages[1:]:
        for r in range(replicas):
            peer = f"tcp-s{spec.index}-r{r}"
            ex = StageExecutor(cfg, spec, slice_stage_params(cfg, params, spec),
                               peer_id=peer)
            srv = TcpStageServer(ex, wire_dtype="f32")
            srv.start()
            rec = make_server_record(peer, spec)
            rec.address = srv.address
            reg_server.registry.register(rec)
            servers.append(srv)

    registry = RemoteRegistry(reg_server.address)
    transport = TcpTransport(registry, wire_dtype="f32")
    stage0 = StageExecutor(cfg, plan.stages[0],
                           slice_stage_params(cfg, params, plan.stages[0]),
                           peer_id="client-local")
    client = PipelineClient(cfg, plan, stage0, transport, registry,
                            settle_seconds=0.0)
    yield cfg, params, client, transport, servers, reg_server
    transport.close()
    for s in servers:
        s.stop()
    reg_server.stop()


def test_tensor_codec_roundtrip():
    rng = np.random.default_rng(0)
    x = rng.standard_normal((2, 3, 8)).astype(np.float32)
    meta, body = _encode_tensor(x, "f32")
    np.testing.assert_array_equal(_decode_tensor(meta, body), x)
    meta, body = _encode_tensor(x, "bf16")
    assert len(body) == x.size * 2  # halved payload
    got = _decode_tensor(meta, body)
    np.testing.assert_allclose(got, x, atol=0.04, rtol=0.02)
    ids = np.arange(6, dtype=np.int32).reshape(2, 3)
    meta, body = _encode_tensor(ids, "bf16")
    np.testing.assert_array_equal(_decode_tensor(meta, body), ids)


def test_generation_over_tcp_matches_oracle(swarm):
    cfg, params, client, _, _, _ = swarm
    sampling = SamplingParams(temperature=0.0)
    res = client.generate([5, 9, 23, 7], max_new_tokens=6, sampling=sampling)
    ref = oracle_generate(cfg, params, [5, 9, 23, 7], 6, sampling)
    assert res.tokens == ref


@pytest.mark.parametrize("swarm", [2], indirect=True)
def test_tcp_failover_mid_generation(swarm):
    cfg, params, client, transport, servers, _ = swarm
    sampling = SamplingParams(temperature=0.0)
    # Kill the stage-2 server that ACTUALLY serves the session (observed
    # from the calls — the route is affinity-keyed, so pre-computing
    # client.route() could watch a replica the generation never uses).
    stage2 = {s.executor.peer_id: s for s in servers
              if s.executor.spec.index == 2}

    calls = [0]
    orig_call = transport.call

    def failing_call(peer_id, req, timeout=None):
        if peer_id in stage2 and not req.is_prefill and not req.is_replay:
            calls[0] += 1
            if calls[0] == 2:
                stage2[peer_id].stop()
        return orig_call(peer_id, req, timeout)

    transport.call = failing_call
    res = client.generate([5, 9, 23, 7], max_new_tokens=6, sampling=sampling)
    ref = oracle_generate(cfg, params, [5, 9, 23, 7], 6, sampling)
    assert res.tokens == ref
    assert client.recoveries >= 1


def test_info_verb(swarm):
    cfg, params, client, transport, servers, _ = swarm
    info = transport.info(servers[0].executor.peer_id)
    assert info["start_block"] == servers[0].executor.spec.start
    assert info["cache_tokens_left"] > 0
    assert info["version"] == 1


def test_swarm_stats_verb(swarm):
    """`swarm-stats` answers with the peer's own digest plus its gossip
    records — registry-free input for `--mode top` (PROTOCOL.md row)."""
    cfg, params, client, transport, servers, _ = swarm
    peer = servers[0].executor.peer_id
    view = transport.swarm_stats(peer)
    assert view["peer_id"] == peer
    assert "self" in view
    assert isinstance(view["records"], list)


def test_bf16_wire_generation_completes():
    """bf16 wire (reference ships fp16): halved payloads, generation runs."""
    cfg = tiny_cfg()
    params = init_params(jax.random.PRNGKey(0), cfg)
    plan = StagePlan.from_splits(cfg.num_layers, [4])
    reg = RegistryServer()
    reg.start()
    ex = StageExecutor(cfg, plan.stages[1],
                       slice_stage_params(cfg, params, plan.stages[1]),
                       peer_id="bf16-srv")
    srv = TcpStageServer(ex, wire_dtype="bf16")
    srv.start()
    rec = make_server_record("bf16-srv", plan.stages[1])
    rec.address = srv.address
    reg.registry.register(rec)
    registry = RemoteRegistry(reg.address)
    transport = TcpTransport(registry, wire_dtype="bf16")
    stage0 = StageExecutor(cfg, plan.stages[0],
                           slice_stage_params(cfg, params, plan.stages[0]),
                           peer_id="client")
    client = PipelineClient(cfg, plan, stage0, transport, registry,
                            settle_seconds=0.0)
    try:
        res = client.generate([5, 9, 23], max_new_tokens=4,
                              sampling=SamplingParams(temperature=0.0))
        assert len(res.tokens) >= 1
        assert all(0 <= t < cfg.vocab_size for t in res.tokens)
    finally:
        transport.close()
        srv.stop()
        reg.stop()


def test_registry_service_ttl_and_discovery():
    reg = RegistryServer(ttl=0.1)
    reg.start()
    try:
        remote = RemoteRegistry(reg.address)
        from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.scheduling.registry import (
            ServerRecord,
        )

        remote.register(ServerRecord(peer_id="p1", start_block=0, end_block=4,
                                     stage_index=1, address="127.0.0.1:1"))
        assert [r.peer_id for r in remote.live_servers()] == ["p1"]
        assert remote.discover_stage(1) == "p1"
        assert remote.heartbeat("p1")
        import time

        time.sleep(0.25)
        assert remote.live_servers() == []
        assert not remote.heartbeat("p1")
    finally:
        reg.stop()


def test_dead_peer_raises_peer_unavailable():
    reg = RegistryServer()
    reg.start()
    try:
        from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.runtime.transport import (
            PeerUnavailable,
        )
        from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.scheduling.registry import (
            ServerRecord,
        )

        remote = RemoteRegistry(reg.address)
        # unreachable address
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            dead_port = s.getsockname()[1]
        remote.register(ServerRecord(
            peer_id="ghost", start_block=0, end_block=4,
            address=f"127.0.0.1:{dead_port}"))
        transport = TcpTransport(remote, connect_timeout=0.5)
        from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.runtime.messages import (
            StageRequest,
        )
        import jax.numpy as jnp

        with pytest.raises(PeerUnavailable):
            transport.call("ghost", StageRequest(
                session_id="s", hidden=jnp.zeros((1, 1, 4)), seq_len=1,
                cur_len=0, is_prefill=True, max_length=8))
    finally:
        reg.stop()


def test_concurrent_sessions_through_stage_runtime():
    """Two clients hammer one server whose compute runs through the
    prioritized StageRuntime: both generations must match the single-client
    oracle (one compute thread serializes donated-buffer steps)."""
    import threading

    from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.runtime.task_pool import (
        StageRuntime,
    )

    cfg = tiny_cfg()
    params = init_params(jax.random.PRNGKey(0), cfg)
    plan = StagePlan.from_splits(cfg.num_layers, [4])
    reg = RegistryServer()
    reg.start()
    ex = StageExecutor(cfg, plan.stages[1],
                       slice_stage_params(cfg, params, plan.stages[1]),
                       peer_id="rt-srv")
    srv = TcpStageServer(ex, wire_dtype="f32", runtime=StageRuntime())
    srv.start()
    rec = make_server_record("rt-srv", plan.stages[1])
    rec.address = srv.address
    reg.registry.register(rec)

    sampling = SamplingParams(temperature=0.0)
    prompts = [[5, 9, 23, 7], [11, 2, 30]]
    expected = [oracle_generate(cfg, params, p, 5, sampling) for p in prompts]
    results = [None, None]

    def run(i):
        registry = RemoteRegistry(reg.address)
        transport = TcpTransport(registry, wire_dtype="f32")
        stage0 = StageExecutor(cfg, plan.stages[0],
                               slice_stage_params(cfg, params, plan.stages[0]),
                               peer_id=f"client-{i}")
        client = PipelineClient(cfg, plan, stage0, transport, registry,
                                settle_seconds=0.0)
        results[i] = client.generate(prompts[i], max_new_tokens=5,
                                     sampling=sampling).tokens
        transport.close()

    threads = [threading.Thread(target=run, args=(i,)) for i in range(2)]
    try:
        for t in threads:
            t.start()
        for t in threads:
            t.join(60.0)
        assert results[0] == expected[0]
        assert results[1] == expected[1]
        assert srv.runtime.tasks_done > 0
    finally:
        srv.stop()
        reg.stop()


def test_reach_check_and_direct_reachability(swarm):
    """V10 parity: peers answer "can you reach X?" (rpc_check) and the
    >=50%-of-<=5-peers direct-reachability rule aggregates the answers."""
    from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.runtime.net import (
        check_direct_reachability,
    )

    cfg, params, client, transport, servers, reg = swarm
    a, b = servers[0], servers[1]
    # a can dial b's real address
    assert transport.reach_check(a.executor.peer_id, b.address) is True
    # nobody listens on this port
    assert transport.reach_check(a.executor.peer_id, "127.0.0.1:1") is False

    # b's address is vouched for by the other peers -> direct
    assert check_direct_reachability(transport, client.registry,
                                     b.address) is True
    assert check_direct_reachability(transport, client.registry,
                                     "127.0.0.1:1") is False


# ---------------------------------------------------------------------------
# Persistent per-session streams (petals/server/handler.py:132-308)
# ---------------------------------------------------------------------------

def test_stream_metadata_ships_once(swarm):
    """Steady-state decode sends ONE stream_open per (session, hop); every
    later step is a delta frame, and the final server's recent-token window
    (maintained server-side) matches what the client generated."""
    cfg, params, client, transport, servers, _ = swarm
    sampling = SamplingParams(temperature=0.0)
    res = client.generate([5, 9, 23, 7], max_new_tokens=6, sampling=sampling)
    ref = oracle_generate(cfg, params, [5, 9, 23, 7], 6, sampling)
    assert res.tokens == ref
    for srv in servers:
        # 1 open per hop for this session; all decode steps rode deltas.
        assert srv.stream_opens == 1, srv.executor.peer_id
        assert srv.stream_steps >= 6


def test_stream_sampled_window_parity(swarm):
    """temperature>0 with repetition penalty: the penalty window lives
    SERVER-side on the stream path — parity with the oracle proves the
    server's window tracks the client's exactly."""
    cfg, params, client, _, _, _ = swarm
    sampling = SamplingParams(temperature=0.8, top_p=0.9, top_k=40,
                              repetition_penalty=1.4)
    res = client.generate([5, 9, 23, 7], max_new_tokens=8, sampling=sampling)
    ref = oracle_generate(cfg, params, [5, 9, 23, 7], 8, sampling)
    assert res.tokens == ref


@pytest.mark.parametrize("swarm", [2], indirect=True)
def test_stream_session_failover(swarm):
    """Kill a hop mid-generation on the STREAM path: the client fails over,
    re-opens the stream (full metadata, incl. the current token window) on
    the replacement peer, and the tokens are preserved."""
    cfg, params, client, transport, servers, _ = swarm
    sampling = SamplingParams(temperature=0.7, repetition_penalty=1.3)
    # Victim = the stage-2 replica the session actually lands on (see
    # test_tcp_failover_mid_generation).
    stage2 = {s.executor.peer_id: s for s in servers
              if s.executor.spec.index == 2}
    victim_peer = [None]

    calls = [0]
    orig_call = transport.call

    def failing_call(peer_id, req, timeout=None):
        if peer_id in stage2 and not req.is_prefill and not req.is_replay:
            calls[0] += 1
            if calls[0] == 3:
                victim_peer[0] = peer_id
                stage2[peer_id].stop()
        return orig_call(peer_id, req, timeout)

    transport.call = failing_call
    res = client.generate([5, 9, 23, 7], max_new_tokens=8, sampling=sampling)
    ref = oracle_generate(cfg, params, [5, 9, 23, 7], 8, sampling)
    assert res.tokens == ref
    assert client.recoveries >= 1
    # The replacement server saw a fresh stream_open (metadata re-shipped).
    replacement = next(s for s in servers
                       if s.executor.peer_id in stage2
                       and s.executor.peer_id != victim_peer[0])
    assert replacement.stream_opens >= 1


def test_stream_step_without_open_refused(swarm):
    """A raw `step` with no stream_open is a retryable stage error, not a
    protocol wedge."""
    import jax.numpy as jnp

    from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.runtime.executor import (
        StageExecutionError,
    )
    from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.runtime.net import (
        _recv_frame,
        _send_frame,
    )

    _, _, _, _, servers, _ = swarm
    srv = servers[0]
    host, port = srv.address.rsplit(":", 1)
    with socket.create_connection((host, int(port)), timeout=5.0) as s:
        meta, body = _encode_tensor(np.zeros((1, 1), np.int32), "f32")
        _send_frame(s, {"verb": "step", "session_id": "ghost", "seq_len": 1,
                        "cur_len": 0, "tensor": meta}, body)
        h, _ = _recv_frame(s)
        assert h["verb"] == "error" and h["kind"] == "stage"
        assert "stream_open" in h["message"]


def test_stream_session_deadline_enforced(swarm):
    """A stream opened with a session deadline refuses steps (and frees the
    stream) once the deadline passes — server-side lifetime enforcement.
    The deadline check runs BEFORE compute, so compile time can't race it:
    prefill lands inside the window, the post-sleep decode step cannot."""
    import time as _time

    import jax.numpy as jnp

    from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.runtime.executor import (
        StageExecutionError,
    )
    from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.runtime.messages import (
        StageRequest,
    )

    cfg, params, client, transport, servers, _ = swarm
    hop = client.route()[0]  # stage-1 server: consumes hidden [B, T, D]
    h3 = jnp.zeros((1, 3, cfg.hidden_size), jnp.float32)
    h1 = jnp.zeros((1, 1, cfg.hidden_size), jnp.float32)
    # Warm the compile so the prefill step itself is fast.
    transport.call(hop.peer_id, StageRequest(
        session_id="warm", hidden=h3, seq_len=3, cur_len=0, is_prefill=True,
        max_length=16))
    transport.end_session(hop.peer_id, "warm")

    transport.session_deadline_s = 1.0
    transport.call(hop.peer_id, StageRequest(
        session_id="dl", hidden=h3, seq_len=3, cur_len=0, is_prefill=True,
        max_length=16))
    _time.sleep(1.5)
    with pytest.raises(StageExecutionError, match="deadline"):
        transport.call(hop.peer_id, StageRequest(
            session_id="dl", hidden=h1, seq_len=1, cur_len=3,
            is_prefill=False, max_length=16))


def test_stream_per_step_timeout_enforced_via_runtime():
    """A stream opened with a tiny step_timeout gets a retryable stage error
    from the runtime's deadline instead of hanging — the server-side
    per-step budget of petals handler.py:132-195."""
    import jax.numpy as jnp

    from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.runtime.executor import (
        StageExecutionError,
    )
    from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.runtime.messages import (
        StageRequest,
    )
    from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.runtime.task_pool import (
        StageRuntime,
    )

    cfg = tiny_cfg()
    params = init_params(jax.random.PRNGKey(0), cfg)
    plan = StagePlan.from_splits(cfg.num_layers, [4])
    reg = RegistryServer()
    reg.start()
    ex = StageExecutor(cfg, plan.stages[1],
                       slice_stage_params(cfg, params, plan.stages[1]),
                       peer_id="to-srv")
    srv = TcpStageServer(ex, wire_dtype="f32", runtime=StageRuntime())
    srv.start()
    rec = make_server_record("to-srv", plan.stages[1])
    rec.address = srv.address
    reg.registry.register(rec)
    try:
        registry = RemoteRegistry(reg.address)
        h = jnp.zeros((1, 3, cfg.hidden_size), jnp.float32)
        # Sanity: a NORMAL stream step works on this server first.
        ok_tx = TcpTransport(registry, wire_dtype="f32")
        ok_tx.call("to-srv", StageRequest(
            session_id="ok", hidden=h, seq_len=3, cur_len=0,
            is_prefill=True, max_length=16))
        ok_tx.close()
        # Deterministic slowness: wrap forward with a sleep far past the
        # budget. (The old version relied on "the first step compiles
        # slowly", but the ok-call above already warmed this executor and
        # a warm tiny-model step can beat 5 ms under synchronous CPU
        # dispatch — the enforcement plumbing, not wall-clock luck, is
        # what this test pins.)
        import time as _time

        orig_forward = ex.forward

        def slow_forward(req):
            _time.sleep(0.2)
            return orig_forward(req)

        ex.forward = slow_forward
        to_tx = TcpTransport(registry, wire_dtype="f32",
                             step_timeout=0.005)
        with pytest.raises(StageExecutionError, match="timed out"):
            to_tx.call("to-srv", StageRequest(
                session_id="slow", hidden=h, seq_len=3, cur_len=0,
                is_prefill=True, max_length=16), timeout=30.0)
        to_tx.close()
    finally:
        srv.stop()
        reg.stop()


def test_end_session_drops_stream_state(swarm):
    """end_session must free the per-session stream entry too — on a
    long-lived client connection, ended sessions would otherwise accumulate
    metadata + 50-token windows until the socket closes (ADVICE r2)."""
    cfg, params, client, transport, servers, _ = swarm
    for i in range(3):
        client.generate([5, 9, 23, 7], max_new_tokens=2,
                        sampling=SamplingParams(temperature=0.0),
                        session_id=f"es-{i}")
    for srv in servers:
        live = sum(len(d) for d in srv._streams.values())
        assert live == 0, (srv.executor.peer_id, srv._streams)


def test_structured_request_log_rides_info_verb(swarm):
    """Per-request structured records (reference _log_request,
    petals/server/handler.py:549-573, exceeded): after a generation, the
    server's info verb returns a recent-request tail with verb/session/
    duration/outcome fields, and failures are recorded with their detail."""
    cfg, params, client, transport, servers, reg_server = swarm
    rng = np.random.default_rng(5)
    prompt = [int(t) for t in rng.integers(0, cfg.vocab_size, 8)]
    client.generate(prompt, max_new_tokens=3,
                    sampling=SamplingParams(temperature=0.0))

    info = transport.info("tcp-s1-r0")
    recent = info["recent_requests"]
    assert recent, "info verb must surface the request ring"
    verbs = {r["verb"] for r in recent}
    assert "prefill" in verbs and "forward" in verbs
    assert all(r["outcome"] == "ok" for r in recent)
    # compute verbs carry timing + request identity; lifecycle records
    # (end_session) are identity-only
    for r in recent:
        if r["verb"] in ("prefill", "forward"):
            assert "dur_ms" in r and r["dur_ms"] >= 0
            assert "session" in r and "peer" in r

    # a refused request lands in the ring with its outcome + detail
    import jax.numpy as jnp

    from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.runtime.executor import (
        StageExecutionError,
    )
    from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.runtime.messages import (
        StageRequest,
    )

    with pytest.raises(StageExecutionError):
        transport.call("tcp-s1-r0", StageRequest(
            session_id="ghost", seq_len=1, cur_len=5, is_prefill=False,
            max_length=16,
            hidden=jnp.zeros((1, 1, cfg.hidden_size), jnp.float32)))
    recent = transport.info("tcp-s1-r0")["recent_requests"]
    errs = [r for r in recent if r["outcome"] != "ok"]
    assert errs and "detail" in errs[-1]


def test_wire_dtype_negotiation_f32_client_exact_over_bf16_server():
    """Per-session wire negotiation (reference parity: per-tensor
    compression choice in the serving schema, handler.py:411-432): an f32
    client against a bf16-DEFAULT server negotiates f32 responses, so the
    generation is token-identical to the oracle — without negotiation the
    server's bf16 response encoding would distort intermediate activations."""
    cfg = tiny_cfg()
    params = init_params(jax.random.PRNGKey(0), cfg)
    plan = StagePlan.from_splits(cfg.num_layers, parse_splits("4"))

    reg = RegistryServer()
    reg.start()
    ex = StageExecutor(cfg, plan.stages[1],
                       slice_stage_params(cfg, params, plan.stages[1]),
                       peer_id="nego-srv")
    srv = TcpStageServer(ex, wire_dtype="bf16")      # server DEFAULT: bf16
    srv.start()
    rec = make_server_record("nego-srv", plan.stages[1])
    rec.address = srv.address
    reg.registry.register(rec)
    registry = RemoteRegistry(reg.address)
    transport = TcpTransport(registry, wire_dtype="f32")   # client wants f32
    stage0 = StageExecutor(cfg, plan.stages[0],
                           slice_stage_params(cfg, params, plan.stages[0]),
                           peer_id="client")
    client = PipelineClient(cfg, plan, stage0, transport, registry,
                            settle_seconds=0.0)
    try:
        rng = np.random.default_rng(9)
        prompt = [int(t) for t in rng.integers(0, cfg.vocab_size, 10)]
        sampling = SamplingParams(temperature=0.0)
        got = client.generate(prompt, max_new_tokens=6,
                              sampling=sampling).tokens
        ref = oracle_generate(cfg, params, prompt, 6, sampling)
        assert got == ref, (
            "negotiated f32 responses must make the bf16-default server "
            "token-exact for an f32 client")
    finally:
        transport.close()
        srv.stop()
        reg.stop()


@pytest.mark.parametrize("swarm", [2], indirect=True)
def test_status_swarm_health_aggregates_rings(swarm, capsys):
    """--mode status aggregates every server's recent-request ring into a
    swarm-health section: the injected fault's peer shows under `errors`,
    healthy traffic shows under `slowest hops` and `cache pressure`
    (VERDICT r4 item 8 — one operator surface instead of N server logs)."""
    from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.main import (
        main as cli_main,
    )
    from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.runtime.executor import (
        StageExecutionError,
    )
    from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.runtime.messages import (
        StageRequest,
    )

    cfg, params, client, transport, servers, reg_server = swarm
    # Real traffic so rings hold ok-records with durations.
    client.generate([5, 9, 23], max_new_tokens=4,
                    sampling=SamplingParams(temperature=0.0))
    # Injected fault: a decode step for a session no server holds — the
    # handling peer logs a non-ok record in its ring.
    victim = servers[0]
    bad = StageRequest(
        session_id="no-such-session", hidden=jnp.zeros((1, 1, 64)),
        seq_len=1, cur_len=7, is_prefill=False, max_length=16,
    )
    with pytest.raises(StageExecutionError):
        transport.call(victim.peer_id, bad, timeout=5.0)

    rc = cli_main(["--mode", "status", "--registry_addr",
                   reg_server.address, "--total_blocks", "8",
                   "--splits", "2"])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "swarm health" in out
    assert f"errors: {victim.peer_id}" in out
    assert "slowest hops:" in out
    assert "cache pressure:" in out


def test_per_tensor_wire_schema():
    """Per-tensor compression (petals handler.py:411-432 parity): one
    payload can mix wire dtypes — the activation bf16-compressed, the
    learned prompts exactly f32 — and each meta records its own dtype so
    decode needs no side channel."""
    from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.runtime.net import (
        _decode_tensors,
        _encode_tensors,
    )

    rng = np.random.default_rng(0)
    hidden = rng.standard_normal((2, 3, 8)).astype(np.float32)
    prompts = rng.standard_normal((4, 2, 8)).astype(np.float32)
    metas, body = _encode_tensors([hidden, prompts], ["bf16", "f32"])
    assert [m["dtype"] for m in metas] == ["bf16", "f32"]
    h2, p2 = _decode_tensors(metas, body)
    np.testing.assert_array_equal(p2, prompts)          # bit-exact f32
    np.testing.assert_allclose(h2, hidden, atol=0.04)   # bf16 rounded
    assert metas[0]["nbytes"] == hidden.size * 2
    with pytest.raises(Exception):
        _encode_tensors([hidden, prompts], ["bf16"])    # length mismatch


def test_deep_prompts_exact_over_bf16_wire():
    """End-to-end: a bf16-wire session's deep prompts reach the server
    bit-exact (f32 schema lane), so generation matches the f32-wire run."""
    cfg = tiny_cfg()
    params = init_params(jax.random.PRNGKey(0), cfg)
    plan = StagePlan.from_splits(cfg.num_layers, parse_splits("4"))
    from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.runtime.net import (
        RegistryServer,
        RemoteRegistry,
        TcpStageServer,
        TcpTransport,
    )

    dp = np.asarray(0.5 * np.random.default_rng(9).standard_normal(
        (cfg.num_layers, 5, cfg.hidden_size)), np.float32)

    def run(wire, prompts):
        reg = RegistryServer()
        reg.start()
        servers = []
        try:
            for spec in plan.stages[1:]:
                peer = f"w{wire}-s{spec.index}"
                ex = StageExecutor(cfg, spec,
                                   slice_stage_params(cfg, params, spec),
                                   peer_id=peer)
                srv = TcpStageServer(ex, wire_dtype=wire)
                srv.start()
                rec = make_server_record(peer, spec)
                rec.address = srv.address
                reg.registry.register(rec)
                servers.append(srv)
            registry = RemoteRegistry(reg.address)
            tx = TcpTransport(registry, wire_dtype=wire)
            stage0 = StageExecutor(cfg, plan.stages[0],
                                   slice_stage_params(cfg, params,
                                                      plan.stages[0]),
                                   peer_id="c")
            client = PipelineClient(cfg, plan, stage0, tx, registry,
                                    settle_seconds=0.0)
            res = client.generate([5, 9, 23], max_new_tokens=5,
                                  sampling=SamplingParams(temperature=0.0),
                                  deep_prompts=prompts)
            tx.close()
            return res.tokens
        finally:
            for s in servers:
                s.stop()
            reg.stop()

    # The mixed-schema frame must round-trip AND the prompts must reach
    # the server with effect: the bf16-wire deep-prompt run has to
    # diverge from the bf16-wire plain run (a regression that drops or
    # corrupts the f32 prompts lane makes these equal). The lane's
    # bit-exactness is pinned by test_per_tensor_wire_schema above.
    with_p = run("bf16", dp)
    without_p = run("bf16", None)
    assert len(with_p) == 5
    assert with_p != without_p, (
        "deep prompts had no effect over the bf16 wire — the f32 prompts "
        "lane regressed")
