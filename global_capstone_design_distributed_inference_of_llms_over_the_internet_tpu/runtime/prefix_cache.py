"""Content-addressed prompt-prefix KV store (no reference counterpart).

Serving workloads repeat prompt prefixes constantly — a shared system
prompt, a few-shot preamble, a long document queried many times. The
reference recomputes every prefill from scratch (its only prefill
optimization is chunking one oversized request,
``petals/server/backend.py:129-143``). This store lets a stage skip the
span forward for a previously-seen prefix: on a prefill whose leading rows
chain-hash to stored segments, the executor copies their KV rows into the
session's arena lease and computes only the remainder.

Design:

* **Grain-chained block hashing.** The prefix is split into fixed
  ``grain``-token segments; segment k is keyed by a ROLLING digest of
  everything up to and including it (``d_k = H(coords || bytes[0:k*G])``,
  one incremental sha256 pass with per-grain snapshots). Lookup walks
  k = 1, 2, ... while the chain is unbroken — so two prompts sharing a
  100-token system preamble reuse ``floor(100/G)`` grains automatically,
  with no application-level annotation of where the shared part ends
  (clients simply mark the whole prompt shareable). The rolling digest
  makes a segment valid ONLY after its exact full prefix: segment content
  is position-dependent (attention reads everything before it), which a
  per-segment-only hash would get wrong.
* **Content-addressed, not client-named.** The digest covers the actual
  bytes entering the span (token ids on stage0, hidden-state rows
  downstream) plus the execution coordinates (block range, batch, dtypes,
  model tag). A client cannot poison another session's cache with a forged
  id, and a hit is exact by construction — same bytes through same blocks.
* **Per-segment storage** means overlapping prefixes share memory: each
  entry holds only its own ``[L, B, G, H, Dh]`` KV rows (and, off the
  final stage, its ``[B, G, D]`` output rows — a chained stage must still
  FORWARD the prefix's output to the next hop). Evicting a middle link
  merely shortens every chain through it; lookup stops at the first
  missing link.
* **Bounded bytes, LRU.** A lookup touches every link it uses — root
  last, so the link every chain depends on is the warmest of its chain.

Accepted tradeoff — the classic shared-prefix-cache timing channel: the
store is server-wide, so a client who can GUESS another session's prompt
prefix can confirm it was recently served by observing TTFT collapse (and
hit counters move on the ``info`` verb). That is inherent to cross-session
prefix sharing (vLLM/SGLang prefix caches share it); deployments serving
mutually untrusted tenants with secret prompts should leave the store off
(its default) or partition tenants across servers. Content addressing
still rules out the worse failure — serving one tenant's cached KV for a
DIFFERENT prefix — by construction.

Thread-safe: serving engines run compute on one thread, but LocalTransport
tests (and batched-adapter handler threads) may race get/put.
"""

from __future__ import annotations

import dataclasses
import hashlib
import threading
from collections import OrderedDict
from typing import List, Optional

import jax.numpy as jnp

from ..telemetry import catalog as _tm
from ..telemetry import events as _ev

# Tokens per cached segment. Smaller = finer shared-prefix matching but
# more entries and more copy calls per hit; 64 keeps a segment's KV write
# one cheap dynamic_update_slice while matching system prompts closely.
DEFAULT_GRAIN = 64


@dataclasses.dataclass
class PrefixEntry:
    """One grain's KV rows (k/v: ``[span_layers, B, G, kv_heads, head_dim]``)
    and, off the final stage, its output hidden rows (out: ``[B, G, D]``)."""

    k: jnp.ndarray
    v: jnp.ndarray
    out: Optional[jnp.ndarray]
    nbytes: int


def chain_digests(prefix_bytes_per_grain: List[bytes], coords: tuple) -> List[str]:
    """Rolling digests d_1..d_K over grain-sized byte blocks: d_k commits to
    coords + ALL bytes through grain k (one pass, snapshot per grain).

    blake2b with a 16-byte digest, not sha256: the input is the full f32
    hidden lane of the prefix (grain 64 × D floats per block — megabytes
    for long system prompts), and this runs on the serving thread of every
    store-enabled prefill, hits AND misses. blake2b is ~2x sha256 on large
    buffers with no SHA-NI dependence, and 128 bits keeps collisions
    negligible for a cache key (not a security boundary)."""
    h = hashlib.blake2b(repr(coords).encode(), digest_size=16)
    out = []
    for blk in prefix_bytes_per_grain:
        h.update(blk)
        out.append(h.hexdigest())
    return out


class PrefixStore:
    """Bounded LRU of :class:`PrefixEntry` keyed by rolling chain digest."""

    def __init__(self, max_bytes: int, grain: int = DEFAULT_GRAIN):
        self.max_bytes = int(max_bytes)
        self.grain = int(grain)
        self._entries: "OrderedDict[str, PrefixEntry]" = OrderedDict()
        self._lock = threading.Lock()
        self.used_bytes = 0
        self.hits = 0          # lookups that reused >= 1 grain
        self.misses = 0        # lookups that reused none
        self.grains_reused = 0
        self.evictions = 0
        # Registry mirrors of the counters above (process-global telemetry;
        # no-op unless enabled). The ints stay authoritative for ``stats()``
        # — the info verb must work with telemetry off.
        self._m_hits = _tm.get("server_prefix_cache_hits_total")
        self._m_misses = _tm.get("server_prefix_cache_misses_total")
        self._m_evictions = _tm.get("server_prefix_cache_evictions_total")
        self._m_grains = _tm.get("server_prefix_cache_grains_reused_total")
        self._m_bytes = _tm.get("server_prefix_cache_used_bytes")

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def lookup_chain(self, keys: List[str],
                     need_out: bool) -> List[PrefixEntry]:
        """Longest unbroken chain of stored segments for rolling digests
        ``keys``; with ``need_out`` (intermediate stages) a KV-only link
        ends the chain. Touches every returned link (LRU)."""
        got: List[PrefixEntry] = []
        used: List[str] = []
        with self._lock:
            for key in keys:
                entry = self._entries.get(key)
                if entry is None or (need_out and entry.out is None):
                    break
                used.append(key)
                got.append(entry)
            # Touch ROOT-LAST: a chain is only reachable through its first
            # link, so the root must be the warmest of its chain — touching
            # in walk order would evict roots first and strand every
            # descendant as unreachable dead weight.
            for key in reversed(used):
                self._entries.move_to_end(key)
            if got:
                self.hits += 1
                self.grains_reused += len(got)
                self._m_hits.inc()
                self._m_grains.inc(len(got))
            elif keys:
                self.misses += 1
                self._m_misses.inc()
        return got

    def put(self, key: str, k: jnp.ndarray, v: jnp.ndarray,
            out: Optional[jnp.ndarray]) -> bool:
        """Insert one segment (idempotent per key), evicting LRU entries to
        fit. Returns False when the segment alone exceeds the budget."""
        nbytes = int(k.nbytes) + int(v.nbytes) + (
            int(out.nbytes) if out is not None else 0)
        if nbytes > self.max_bytes:
            return False
        entry = PrefixEntry(k=k, v=v, out=out, nbytes=nbytes)
        evicted, evicted_bytes = 0, 0
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self.used_bytes -= old.nbytes
            while self.used_bytes + nbytes > self.max_bytes and self._entries:
                _, victim = self._entries.popitem(last=False)
                self.used_bytes -= victim.nbytes
                self.evictions += 1
                self._m_evictions.inc()
                evicted += 1
                evicted_bytes += victim.nbytes
            self._entries[key] = entry
            self.used_bytes += nbytes
            self._m_bytes.set(self.used_bytes)
        if evicted:
            _ev.emit("prefix_eviction", grains=evicted, bytes=evicted_bytes)
        return True

    def stats(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._entries),
                "bytes": self.used_bytes,
                "grain": self.grain,
                "hits": self.hits,
                "misses": self.misses,
                "grains_reused": self.grains_reused,
                "evictions": self.evictions,
            }
