"""Structured logging: one formatter for every line the runtime prints.

Plain text by default (human-scannable, same shape main.py always used);
``--log-json`` switches to one JSON object per line carrying the same
trace/session fields the flight recorder and spans use — so a log
aggregator can join log lines, events, and spans on trace_id.

Context propagation is thread-local: a component entering traced work calls
``set_log_context(trace_id=..., session_id=...)`` (or uses the
``log_context`` context manager) and every log record emitted from that
thread carries the ids until cleared. Dependency-free, stdlib ``logging``
only.
"""

from __future__ import annotations

import contextlib
import json
import logging
import threading
import time
from typing import Iterator, Optional

_ctx = threading.local()


def set_log_context(trace_id: Optional[str] = None,
                    session_id: Optional[str] = None) -> None:
    _ctx.trace_id = trace_id
    _ctx.session_id = session_id


def clear_log_context() -> None:
    _ctx.trace_id = None
    _ctx.session_id = None


def get_log_context() -> tuple:
    return (getattr(_ctx, "trace_id", None),
            getattr(_ctx, "session_id", None))


@contextlib.contextmanager
def log_context(trace_id: Optional[str] = None,
                session_id: Optional[str] = None) -> Iterator[None]:
    prev = get_log_context()
    set_log_context(trace_id, session_id)
    try:
        yield
    finally:
        set_log_context(*prev)


class StructuredFormatter(logging.Formatter):
    """Text or JSON lines, both carrying trace/session context when set.

    Text:  ``2026-08-05 12:00:00 name LEVEL [trace=ab12 session=s1] msg``
    JSON:  ``{"ts": ..., "level": ..., "logger": ..., "msg": ...,
    "trace_id": ..., "session_id": ...}`` (+ ``exc`` on exceptions).
    """

    def __init__(self, json_mode: bool = False):
        super().__init__(datefmt="%Y-%m-%d %H:%M:%S")
        self.json_mode = json_mode

    def format(self, record: logging.LogRecord) -> str:
        # Explicit record attributes (logger.info(..., extra={...})) win
        # over the ambient thread-local context.
        trace_id = getattr(record, "trace_id", None)
        session_id = getattr(record, "session_id", None)
        if trace_id is None and session_id is None:
            trace_id, session_id = get_log_context()
        msg = record.getMessage()
        if self.json_mode:
            d = {
                "ts": round(record.created, 6),
                "level": record.levelname.lower(),
                "logger": record.name,
                "msg": msg,
            }
            if trace_id:
                d["trace_id"] = trace_id
            if session_id:
                d["session_id"] = session_id
            if record.exc_info:
                d["exc"] = self.formatException(record.exc_info)
            return json.dumps(d, sort_keys=True, default=str)
        ts = time.strftime("%Y-%m-%d %H:%M:%S",
                           time.localtime(record.created))
        ctx = ""
        if trace_id or session_id:
            parts = []
            if trace_id:
                parts.append(f"trace={trace_id}")
            if session_id:
                parts.append(f"session={session_id}")
            ctx = " [" + " ".join(parts) + "]"
        line = f"{ts} {record.name} {record.levelname}{ctx} {msg}"
        if record.exc_info:
            line += "\n" + self.formatException(record.exc_info)
        return line


def setup_logging(json_mode: bool = False,
                  level: int = logging.INFO) -> None:
    """Route the root logger through the structured formatter — the
    ``logging.basicConfig`` replacement main.py calls once at startup.
    Idempotent: reconfigures the existing handler on repeat calls."""
    root = logging.getLogger()
    root.setLevel(level)
    handler = None
    for h in root.handlers:
        if isinstance(getattr(h, "formatter", None), StructuredFormatter):
            handler = h
            break
    if handler is None:
        handler = logging.StreamHandler()
        root.addHandler(handler)
    handler.setFormatter(StructuredFormatter(json_mode=json_mode))
