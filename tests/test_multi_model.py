"""Multi-model swarm: two models share one registry without cross-routing.

Every reference DHT key embeds the model name (``src/dht_utils.py:20-31``;
``petals/server/server.py:738-744`` keeps a per-model registry) — so a
registry serving two models must never route a client of model A through a
server of model B. Round 1's ServerRecord had no model field; these tests
pin the fixed behavior end to end (discovery, generation, elastic span
choice, and the wire registry).
"""

import random

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.models import (
    init_params,
)
from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.models.partition import (
    StagePlan,
    parse_splits,
    slice_stage_params,
)
from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.ops.sampling import (
    SamplingParams,
)
from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.runtime.client import (
    PipelineClient,
    make_server_record,
)
from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.runtime.executor import (
    StageExecutor,
)
from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.runtime.transport import (
    LocalTransport,
)
from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.scheduling.registry import (
    PlacementRegistry,
    ServerRecord,
)

from test_runtime_pipeline import oracle_generate, tiny_cfg


def _register_swarm(cfg, params, registry, transport, model, seed):
    """Fixed-split stage servers for one model on a SHARED registry+transport."""
    plan = StagePlan.from_splits(cfg.num_layers, parse_splits("3,6"))
    for spec in plan.stages[1:]:
        peer = f"{model}-s{spec.index}"
        ex = StageExecutor(cfg, spec, slice_stage_params(cfg, params, spec),
                           peer_id=peer)
        transport.add_peer(peer, ex)
        registry.register(make_server_record(peer, spec, model=model))
    return plan


def test_two_models_one_registry_no_cross_routing():
    cfg_a = tiny_cfg("llama")
    cfg_b = tiny_cfg("gpt2")
    params_a = init_params(jax.random.PRNGKey(0), cfg_a)
    params_b = init_params(jax.random.PRNGKey(1), cfg_b)
    # Long TTL: this test's subject is model isolation, not liveness — a
    # cold-compile run of two swarms can exceed the default 45 s, expiring
    # the unrefreshed records before the final route assertions.
    registry = PlacementRegistry(rng=random.Random(0), ttl=3600.0)
    transport = LocalTransport()
    plan_a = _register_swarm(cfg_a, params_a, registry, transport, "llama", 0)
    plan_b = _register_swarm(cfg_b, params_b, registry, transport, "gpt2", 1)

    sampling = SamplingParams(temperature=0.0)
    prompt = [5, 9, 23, 7]
    for cfg, params, plan, model in ((cfg_a, params_a, plan_a, "llama"),
                                     (cfg_b, params_b, plan_b, "gpt2")):
        stage0 = StageExecutor(cfg, plan.stages[0],
                               slice_stage_params(cfg, params, plan.stages[0]),
                               peer_id=f"client-{model}")
        client = PipelineClient(cfg, plan, stage0, transport, registry,
                                settle_seconds=0.0, seed=0, model=model)
        got = client.generate(prompt, max_new_tokens=5,
                              sampling=sampling).tokens
        want = oracle_generate(cfg, params, prompt, 5, sampling)
        assert got == want, model
        # Route never touches the other model's peers.
        for hop in client.route():
            assert hop.peer_id.startswith(model)


def test_discovery_filters_by_model():
    registry = PlacementRegistry(rng=random.Random(0))
    registry.register(ServerRecord(peer_id="a0", start_block=0, end_block=4,
                                   stage_index=1, model="m-a"))
    registry.register(ServerRecord(peer_id="b0", start_block=0, end_block=4,
                                   stage_index=1, model="m-b"))
    registry.register(ServerRecord(peer_id="legacy", start_block=0,
                                   end_block=4, stage_index=1))  # untagged
    # Model-scoped queries see their model + legacy untagged records only.
    for _ in range(16):
        assert registry.discover_stage(1, model="m-a") in ("a0", "legacy")
    got = {r.peer_id for r in registry.discover_block(2, model="m-b")}
    assert got == {"b0", "legacy"}
    # Unscoped query sees everything (single-model swarm compatibility).
    got = {r.peer_id for r in registry.discover_block(2)}
    assert got == {"a0", "b0", "legacy"}
    # Coverage is scoped too (feeds load balancing / elastic span choice).
    cov = registry.coverage(4, model="m-a")
    assert all({r.peer_id for r in blk} == {"a0", "legacy"} for blk in cov)


def test_elastic_server_ignores_other_models_coverage():
    """An elastic server balancing model A must not count model B's span as
    coverage — otherwise it would leave A's blocks unserved."""
    from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.runtime.server import (
        ElasticStageServer,
    )

    cfg = tiny_cfg()
    params = init_params(jax.random.PRNGKey(2), cfg)
    registry = PlacementRegistry(rng=random.Random(0))
    transport = LocalTransport()
    # Model B fully covers [0, 8) — bait for an unscoped rule-1.
    registry.register(ServerRecord(peer_id="other-model", start_block=0,
                                   end_block=8, final_stage=True, model="b"))

    def provider(spec):
        return slice_stage_params(cfg, params, spec)

    es = ElasticStageServer("elastic-a", cfg, provider, registry, transport,
                            num_blocks=4, total_blocks=8, model="a",
                            rng=random.Random(0))
    spec = es.choose_span()
    # With no model-A servers live, rule 1 must behave as on an EMPTY swarm:
    # start at block 0 (the least-covered prefix), not skip past B's span.
    assert spec.start == 0
    es.load_span(spec)
    rec = registry.get("elastic-a")
    assert rec.model == "a"
    es.shutdown()


def test_remote_registry_model_roundtrip():
    """The model field survives the TCP registry wire schema."""
    from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.runtime.net import (
        RegistryServer,
        RemoteRegistry,
    )

    srv = RegistryServer(port=0, ttl=30.0)
    srv.start()
    try:
        reg = RemoteRegistry(srv.address)
        reg.register(ServerRecord(peer_id="x", start_block=0, end_block=4,
                                  stage_index=1, final_stage=True, model="mx"))
        reg.register(ServerRecord(peer_id="y", start_block=0, end_block=4,
                                  stage_index=1, final_stage=True, model="my"))
        assert reg.get("x").model == "mx"
        assert {r.peer_id for r in reg.live_servers(model="mx")} == {"x"}
        assert reg.discover_stage(1, model="my") == "y"
        cov = reg.coverage(4, model="mx")
        assert all({r.peer_id for r in blk} == {"x"} for blk in cov)
    finally:
        srv.stop()


def test_data_plane_rejects_model_mismatch():
    """The model id is echoed in every request and the server rejects a
    mismatch BEFORE touching the executor (ADVICE r2: registry-side scoping
    alone cannot stop a mis-constructed client from shipping model-A
    activations into model-B blocks). The error is kind="stage" (retryable),
    so the client's failover taxonomy blacklists the peer and re-discovers."""
    import jax.numpy as jnp

    from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.runtime.executor import (
        StageExecutionError,
    )
    from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.runtime.messages import (
        StageRequest,
    )
    from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.runtime.net import (
        RegistryServer,
        RemoteRegistry,
        TcpStageServer,
        TcpTransport,
    )

    cfg = tiny_cfg()
    params = init_params(jax.random.PRNGKey(0), cfg)
    plan = StagePlan.from_splits(cfg.num_layers, parse_splits("3,6"))
    spec = plan.stages[1]
    ex = StageExecutor(cfg, spec, slice_stage_params(cfg, params, spec),
                       peer_id="srv-a")
    reg_server = RegistryServer()
    reg_server.start()
    srv = TcpStageServer(ex, wire_dtype="f32", model="model-a")
    srv.start()
    try:
        rec = make_server_record("srv-a", spec, model="model-a")
        rec.address = srv.address
        reg_server.registry.register(rec)
        registry = RemoteRegistry(reg_server.address)
        hidden = jnp.zeros((1, 2, cfg.hidden_size), jnp.float32)

        def _req():
            return StageRequest(session_id="s", hidden=hidden, seq_len=2,
                                cur_len=0, is_prefill=True, max_length=8)

        # Wrong model: rejected on both the stream path (stream_open) and
        # the classic full-metadata frame path.
        for streams in (True, False):
            tx_bad = TcpTransport(registry, wire_dtype="f32",
                                  model="model-b", use_streams=streams)
            with pytest.raises(StageExecutionError, match="model mismatch"):
                tx_bad.call("srv-a", _req())
            tx_bad.close()
        # Matching model and legacy untagged client both pass.
        for model in ("model-a", None):
            tx = TcpTransport(registry, wire_dtype="f32", model=model)
            resp = tx.call("srv-a", _req())
            assert resp.hidden is not None
            tx.end_session("srv-a", "s")
            tx.close()
    finally:
        srv.stop()
        reg_server.stop()


def test_relay_propagates_client_model_tag():
    """An UNTAGGED legacy hop relaying a push chain must forward the
    originating client's model tag, not strip it — the tagged downstream
    server is the one that can still catch the mis-route."""
    import jax.numpy as jnp

    from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.runtime.executor import (
        StageExecutionError,
    )
    from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.runtime.messages import (
        StageRequest,
    )
    from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.runtime.net import (
        TcpStageServer,
        TcpTransport,
    )

    cfg = tiny_cfg()
    params = init_params(jax.random.PRNGKey(0), cfg)
    plan = StagePlan.from_splits(cfg.num_layers, parse_splits("2,4,6"))
    registry = PlacementRegistry(rng=random.Random(0))
    servers = []
    try:
        # Hop A: legacy untagged. Hop B (final): tagged model-a.
        for spec, model in ((plan.stages[1], None),
                            (plan.stages[2], "model-a")):
            peer = f"relay-s{spec.index}"
            ex = StageExecutor(cfg, spec, slice_stage_params(cfg, params, spec),
                               peer_id=peer)
            srv = TcpStageServer(ex, wire_dtype="f32", model=model)
            srv.start()
            servers.append(srv)
            rec = make_server_record(peer, spec)  # records untagged: the
            rec.address = srv.address             # mis-route must be possible
            registry.register(rec)
        tx = TcpTransport(registry, wire_dtype="f32", model="model-b",
                          use_streams=False)
        b_rec = registry.get("relay-s2")
        with pytest.raises(StageExecutionError, match="model mismatch") as ei:
            tx.call("relay-s1", StageRequest(
                session_id="s", seq_len=2, cur_len=0, is_prefill=True,
                max_length=8,
                hidden=jnp.zeros((1, 2, cfg.hidden_size), jnp.float32),
                next_servers=({"peer_id": "relay-s2",
                               "address": b_rec.address,
                               "start_block": 4, "end_block": 6},)))
        assert ei.value.peer_id == "relay-s2"  # blame lands downstream
        tx.close()
    finally:
        for srv in servers:
            srv.stop()
