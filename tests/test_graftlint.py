"""graftlint: positive controls, full-tree gate, baseline policy, and
regression pins for the races the linter caught.

Three layers (docs/STATIC_ANALYSIS.md):
  1. every analyzer family FIRES on the seeded fixtures under
     tests/fixtures/graftlint/ — a linter that can't find the planted bug
     is silently useless;
  2. the real package is CLEAN — zero findings outside
     graftlint_baseline.json, no stale suppressions, every suppression
     justified;
  3. the concrete races fixed when graftlint first ran stay fixed (their
     keys must never reappear), plus behavioral hammers for two of them.
"""

import json
import pathlib
import subprocess
import sys
import threading

import pytest

REPO = pathlib.Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

from scripts.graftlint import (  # noqa: E402
    ALL_ANALYZERS, Baseline, BaselineError, build_context, run_analyzers,
)

FIXTURES = REPO / "tests" / "fixtures" / "graftlint"


# ---------------------------------------------------------------------------
# 1. Fixtures: each analyzer family provably fires
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def fixture_findings():
    ctx = build_context(FIXTURES, pkg=FIXTURES / "pkg")
    return {f.key for f in
            run_analyzers(ctx, ["locks", "jax", "dispatch", "env_flags"])}


def test_fixture_lock_unguarded_attr_fires(fixture_findings):
    assert ("lock-unguarded-attr:pkg/locks_bad.py:Counter.peek:_count"
            in fixture_findings)


def test_fixture_blocking_under_lock_fires(fixture_findings):
    assert ("lock-blocking-call:pkg/locks_bad.py:Counter.slow_inc:time.sleep"
            in fixture_findings)


def test_fixture_lock_order_cycle_fires(fixture_findings):
    assert ("lock-order-cycle:pkg/locks_bad.py:cycle:Alpha->Beta"
            in fixture_findings)


def test_fixture_host_sync_in_jit_fires(fixture_findings):
    assert ("jax-host-sync:pkg/jax_bad.py:helper:np.asarray"
            in fixture_findings)


def test_fixture_env_read_in_jit_fires(fixture_findings):
    assert "jax-env-read:pkg/jax_bad.py:helper:environ" in fixture_findings


def test_fixture_ungated_callback_fires(fixture_findings):
    assert ("jax-callback-ungated:pkg/jax_bad.py:emit_debug:debug.callback"
            in fixture_findings)


def test_fixture_undocumented_verb_fires(fixture_findings):
    for rule in ("verb-undocumented", "verb-untested",
                 "verb-no-fault-injection"):
        assert (f"{rule}:pkg/dispatch_bad.py:phantom_verb"
                in fixture_findings)


def test_fixture_uncatalogued_env_fires(fixture_findings):
    assert ("env-uncatalogued:pkg/env_bad.py:read_uncatalogued:NOT_IN_CATALOG"
            in fixture_findings)


# ---------------------------------------------------------------------------
# 2. The real tree: zero non-baselined findings, honest baseline
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def full_tree():
    ctx = build_context(REPO)
    findings = run_analyzers(ctx, ALL_ANALYZERS)
    baseline = Baseline.load(REPO / "graftlint_baseline.json")
    return findings, baseline


def test_full_tree_has_no_unbaselined_findings(full_tree):
    findings, baseline = full_tree
    new, _, _ = baseline.split(findings)
    assert not new, "new graftlint findings:\n" + "\n".join(
        f.render() for f in new)


def test_baseline_has_no_stale_entries(full_tree):
    findings, baseline = full_tree
    _, _, stale = baseline.split(findings)
    assert not stale, (
        "stale baseline entries (fixed code must shed its suppression): "
        f"{stale}")


def test_every_baseline_entry_has_a_reason(full_tree):
    _, baseline = full_tree
    assert baseline.entries, "baseline unexpectedly empty"
    for key, reason in baseline.entries.items():
        assert reason.strip(), f"baseline entry {key!r} has empty reason"


def test_cli_exits_clean_with_json(tmp_path):
    proc = subprocess.run(
        [sys.executable, "-m", "scripts.graftlint", "--json"],
        capture_output=True, text=True, timeout=300, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    report = json.loads(proc.stdout)
    assert report["new"] == []


# ---------------------------------------------------------------------------
# 3. Baseline policy: missing reasons / duplicates / staleness are errors
# ---------------------------------------------------------------------------

def _write_baseline(tmp_path, rows):
    p = tmp_path / "graftlint_baseline.json"
    p.write_text(json.dumps({"findings": rows}), encoding="utf-8")
    return p


def test_baseline_rejects_missing_reason(tmp_path):
    p = _write_baseline(tmp_path, [{"key": "r:p:a"}])
    with pytest.raises(BaselineError, match="no reason"):
        Baseline.load(p)


def test_baseline_rejects_blank_reason(tmp_path):
    p = _write_baseline(tmp_path, [{"key": "r:p:a", "reason": "   "}])
    with pytest.raises(BaselineError, match="no reason"):
        Baseline.load(p)


def test_baseline_rejects_duplicate_key(tmp_path):
    p = _write_baseline(tmp_path, [
        {"key": "r:p:a", "reason": "x"},
        {"key": "r:p:a", "reason": "y"},
    ])
    with pytest.raises(BaselineError, match="duplicate"):
        Baseline.load(p)


def test_split_reports_stale_keys():
    baseline = Baseline({"gone-rule:gone.py:anchor": "was fixed"})
    new, suppressed, stale = baseline.split([])
    assert stale == ["gone-rule:gone.py:anchor"]
    assert not new and not suppressed


# ---------------------------------------------------------------------------
# 4. Regression pins: the races graftlint caught must stay fixed
# ---------------------------------------------------------------------------

PKG = ("global_capstone_design_distributed_inference_of_llms"
       "_over_the_internet_tpu")

FIXED_KEYS = [
    # TcpTransport read _via_relay outside its lock in three methods.
    f"lock-unguarded-attr:{PKG}/runtime/net.py:TcpTransport._connect"
    ":_via_relay",
    f"lock-unguarded-attr:{PKG}/runtime/net.py:TcpTransport._unavailable"
    ":_via_relay",
    f"lock-unguarded-attr:{PKG}/runtime/net.py:"
    "TcpTransport._note_relay_failure:_via_relay",
    # PrefixStore.__len__ read the OrderedDict without the lock.
    f"lock-unguarded-attr:{PKG}/runtime/prefix_cache.py:PrefixStore.__len__"
    ":_entries",
    # KVArena capacity counters read apart could advertise negative space.
    f"lock-unguarded-attr:{PKG}/runtime/kv_cache.py:KVArena.used_bytes"
    ":_used_bytes",
    f"lock-unguarded-attr:{PKG}/runtime/kv_cache.py:KVArena.bytes_left"
    ":_used_bytes",
    # LocalTransport.executor read the peer map during mutation.
    f"lock-unguarded-attr:{PKG}/runtime/transport.py:LocalTransport.executor"
    ":_peers",
    # EventRecorder.render_jsonl read `dropped` while emitters bumped it.
    f"lock-unguarded-attr:{PKG}/telemetry/events.py:"
    "EventRecorder.render_jsonl:dropped",
]


def test_fixed_races_do_not_reappear(full_tree):
    findings, _ = full_tree
    keys = {f.key for f in findings}
    back = [k for k in FIXED_KEYS if k in keys]
    assert not back, f"previously fixed races reappeared: {back}"


def test_event_recorder_dump_during_emit_hammer():
    """EventRecorder.render_jsonl vs concurrent emit/clear: the dump's
    `_meta.dropped` snapshot is taken under the ring lock (the fixed
    race); the hammer asserts no exception and a parseable dump."""
    from importlib import import_module
    events = import_module(f"{PKG}.telemetry.events")
    rec = events.EventRecorder(capacity=8, enabled=True)
    stop = threading.Event()
    errors = []

    def churn():
        while not stop.is_set():
            try:
                rec.emit("session_start", kind="hammer")
                rec.clear()
            except Exception as exc:  # pragma: no cover - failure path
                errors.append(exc)
                return

    threads = [threading.Thread(target=churn) for _ in range(4)]
    for t in threads:
        t.start()
    try:
        for _ in range(50):
            dump = rec.render_jsonl()
            meta = json.loads(dump.splitlines()[0])
            assert meta["record"] == "_meta"
            assert meta["dropped"] >= 0
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=5)
    assert not errors, errors


def test_prefix_store_len_during_put_hammer():
    """len(PrefixStore) vs concurrent put(): the fixed race read the
    OrderedDict unlocked while writers resized it."""
    from importlib import import_module
    jnp = import_module("jax.numpy")
    pc = import_module(f"{PKG}.runtime.prefix_cache")
    k = jnp.zeros((2, 4), dtype=jnp.float32)
    store = pc.PrefixStore(max_bytes=100 * int(k.nbytes))
    stop = threading.Event()
    errors = []

    def churn(tag):
        i = 0
        while not stop.is_set():
            try:
                store.put(f"{tag}:{i % 64}", k, k, None)
                i += 1
            except Exception as exc:  # pragma: no cover - failure path
                errors.append(exc)
                return

    threads = [threading.Thread(target=churn, args=(t,)) for t in range(4)]
    for t in threads:
        t.start()
    try:
        for _ in range(200):
            n = len(store)
            assert 0 <= n <= 256
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=5)
    assert not errors, errors
