"""Weight-only quantization for serving + quantization-aware block sizing.

Capability parity with the reference's quantization surface (V9,
``petals/server/block_utils.py``): the vendored server sizes and loads
transformer blocks in NONE / INT8 / NF4 precision (``resolve_block_dtype``
``:12-19``, byte accounting with NF4 = 4.25 bits ``get_block_size:22-53``)
and feeds that into how many blocks a server can hold
(``petals/server/server.py:275-326`` ``_choose_num_blocks``).

TPU-native design:
  * int8 weights with per-output-channel fp32 scales (absmax). HBM holds
    int8; dequantization happens INSIDE the jitted step right before each
    matmul — under ``lax.scan`` over stacked layers that means exactly one
    layer's weights materialize at a time, so a stage's resident weight
    memory is ~the int8 bytes.
  * `QuantizedTensor` is a registered pytree node: quantized params slice,
    stack, scan, and device_put exactly like plain arrays, so the executor,
    pipeline, offload runner, and checkpoint streaming need no changes.
  * Norms, biases, embeddings, the lm_head, and MoE routers stay in full
    precision (the reference quantizes transformer blocks only; routers are
    tiny and top-k placement is precision-sensitive).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from .config import ModelConfig

Params = Dict[str, Any]

# bits per weight for sizing (block_utils.py:46: NF4 = 4.25 incl. absmax
# block overhead). The executed NF4 layout below hits this exactly: 4-bit
# codes (two per uint8, packed on the input axis) + one bf16 absmax scale
# per 64-weight block = 4 + 16/64 = 4.25 bits/param.
QUANT_BITS = {"none": None, "int8": 8, "nf4": 4.25}

# The 16 NormalFloat4 levels (quantiles of N(0,1), endpoints at ±1 —
# the QLoRA code-book used by the reference's bitsandbytes NF4 path).
NF4_LEVELS = (
    -1.0, -0.6961928009986877, -0.5250730514526367, -0.39491748809814453,
    -0.28444138169288635, -0.18477343022823334, -0.09105003625154495, 0.0,
    0.07958029955625534, 0.16093020141124725, 0.24611230194568634,
    0.33791524171829224, 0.4407098591327667, 0.5626170039176941,
    0.7229568362236023, 1.0,
)
NF4_BLOCK = 64   # weights per absmax block (QLoRA default)


def _lut16(codes: jnp.ndarray, table) -> jnp.ndarray:
    """16-entry lookup as a 4-level SELECT TREE (15 elementwise wheres on
    the code bits) instead of a per-element gather. Measured on a v5e:
    `jnp.take` over the 16-entry table lowered to a real gather and made
    nf4 flagship decode 8x SLOWER than bf16 (32.7 ms/step vs 4.1); the
    select tree vectorizes on the VPU and fuses into the consumer. codes:
    int32 [...] in [0, 16). Returns f32 of the same shape."""
    b0 = (codes & 1).astype(bool)
    b1 = (codes & 2).astype(bool)
    b2 = (codes & 4).astype(bool)
    b3 = (codes & 8).astype(bool)
    # f32 levels — LOAD-BEARING for the fused kernel (ops.nf4_kernel runs
    # THIS function inside Mosaic, which cannot relayout int32-derived
    # bool masks into bf16-tiled selects), measured identical speed to
    # bf16 intermediates on the XLA path (op-bound, not width-bound), and
    # keeps both paths' dequant VALUES identical so they differ only by
    # matmul accumulation order.
    lvl = [jnp.float32(t) for t in table]
    l1 = [jnp.where(b0, lvl[2 * i + 1], lvl[2 * i]) for i in range(8)]
    l2 = [jnp.where(b1, l1[2 * i + 1], l1[2 * i]) for i in range(4)]
    l3 = [jnp.where(b2, l2[2 * i + 1], l2[2 * i]) for i in range(2)]
    return jnp.where(b3, l3[1], l3[0])


@jax.tree_util.register_pytree_node_class
class QuantizedTensor:
    """int8 weight + per-output-channel fp32 scale.

    Layout: q has the original weight shape [..., in, out]; s broadcasts as
    [..., 1, out] so ``q * s`` reconstructs. `dtype` records the original
    dtype for reconstruction.
    """

    def __init__(self, q: jnp.ndarray, s: jnp.ndarray, dtype: str = "float32"):
        self.q = q
        self.s = s
        self.dtype = dtype

    def tree_flatten(self):
        return (self.q, self.s), self.dtype

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], children[1], aux)

    @property
    def shape(self):
        return self.q.shape

    def dequant(self) -> jnp.ndarray:
        return (self.q.astype(jnp.float32) * self.s).astype(self.dtype)

    def __repr__(self):
        return f"QuantizedTensor(shape={tuple(self.q.shape)}, dtype={self.dtype})"


@jax.tree_util.register_pytree_node_class
class NF4Tensor:
    """4-bit NormalFloat weight: packed codes + per-block bf16 absmax scales.

    Layout (for an original weight [..., in, out]):
      * ``packed``: uint8 [..., in_pad/2, out] — two 4-bit codes per byte
        along the INPUT axis (high nibble = even row, low nibble = odd row);
      * ``scales``: bfloat16 [..., in_pad/64, out] — absmax per 64-weight
        input-axis block (in_pad = in rounded up to 64).

    4 + 16/64 = 4.25 bits/param resident — the exact sizing constant of
    ``petals/server/block_utils.py:46``. Registered as a pytree so NF4
    params slice/stack/scan/device_put like plain arrays; `dequant()` runs
    INSIDE the jitted step (a 16-entry gather + one multiply, fused by XLA),
    so under ``lax.scan`` only one layer materializes full-precision.
    """

    def __init__(self, packed: jnp.ndarray, scales: jnp.ndarray,
                 in_dim: int, dtype: str = "float32"):
        self.packed = packed
        self.scales = scales
        self.in_dim = in_dim
        self.dtype = dtype

    def tree_flatten(self):
        return (self.packed, self.scales), (self.in_dim, self.dtype)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], children[1], aux[0], aux[1])

    @property
    def shape(self):
        return (*self.packed.shape[:-2], self.in_dim, self.packed.shape[-1])

    def dequant(self) -> jnp.ndarray:
        high = (self.packed >> 4).astype(jnp.int32)
        low = (self.packed & 0xF).astype(jnp.int32)
        codes = jnp.stack([high, low], axis=-2)        # [..., P, 2, out]
        lead = self.packed.shape[:-2]
        out = self.packed.shape[-1]
        in_pad = self.packed.shape[-2] * 2
        vals = _lut16(codes.reshape(*lead, in_pad, out), NF4_LEVELS)
        nb = in_pad // NF4_BLOCK
        vals = vals.reshape(*lead, nb, NF4_BLOCK, out)
        vals = vals * self.scales[..., :, None, :].astype(jnp.float32)
        vals = vals.reshape(*lead, in_pad, out)
        return vals[..., : self.in_dim, :].astype(self.dtype)

    def __repr__(self):
        return f"NF4Tensor(shape={tuple(self.shape)}, dtype={self.dtype})"


def _quantize_leaf_nf4(w) -> NF4Tensor:
    """Host-side NF4 quantization: block the input axis by 64, scale each
    block to [-1, 1] by its (bf16-rounded) absmax, snap to the nearest of
    the 16 NF4 levels via boundary search (O(1) temp memory), pack two codes
    per byte."""
    import numpy as np

    w_np = np.asarray(jax.device_get(w), np.float32)
    *lead, in_dim, out = w_np.shape
    in_pad = -(-in_dim // NF4_BLOCK) * NF4_BLOCK
    if in_pad != in_dim:
        pad = [(0, 0)] * len(lead) + [(0, in_pad - in_dim), (0, 0)]
        w_np = np.pad(w_np, pad)
    nb = in_pad // NF4_BLOCK
    blocks = w_np.reshape(*lead, nb, NF4_BLOCK, out)
    absmax = np.max(np.abs(blocks), axis=-2, keepdims=True)
    # Quantize AGAINST the bf16-rounded scale the dequant will actually use,
    # so the round trip has no scale mismatch on top of the 4-bit error.
    scales = jnp.asarray(absmax[..., 0, :], jnp.bfloat16)
    scale32 = np.asarray(scales, np.float32)[..., None, :]
    norm = np.divide(blocks, scale32, out=np.zeros_like(blocks),
                     where=scale32 > 0)
    levels = np.asarray(NF4_LEVELS, np.float32)
    bounds = (levels[1:] + levels[:-1]) / 2.0
    codes = np.searchsorted(bounds, norm).astype(np.uint8)
    codes = codes.reshape(*lead, in_pad, out)
    packed = (codes[..., 0::2, :] << 4) | codes[..., 1::2, :]
    return NF4Tensor(jnp.asarray(packed), scales, in_dim,
                     str(jnp.asarray(w).dtype))


def _quantize_leaf(w: jnp.ndarray) -> QuantizedTensor:
    """Per-output-channel absmax int8: channel axis = last, reduce over the
    input axis (-2). Works for [in, out], stacked [L, in, out], and expert
    [E, in, out] weights alike."""
    w32 = jnp.asarray(w, jnp.float32)
    absmax = jnp.max(jnp.abs(w32), axis=-2, keepdims=True)
    s = jnp.where(absmax > 0, absmax / 127.0, 1.0)
    q = jnp.clip(jnp.round(w32 / s), -127, 127).astype(jnp.int8)
    return QuantizedTensor(q, s.astype(jnp.float32), str(jnp.asarray(w).dtype))


# The matmul weight names of models/transformer.py's layer schema. Norms,
# biases, and the MoE "router" are deliberately absent (full precision).
_MATMUL_KEYS = frozenset(
    {"wq", "wk", "wv", "wqkv", "wo", "wg", "wu", "wgu", "wd", "wi"})


def quantize_layers(layers: Params, quant: str = "int8") -> Params:
    """Quantize a `layers` subtree (stacked or single): matmul weights by
    NAME (norm weights and biases share the ndim of stacked matmul weights,
    so shape alone cannot distinguish them)."""
    if quant in (None, "none"):
        return layers
    if quant not in ("int8", "nf4"):
        raise NotImplementedError(
            f"quant={quant!r}: int8 and nf4 execution are implemented")
    leaf = _quantize_leaf if quant == "int8" else _quantize_leaf_nf4

    def walk(tree, key=None):
        if isinstance(tree, dict):
            return {k: walk(v, k) for k, v in tree.items()}
        if key in _MATMUL_KEYS and getattr(tree, "ndim", 0) >= 2:
            return leaf(tree)
        return tree

    # dict-walk instead of tree_map: the selection is name-dependent.
    return walk(layers)


def quantize_params(params: Params, quant: str = "int8") -> Params:
    """Quantize a full/stage param tree: blocks only (embed/head/norm full
    precision, matching the reference's block-scoped quantization)."""
    out = dict(params)
    if "layers" in params:
        out["layers"] = quantize_layers(params["layers"], quant)
    return out


_QUANT_TYPES = (QuantizedTensor, NF4Tensor)


def nf4_kernel_enabled() -> bool:
    """NF4_KERNEL=1 routes per-layer NF4 matmuls through the fused Pallas
    dequant-matmul kernel (ops.nf4_kernel) instead of materializing the
    weight — the measured lever for nf4 decode throughput. Default OFF.

    Trace-time flag (utils/flags.py catalog): resolved while the engine
    traces, so flips after warmup require a retrace."""
    from ..utils.flags import bool_flag

    return bool_flag("NF4_KERNEL")


def int8_fold_enabled() -> bool:
    """INT8_FOLD=1 (default ON) keeps per-layer 2-D int8 leaves packed so
    the matmul sites stream the int8 bytes and apply the per-channel
    scale in the matmul EPILOGUE (ops.int8_kernel: ``(x @ q) * s``)
    instead of materializing a full bf16 weight per layer first — the
    difference between 0.65 and roofline `frac_of_sustained` on decode.
    INT8_FOLD=0 restores the dequant-materialize path (bit-for-bit the
    round-5 behavior) as the kill switch.

    Trace-time flag (utils/flags.py catalog): resolved while the engine
    traces, so flips after warmup require a retrace."""
    from ..utils.flags import bool_flag

    return bool_flag("INT8_FOLD")


def dequant_tree(tree: Params, keep_experts: bool = False) -> Params:
    """Materialize full-precision weights for any quantized leaves (int8 or
    NF4). Identity (and free) for unquantized trees; under jit+scan this
    runs per layer, so only one layer's weights exist dequantized at a
    time.

    With `nf4_kernel_enabled()`, per-layer (2-D) NF4 leaves stay packed —
    the matmul sites (`models.transformer._dot`) feed them to the fused
    kernel. With `int8_fold_enabled()` (default), per-layer (2-D) int8
    leaves stay packed the same way and run the scale-folded epilogue
    (ops.int8_kernel).

    `keep_experts=True` (the PER-LAYER MoE call sites: layer_forward and
    the engine scan bodies, where any 3-D quantized leaf IS an [E, in,
    out] expert stack) keeps those stacks packed too whenever the sparse
    dispatch is on (`models.moe.moe_sparse_enabled`): the grouped matmuls
    consume them per expert (int8 scale-folded einsum / NF4 one-expert-at-
    a-time lax.map — models.moe._expert_dot), so a stage's resident expert
    bytes stay at the quantized size. Default False because callers also
    dequant whole STACKED trees, where a 3-D leaf is an [L, in, out] dense
    weight, not an expert stack."""
    keep_nf4 = nf4_kernel_enabled()
    keep_int8 = int8_fold_enabled()
    if keep_experts:
        from .moe import moe_sparse_enabled

        keep_experts = moe_sparse_enabled()

    def f(x):
        if not isinstance(x, _QUANT_TYPES):
            return x
        nd = x.q.ndim if isinstance(x, QuantizedTensor) else x.packed.ndim
        if keep_nf4 and isinstance(x, NF4Tensor) and nd == 2:
            return x
        if keep_int8 and isinstance(x, QuantizedTensor) and nd == 2:
            return x
        if keep_experts and nd == 3:
            return x
        return x.dequant()

    return jax.tree.map(
        f, tree, is_leaf=lambda x: isinstance(x, _QUANT_TYPES))


def is_quantized(tree: Params) -> bool:
    return any(isinstance(x, _QUANT_TYPES) for x in jax.tree.leaves(
        tree, is_leaf=lambda x: isinstance(x, _QUANT_TYPES)))


# ---------------------------------------------------------------------------
# Quantization-aware sizing (block_utils.get_block_size:22-53) and server
# auto-capacity (server.py _choose_num_blocks:275-326)
# ---------------------------------------------------------------------------

def params_per_block(cfg: ModelConfig) -> int:
    """Parameter count of ONE transformer block (no embed/head)."""
    d, i = cfg.hidden_size, cfg.intermediate_size
    h, hkv, dh = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
    attn = d * h * dh + 2 * d * hkv * dh + h * dh * d
    if cfg.use_bias or cfg.attn_qkv_bias:
        attn += h * dh + 2 * hkv * dh   # q/k/v biases (gpt2 AND qwen2)
    if cfg.use_bias:
        attn += d                        # o bias (gpt2 only)
    if cfg.is_moe:
        mlp = cfg.num_experts * 3 * d * i + d * cfg.num_experts
    elif cfg.mlp == "swiglu":
        mlp = 3 * d * i
    else:
        mlp = 2 * d * i + (i + d if cfg.use_bias else 0)
    norms = (4 if cfg.norm == "layernorm" else 2) * d
    return attn + mlp + norms


def block_bytes(cfg: ModelConfig, dtype_bytes: int = 2,
                quant: str = "none") -> int:
    """Bytes one block occupies resident (quant-aware, V9 parity)."""
    if quant not in QUANT_BITS:
        raise ValueError(f"unknown quant mode {quant!r} "
                         f"(expected one of {sorted(QUANT_BITS)})")
    n = params_per_block(cfg)
    bits = QUANT_BITS[quant]
    if bits is None:  # "none": full precision
        return n * dtype_bytes
    return int(n * bits / 8)


def choose_num_blocks(
    cfg: ModelConfig,
    memory_budget_bytes: int,
    *,
    dtype_bytes: int = 2,
    quant: str = "none",
    attn_cache_bytes: int = 0,
    reserve_fraction: float = 0.05,
) -> int:
    """How many blocks fit a device budget after the KV-cache arena and a
    safety reserve — the server auto-capacity rule
    (``petals/server/server.py:275-326``, which budgets weights + attention
    cache + autograd headroom out of free GPU memory)."""
    usable = int(memory_budget_bytes * (1.0 - reserve_fraction))
    usable -= attn_cache_bytes
    per = block_bytes(cfg, dtype_bytes, quant)
    return max(1, min(cfg.num_layers, usable // max(per, 1)))
