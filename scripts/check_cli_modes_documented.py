#!/usr/bin/env python
"""Fail (exit 1) when the CLI's ``--mode`` surface and the docs drift.

Both directions:

  * every choice in main.py's ``--mode`` (and ``--chaos_scenario``) argparse
    declaration must be shown in use — as ``--mode <choice>`` /
    ``--chaos_scenario <choice>`` — somewhere in README.md or docs/*.md,
    so no entry point ships undocumented;
  * every ``--mode <word>`` / ``--chaos_scenario <word>`` usage in those
    files must name a real choice, so renamed or removed modes cannot
    linger in the docs.

The parser choices are read from main.py's SOURCE TEXT (regex, no import):
main.py pulls in jax at import time and this check must stay cheap enough
to run as a tier-1 test (tests/test_cli_modes_documented.py).
"""

import pathlib
import re
import sys

REPO = pathlib.Path(__file__).resolve().parents[1]
MAIN = (REPO / "global_capstone_design_distributed_inference_of_llms"
        "_over_the_internet_tpu" / "main.py")
DOCS = [REPO / "README.md"] + sorted((REPO / "docs").glob("*.md"))


def parser_choices(src: str, flag: str) -> list:
    m = re.search(
        r'add_argument\(\s*"%s",\s*choices=\[(.*?)\]' % re.escape(flag),
        src, re.S)
    if not m:
        print(f"could not find {flag} choices in {MAIN.relative_to(REPO)}")
        sys.exit(2)
    return re.findall(r'"([a-z0-9_-]+)"', m.group(1))


def main() -> int:
    src = MAIN.read_text(encoding="utf-8")
    text = "\n".join(p.read_text(encoding="utf-8") for p in DOCS if p.exists())
    failed = False
    for flag, choices in (("--mode", parser_choices(src, "--mode")),
                          ("--chaos_scenario",
                           parser_choices(src, "--chaos_scenario"))):
        used = set(re.findall(r"%s[ =]+([a-z0-9_-]+)" % re.escape(flag), text))
        undocumented = [c for c in choices if c not in used]
        unknown = sorted(used - set(choices))
        if undocumented:
            failed = True
            print(f"{flag} choices never shown in README.md or docs/*.md:")
            for c in undocumented:
                print(f"  {c}")
        if unknown:
            failed = True
            print(f"{flag} usages in the docs that are not parser choices:")
            for c in unknown:
                print(f"  {c}")
        if not undocumented and not unknown:
            print(f"ok: all {len(choices)} {flag} choices documented")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
