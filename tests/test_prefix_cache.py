"""Prompt-prefix KV reuse (runtime.prefix_cache) — no reference counterpart.

The store is content-addressed with grain-chained rolling digests, so two
prompts sharing a system preamble reuse its grains automatically. A hit is
exact in content (same bytes through the same blocks); outputs are compared
at the chunk-boundary fp tolerance the suite uses for chunked prefill (the
warm suffix runs under a different seq-bucket shape than the cold one-shot
prefill, so fusion differences move the last ulp, not the math).
"""

import jax
import jax.numpy as jnp
import numpy as np

from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.models import (
    init_params,
)
from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.models.partition import (
    StagePlan,
    parse_splits,
    slice_stage_params,
)
from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.ops.sampling import (
    SamplingParams,
)
from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.runtime.executor import (
    StageExecutor,
)
from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.runtime.messages import (
    StageRequest,
)
from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.runtime.net import (
    _header_to_request,
    _request_header,
)
from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.runtime.prefix_cache import (
    PrefixStore,
    chain_digests,
)

from test_runtime_pipeline import tiny_cfg

GRAIN = 8


# ---------------------------------------------------------------------------
# Store unit tests
# ---------------------------------------------------------------------------

def _seg(val, nbytes=64):
    a = jnp.full((1, 1, GRAIN, 1, 2), float(val), jnp.float32)
    return a, a, jnp.full((1, GRAIN, 2), float(val), jnp.float32)


def test_store_chain_lookup_stops_at_first_missing():
    st = PrefixStore(1 << 20, grain=GRAIN)
    keys = chain_digests([b"a", b"b", b"c"], coords=("t",))
    k, v, out = _seg(1)
    st.put(keys[0], k, v, out)
    st.put(keys[2], k, v, out)  # keys[1] missing -> chain ends after 1
    got = st.lookup_chain(keys, need_out=True)
    assert len(got) == 1
    assert st.hits == 1 and st.grains_reused == 1


def test_store_need_out_breaks_on_kv_only_entry():
    st = PrefixStore(1 << 20, grain=GRAIN)
    keys = chain_digests([b"a", b"b"], coords=("t",))
    k, v, out = _seg(1)
    st.put(keys[0], k, v, None)
    st.put(keys[1], k, v, out)
    assert st.lookup_chain(keys, need_out=True) == []
    assert len(st.lookup_chain(keys, need_out=False)) == 2


def test_store_lru_eviction_bounded():
    k, v, out = _seg(1)
    per = int(k.nbytes + v.nbytes + out.nbytes)
    st = PrefixStore(per * 2, grain=GRAIN)
    keys = chain_digests([b"a", b"b", b"c"], coords=("t",))
    for key in keys:
        assert st.put(key, k, v, out)
    assert len(st) == 2 and st.evictions == 1
    assert st.used_bytes <= st.max_bytes
    # oldest evicted -> chain broken at first key
    assert st.lookup_chain(keys, need_out=True) == []
    # oversized entry refused
    tiny = PrefixStore(per - 1, grain=GRAIN)
    assert not tiny.put(keys[0], k, v, out)


def test_rolling_digest_is_position_dependent():
    d1 = chain_digests([b"aa", b"bb"], coords=("c",))
    d2 = chain_digests([b"bb", b"bb"], coords=("c",))
    # same 2nd-grain bytes, different prefix -> different 2nd digest
    assert d1[1] != d2[1]
    assert chain_digests([b"aa"], coords=("c",)) != chain_digests(
        [b"aa"], coords=("other",))


def test_wire_header_roundtrip_prefix_len():
    req = StageRequest(session_id="s", hidden=jnp.zeros((1, 4, 8)),
                       seq_len=4, cur_len=0, is_prefill=True, max_length=32,
                       prefix_len=4)
    hdr = _request_header(req, {"dtype": "f32", "shape": [1, 4, 8]})
    body = np.zeros((1, 4, 8), np.float32).tobytes()
    back = _header_to_request(hdr, body)
    assert back.prefix_len == 4
    # absent for the common case (legacy header compatibility)
    req0 = StageRequest(session_id="s", hidden=jnp.zeros((1, 4, 8)),
                        seq_len=4, cur_len=0, is_prefill=True, max_length=32)
    assert "prefix_len" not in _request_header(
        req0, {"dtype": "f32", "shape": [1, 4, 8]})


# ---------------------------------------------------------------------------
# Executor integration
# ---------------------------------------------------------------------------

def _seg_executor(cfg, params, cache_mb=64):
    plan = StagePlan.from_splits(cfg.num_layers, parse_splits("2,6"))
    spec = plan.stages[1]  # layers [2, 6)
    ex = StageExecutor(cfg, spec, slice_stage_params(cfg, params, spec),
                       peer_id="seg",
                       prefix_cache_bytes=cache_mb << 20)
    ex.prefix_store.grain = GRAIN  # fine-grained for small test prompts
    return ex


def _prefill(ex, sid, hid, prefix_len):
    return ex.forward(StageRequest(
        session_id=sid, hidden=jnp.asarray(hid), seq_len=hid.shape[1],
        cur_len=0, is_prefill=True, max_length=64, prefix_len=prefix_len))


def _decode(ex, sid, hid, cur_len):
    return ex.forward(StageRequest(
        session_id=sid, hidden=jnp.asarray(hid), seq_len=1, cur_len=cur_len,
        is_prefill=False, max_length=64))


def test_segment_hit_is_bitwise_exact_through_decode():
    cfg = tiny_cfg()
    params = init_params(jax.random.PRNGKey(0), cfg)
    rng = np.random.default_rng(1)
    hid = rng.standard_normal((1, 40, cfg.hidden_size)).astype(np.float32)

    ex = _seg_executor(cfg, params)
    cold = _prefill(ex, "cold", hid, prefix_len=40)
    st = ex.prefix_store.stats()
    # min(40, 39) // 8 = 4 grains registered on the miss
    assert st == {**st, "entries": 4, "misses": 1, "hits": 0}

    warm = _prefill(ex, "warm", hid, prefix_len=40)
    st = ex.prefix_store.stats()
    assert st["hits"] == 1 and st["grains_reused"] == 4
    np.testing.assert_allclose(np.asarray(cold.hidden),
                               np.asarray(warm.hidden), atol=1e-5, rtol=1e-5)
    assert warm.cache_len == 40

    # decode must continue bitwise-identically from the copied KV
    step = rng.standard_normal((1, 1, cfg.hidden_size)).astype(np.float32)
    for i in range(3):
        rc = _decode(ex, "cold", step, 40 + i)
        rw = _decode(ex, "warm", step, 40 + i)
        np.testing.assert_allclose(np.asarray(rc.hidden),
                                   np.asarray(rw.hidden), atol=1e-5, rtol=1e-5)


def test_shared_prefix_divergent_suffix_matches_uncached():
    cfg = tiny_cfg()
    params = init_params(jax.random.PRNGKey(0), cfg)
    rng = np.random.default_rng(2)
    shared = rng.standard_normal((1, 32, cfg.hidden_size)).astype(np.float32)
    tail_a = rng.standard_normal((1, 9, cfg.hidden_size)).astype(np.float32)
    tail_b = rng.standard_normal((1, 9, cfg.hidden_size)).astype(np.float32)
    hid_a = np.concatenate([shared, tail_a], axis=1)
    hid_b = np.concatenate([shared, tail_b], axis=1)

    cached = _seg_executor(cfg, params)
    _prefill(cached, "a", hid_a, prefix_len=41)
    warm_b = _prefill(cached, "b", hid_b, prefix_len=41)
    st = cached.prefix_store.stats()
    # prompts diverge after 32 rows -> exactly 4 shared grains reused
    assert st["hits"] == 1 and st["grains_reused"] == 4

    oracle = StageExecutor(
        cfg, cached.spec, slice_stage_params(cfg, params, cached.spec),
        peer_id="oracle")
    cold_b = _prefill(oracle, "b", hid_b, prefix_len=0)
    np.testing.assert_allclose(np.asarray(cold_b.hidden),
                               np.asarray(warm_b.hidden), atol=1e-5, rtol=1e-5)


def test_final_stage_hit_keeps_sampled_token():
    cfg = tiny_cfg()
    params = init_params(jax.random.PRNGKey(0), cfg)
    plan = StagePlan.from_splits(cfg.num_layers, parse_splits("2,6"))
    spec = plan.stages[-1]  # layers [6, 8) + head
    ex = StageExecutor(cfg, spec, slice_stage_params(cfg, params, spec),
                       peer_id="last", prefix_cache_bytes=64 << 20)
    ex.prefix_store.grain = GRAIN
    rng = np.random.default_rng(3)
    hid = rng.standard_normal((1, 33, cfg.hidden_size)).astype(np.float32)

    def prefill(sid):
        return ex.forward(StageRequest(
            session_id=sid, hidden=jnp.asarray(hid), seq_len=33, cur_len=0,
            is_prefill=True, max_length=64, prefix_len=33,
            sampling=SamplingParams(temperature=0.0)))

    cold = prefill("cold")
    warm = prefill("warm")
    # min(33, 32) // 8 = 4 grains; final stage stores KV-only entries
    assert ex.prefix_store.stats()["grains_reused"] == 4
    assert cold.token_id == warm.token_id
    assert warm.cache_len == 33


def test_prefix_len_clamp_never_skips_last_row():
    """prefix_len == seq_len must leave >= 1 computed row (the final stage
    samples from it): with T = 32 and grain 8, only 3 grains are usable."""
    cfg = tiny_cfg()
    params = init_params(jax.random.PRNGKey(0), cfg)
    ex = _seg_executor(cfg, params)
    hid = np.random.default_rng(4).standard_normal(
        (1, 32, cfg.hidden_size)).astype(np.float32)
    a = _prefill(ex, "a", hid, prefix_len=32)
    warm = _prefill(ex, "b", hid, prefix_len=32)
    assert ex.prefix_store.stats()["grains_reused"] == 3
    np.testing.assert_allclose(np.asarray(a.hidden),
                               np.asarray(warm.hidden), atol=1e-5, rtol=1e-5)


def test_exotic_requests_bypass_store():
    cfg = tiny_cfg()
    params = init_params(jax.random.PRNGKey(0), cfg)
    ex = _seg_executor(cfg, params)
    hid = np.random.default_rng(5).standard_normal(
        (1, 24, cfg.hidden_size)).astype(np.float32)
    prompts = np.zeros((4, 2, cfg.hidden_size), np.float32)
    ex.forward(StageRequest(
        session_id="dp", hidden=jnp.asarray(hid), seq_len=24, cur_len=0,
        is_prefill=True, max_length=64, prefix_len=24,
        prompts=jnp.asarray(prompts)))
    st = ex.prefix_store.stats()
    assert st["entries"] == 0 and st["hits"] == 0 and st["misses"] == 0


def test_end_to_end_client_reuse_token_parity():
    """Two PipelineClient generations with the same prompt: the second hits
    every server's store and produces identical tokens; a shared-prefix
    third prompt reuses only the shared grains and still matches a
    cache-free cluster."""
    from test_runtime_pipeline import build_cluster

    cfg = tiny_cfg()
    client, transport, registry, params, plan = build_cluster(cfg)
    stores = []
    for pid in transport.peers():
        ex = transport.executor(pid)
        ex.prefix_store = PrefixStore(64 << 20, grain=GRAIN)
        stores.append(ex.prefix_store)
    prompt = list(range(7, 47))  # 40 tokens -> 4 reusable grains of 8
    sampling = SamplingParams(temperature=0.0)

    r1 = client.generate(prompt, max_new_tokens=6, sampling=sampling)
    assert all(s.stats()["misses"] == 1 for s in stores)
    r2 = client.generate(prompt, max_new_tokens=6, sampling=sampling)
    assert r1.tokens == r2.tokens
    assert all(s.stats()["hits"] == 1 for s in stores)
    assert all(s.stats()["grains_reused"] == 4 for s in stores)

    # divergent tail after 32 shared tokens
    prompt3 = prompt[:32] + [101, 102, 103, 104, 105, 106, 107, 108]
    r3 = client.generate(prompt3, max_new_tokens=6, sampling=sampling)
    fresh_client, *_ = build_cluster(cfg)
    r3_oracle = fresh_client.generate(prompt3, max_new_tokens=6,
                                      sampling=sampling)
    assert r3.tokens == r3_oracle.tokens


# ---------------------------------------------------------------------------
# Batched (slot) engine
# ---------------------------------------------------------------------------

def _batched_engine(cfg, params, role_last=False, cache_mb=64):
    from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.models.partition import (
        StagePlan,
    )
    from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.runtime.batching import (
        BatchedStageExecutor,
    )

    plan = StagePlan.from_splits(cfg.num_layers, parse_splits("2,6"))
    spec = plan.stages[-1] if role_last else plan.stages[1]
    ex = BatchedStageExecutor(
        cfg, spec, slice_stage_params(cfg, params, spec),
        slots=4, max_len=64, prefix_cache_bytes=cache_mb << 20)
    ex.prefix_store.grain = GRAIN
    return ex


def test_batched_engine_hit_parity_through_decode():
    cfg = tiny_cfg()
    params = init_params(jax.random.PRNGKey(0), cfg)
    ex = _batched_engine(cfg, params)
    rng = np.random.default_rng(8)
    hid = rng.standard_normal((1, 40, cfg.hidden_size)).astype(np.float32)

    cold = ex.prefill("cold", hid, prefix_len=40)
    st = ex.prefix_store.stats()
    assert st["entries"] == 4 and st["misses"] == 1

    warm = ex.prefill("warm", hid, prefix_len=40)
    st = ex.prefix_store.stats()
    assert st["hits"] == 1 and st["grains_reused"] == 4
    assert warm.shape == cold.shape  # intermediate: full rows returned
    np.testing.assert_allclose(np.asarray(cold), np.asarray(warm),
                               atol=1e-5, rtol=1e-5)
    assert int(ex.lengths[ex.slot("warm")]) == 40

    # batched decode continues both sessions identically from their KV
    step = rng.standard_normal((1, 1, cfg.hidden_size)).astype(np.float32)
    for _ in range(3):
        outs = ex.decode_batch({"cold": jnp.asarray(step),
                                "warm": jnp.asarray(step)})
        np.testing.assert_allclose(np.asarray(outs["cold"]),
                                   np.asarray(outs["warm"]),
                                   atol=1e-5, rtol=1e-5)


def test_batched_engine_shared_prefix_matches_cacheless():
    cfg = tiny_cfg()
    params = init_params(jax.random.PRNGKey(0), cfg)
    rng = np.random.default_rng(9)
    shared = rng.standard_normal((1, 32, cfg.hidden_size)).astype(np.float32)
    tail_a = rng.standard_normal((1, 8, cfg.hidden_size)).astype(np.float32)
    tail_b = rng.standard_normal((1, 8, cfg.hidden_size)).astype(np.float32)

    cached = _batched_engine(cfg, params)
    cached.prefill("a", np.concatenate([shared, tail_a], 1), prefix_len=40)
    warm_b = cached.prefill("b", np.concatenate([shared, tail_b], 1),
                            prefix_len=40)
    assert cached.prefix_store.stats()["grains_reused"] == 4

    oracle = _batched_engine(cfg, params, cache_mb=64)
    cold_b = oracle.prefill("b", np.concatenate([shared, tail_b], 1),
                            prefix_len=0)
    np.testing.assert_allclose(np.asarray(cold_b), np.asarray(warm_b),
                               atol=1e-5, rtol=1e-5)


def test_batched_engine_final_stage_suffix_only():
    """is_last stores KV-only entries and a hit returns just the computed
    suffix (the adapter samples from its last row)."""
    cfg = tiny_cfg()
    params = init_params(jax.random.PRNGKey(0), cfg)
    ex = _batched_engine(cfg, params, role_last=True)
    rng = np.random.default_rng(10)
    hid = rng.standard_normal((1, 33, cfg.hidden_size)).astype(np.float32)

    cold = ex.prefill("cold", hid, prefix_len=33)
    warm = ex.prefill("warm", hid, prefix_len=33)
    assert ex.prefix_store.stats()["grains_reused"] == 4
    assert warm.shape[1] == 33 - 32  # suffix rows only
    np.testing.assert_allclose(np.asarray(cold[:, -1]),
                               np.asarray(warm[:, -1]),
                               atol=1e-5, rtol=1e-5)
    assert int(ex.lengths[ex.slot("warm")]) == 33


def test_batched_adapter_passes_prefix_len():
    from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.runtime.batching import (
        BatchingStageAdapter,
    )

    cfg = tiny_cfg()
    params = init_params(jax.random.PRNGKey(0), cfg)
    ex = _batched_engine(cfg, params)
    ad = BatchingStageAdapter(ex, peer_id="b")
    hid = np.random.default_rng(12).standard_normal(
        (1, 24, cfg.hidden_size)).astype(np.float32)
    r1 = ad.forward(StageRequest(
        session_id="s1", hidden=jnp.asarray(hid), seq_len=24, cur_len=0,
        is_prefill=True, max_length=64, prefix_len=24))
    r2 = ad.forward(StageRequest(
        session_id="s2", hidden=jnp.asarray(hid), seq_len=24, cur_len=0,
        is_prefill=True, max_length=64, prefix_len=24))
    assert ex.prefix_store.stats()["hits"] == 1
    np.testing.assert_allclose(np.asarray(r1.hidden), np.asarray(r2.hidden),
                               atol=1e-5, rtol=1e-5)


def test_batched_engine_partial_hit_registers_tail():
    """A prompt sharing only its head with a stored chain reuses the shared
    grains AND registers its own tail, so a repeat of the new prompt is a
    full-chain hit."""
    cfg = tiny_cfg()
    params = init_params(jax.random.PRNGKey(0), cfg)
    ex = _batched_engine(cfg, params)
    rng = np.random.default_rng(13)
    shared = rng.standard_normal((1, 16, cfg.hidden_size)).astype(np.float32)
    tail_b = rng.standard_normal((1, 25, cfg.hidden_size)).astype(np.float32)
    hid_a = np.concatenate(
        [shared, rng.standard_normal((1, 25, cfg.hidden_size))
         .astype(np.float32)], 1)
    hid_b = np.concatenate([shared, tail_b], 1)

    ex.prefill("a", hid_a, prefix_len=41)          # registers 5 grains
    ex.prefill("b1", hid_b, prefix_len=41)         # 2 shared, registers 3
    st = ex.prefix_store.stats()
    assert st["grains_reused"] == 2 and st["entries"] == 8
    ex.prefill("b2", hid_b, prefix_len=41)         # full-chain hit now
    assert ex.prefix_store.stats()["grains_reused"] == 2 + 5


# ---------------------------------------------------------------------------
# Prefix-affinity routing (rendezvous hash over replicas)
# ---------------------------------------------------------------------------

def test_affinity_pick_is_deterministic_and_spreads():
    import random as _random

    from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.scheduling.registry import (
        PlacementRegistry,
    )
    from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.runtime.client import (
        make_server_record,
    )
    from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.models.partition import (
        StagePlan,
        parse_splits,
    )

    cfg = tiny_cfg()
    plan = StagePlan.from_splits(cfg.num_layers, parse_splits("3,6"))
    spec = plan.stages[1]
    picks = set()
    for seed in range(5):  # rng must NOT influence affinity picks
        reg = PlacementRegistry(rng=_random.Random(seed))
        for r in range(3):
            reg.register(make_server_record(f"peer-r{r}", spec))
        picks.add(reg.discover_stage(spec.index, affinity="promptheadA"))
    assert len(picks) == 1
    reg = PlacementRegistry(rng=_random.Random(0))
    for r in range(3):
        reg.register(make_server_record(f"peer-r{r}", spec))
    spread = {reg.discover_stage(spec.index, affinity=f"head{i}")
              for i in range(32)}
    assert len(spread) > 1  # distinct prompt heads spread over replicas


def test_cross_client_affinity_warms_the_same_replica():
    """Two independent clients with the same prompt must pick the SAME
    replica chain (rendezvous affinity), so client B's prefill hits the
    store client A warmed."""
    from test_runtime_pipeline import build_cluster
    from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.runtime.client import (
        PipelineClient,
    )

    cfg = tiny_cfg()
    client_a, transport, registry, params, plan = build_cluster(
        cfg, replicas=3, seed=0)
    stores = {}
    for pid in transport.peers():
        ex = transport.executor(pid)
        ex.prefix_store = PrefixStore(64 << 20, grain=GRAIN)
        stores[pid] = ex.prefix_store
    client_b = PipelineClient(cfg, plan, client_a.stage0, transport,
                              registry, settle_seconds=0.0, seed=99)
    prompt = list(range(11, 51))
    sampling = SamplingParams(temperature=0.0)
    ra = client_a.generate(prompt, max_new_tokens=4, sampling=sampling)
    rb = client_b.generate(prompt, max_new_tokens=4, sampling=sampling)
    assert ra.tokens == rb.tokens
    # exactly the replicas client A warmed got client B's hits
    hit_peers = {p for p, s in stores.items() if s.stats()["hits"] > 0}
    miss_peers = {p for p, s in stores.items() if s.stats()["misses"] > 0}
    assert hit_peers == miss_peers and len(hit_peers) == 2  # 2 remote hops
