"""Cache-aware multi-head attention (MHA/GQA/MQA) with static shapes.

TPU-first counterpart of the reference's manual sdpa + legacy-tuple KV concat
(``petals/llama/block.py:123-141``): instead of concatenating growing
per-session tuples, keys/values live in a preallocated fixed-size cache and new
tokens are written with ``dynamic_update_slice`` — shapes never change, so the
prefill and decode step functions each compile exactly once.

Softmax accumulates in float32 (matches reference ``block.py:138``: fp32
softmax), outputs return to the activation dtype (bfloat16 on TPU).

Implementation is pure XLA by DECISION, not omission: a hand-written Pallas
flash kernel (223 lines, VMEM-streamed KV) lived here through round 1 and
lost to XLA's fused attention at EVERY shape class tried under the honest
hard-sync methodology — e.g. 3.5 ms/step (XLA) vs 6.7 ms/step (kernel) at
S=8192 decode on a 0.5B model, v5e — because the kernel's unfused
custom-call boundary cost more than its streaming saved. It was deleted in
round 2 (see docs/PERFORMANCE.md "Flash kernel post-mortem"; history:
``git log -- **/flash_attention.py``). Revisit only with a measured win on
real hardware.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def update_kv_cache(
    k_cache: jnp.ndarray,
    v_cache: jnp.ndarray,
    k_new: jnp.ndarray,
    v_new: jnp.ndarray,
    cache_len: jnp.ndarray,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Write T new tokens at positions [cache_len, cache_len+T).

    k_cache/v_cache: [B, S, Hkv, Dh]; k_new/v_new: [B, T, Hkv, Dh];
    cache_len: scalar int32.

    CONTRACT: cache_len + T <= S. Under jit, ``dynamic_update_slice`` CLAMPS an
    out-of-range start index instead of raising, which would silently overwrite
    the newest cache rows. Callers must enforce max-length admission control
    BEFORE dispatching the step — the runtime does this at session level
    (`runtime.kv_cache`), mirroring the reference's ``inference_max_length``
    guard (``petals/server/block_functions.py:193-197``).
    """
    start = (0, cache_len.astype(jnp.int32), 0, 0)
    k_cache = jax.lax.dynamic_update_slice(k_cache, k_new.astype(k_cache.dtype), start)
    v_cache = jax.lax.dynamic_update_slice(v_cache, v_new.astype(v_cache.dtype), start)
    return k_cache, v_cache


def paged_decode_attention(
    q: jnp.ndarray,
    k_cache: jnp.ndarray,
    v_cache: jnp.ndarray,
    cache_len: jnp.ndarray,
    page: int,
) -> jnp.ndarray:
    """Single-token decode attention whose HBM reads track OCCUPANCY.

    `cached_attention` streams the whole static cache bucket every step —
    at the flagship bench shape that is ~1.8x the occupied rows (bucket
    512 vs mean occupancy 288), measured as ~8pp of roofline lost to
    padded-bucket reads (docs/PERFORMANCE.md, VERDICT r4 item 5). This
    variant runs the classic online-softmax (flash) accumulation over
    PAGES of the cache with a DYNAMIC trip count ``ceil((cache_len+1)/
    page)`` — lax.fori_loop with a traced bound — so a step reads only
    pages holding real rows. Same math: fp32 running max/denominator,
    masked tail page; bitwise it differs from one-pass softmax only in
    accumulation order.

    q: [B, 1, H, Dh]; k_cache/v_cache: [B, S, Hkv, Dh] with the new key
    already written at position cache_len; S % page must be 0 (the jit
    caller pads the bucket). Returns [B, 1, H, Dh].
    """
    b, t, h, dh = q.shape
    assert t == 1, "paged path is the T == 1 decode step"
    s = k_cache.shape[1]
    hkv = k_cache.shape[2]
    groups = h // hkv
    if s % page:
        raise ValueError(f"cache bucket {s} not divisible by page {page}")
    qg = (q * (dh ** -0.5)).reshape(b, hkv, groups, dh)
    n_pages = (cache_len + page) // page   # keys 0..cache_len inclusive

    def body(j, carry):
        m, l, acc = carry
        kp = jax.lax.dynamic_slice_in_dim(k_cache, j * page, page, axis=1)
        vp = jax.lax.dynamic_slice_in_dim(v_cache, j * page, page, axis=1)
        sc = jnp.einsum("bhgd,bphd->bhgp", qg, kp,
                        preferred_element_type=jnp.float32)
        pos = j * page + jnp.arange(page, dtype=jnp.int32)
        sc = jnp.where((pos <= cache_len)[None, None, None, :], sc, NEG_INF)
        m2 = jnp.maximum(m, sc.max(-1))
        corr = jnp.exp(m - m2)
        w = jnp.exp(sc - m2[..., None])
        l2 = l * corr + w.sum(-1)
        acc2 = acc * corr[..., None] + jnp.einsum(
            "bhgp,bphd->bhgd", w.astype(vp.dtype), vp,
            preferred_element_type=jnp.float32)
        return m2, l2, acc2

    m0 = jnp.full((b, hkv, groups), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, hkv, groups), jnp.float32)
    acc0 = jnp.zeros((b, hkv, groups, dh), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, n_pages, body, (m0, l0, acc0))
    out = acc / jnp.maximum(l, 1e-20)[..., None]
    return out.reshape(b, 1, h, dh).astype(q.dtype)


def cached_attention(
    q: jnp.ndarray,
    k_cache: jnp.ndarray,
    v_cache: jnp.ndarray,
    cache_len: jnp.ndarray,
    *,
    sliding_window=None,
    scale: float = 0.0,
    logit_softcap: float = 0.0,
) -> jnp.ndarray:
    """Causal attention of T query tokens over a cache holding cache_len+T keys.

    q: [B, T, H, Dh] — query i has absolute position cache_len + i.
    k_cache/v_cache: [B, S, Hkv, Dh] with the new keys already written.
    Returns [B, T, H, Dh].

    sliding_window may be a static int OR a traced int32 scalar (the
    per-layer "window" leaf of alternating local/global models riding a
    layer scan); a value <= 0 disables the window, so one compiled body
    serves both layer kinds. scale overrides the head_dim ** -0.5 score
    scale (gemma2 query_pre_attn_scalar); logit_softcap > 0 applies
    cap * tanh(s / cap) to scores before masking (gemma2).

    Right-padded prefill is safe: a real query at position i only attends to
    keys j <= cache_len + i, all of which are real tokens; padded queries
    produce garbage rows that the caller discards.
    """
    b, t, h, dh = q.shape
    s = k_cache.shape[1]
    hkv = k_cache.shape[2]
    groups = h // hkv
    # Keep cache operands in their storage dtype (bf16 on TPU) — converting the
    # whole [B,S,Hkv,Dh] cache to fp32 would double HBM traffic per decode
    # step. fp32 accumulation comes from preferred_element_type instead.
    q = q * (scale if scale else dh ** -0.5)

    # [B, T, Hkv, G, Dh] x [B, S, Hkv, Dh] -> [B, Hkv, G, T, S]
    qg = q.reshape(b, t, hkv, groups, dh)
    scores = jnp.einsum(
        "bthgd,bshd->bhgts", qg, k_cache, preferred_element_type=jnp.float32
    )
    if logit_softcap:
        scores = logit_softcap * jnp.tanh(scores / logit_softcap)

    q_pos = cache_len + jnp.arange(t, dtype=jnp.int32)  # [T]
    k_pos = jnp.arange(s, dtype=jnp.int32)  # [S]
    allowed = k_pos[None, :] <= q_pos[:, None]  # causal
    if sliding_window is not None:
        w = jnp.asarray(sliding_window, jnp.int32)
        allowed &= (k_pos[None, :] > (q_pos[:, None] - w)) | (w <= 0)
    scores = jnp.where(allowed[None, None, None, :, :], scores, NEG_INF)

    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum(
        "bhgts,bshd->bthgd",
        probs.astype(v_cache.dtype),
        v_cache,
        preferred_element_type=jnp.float32,
    )
    return out.reshape(b, t, h, dh).astype(q.dtype)
