"""Fused NF4 dequant-matmul Pallas kernel (the round-5 nf4 throughput
lever).

XLA cannot fuse the 4-bit unpack + 16-level codebook lookup into the MXU
operand feed: the dequantized weight materializes through a ~20-op VPU
elementwise chain per weight per step, measured 5x slower than bf16
serving on the flagship (docs/PERFORMANCE.md "Quantized serving"). This
kernel streams the PACKED nibbles (0.5 B/weight) + per-block scales from
HBM, dequantizes per N-tile in VMEM, and feeds the MXU directly.

Layout trick: a packed byte holds K-rows (2r, 2r+1) — rather than
interleave rows in VMEM (a sublane shuffle Mosaic lowers badly), the
matmul is split by nibble parity:

    y = x_even @ dequant(high_nibbles) + x_odd @ dequant(low_nibbles)

which is exact because matmul contraction is order-free. The activation
is split host-side (x[:, 0::2], x[:, 1::2] — tiny [M, K] tensors).

Grid: one program per N tile (128- or 256-wide — `_tile_n` picks the
widest that divides N and fits the VMEM budget), full-K stripes (the K
loop lives in the MXU contraction; no cross-program accumulation
state). Tile-size gotchas learned on-chip, encoded as guards below: N
must split into whole tiles (a non-dividing grid silently truncates),
scales ride as f32 so the scale block's sublane count stays legal, and
the uint8 block is widened to int32 BEFORE shifting (Mosaic cannot
legalize vector i8 shrui).

Launch aggregation (round 7): ONE pallas_call already covers all N
tiles of a weight via the grid, so launches/step = matmul SITES, not
tiles. The round-5 count (~80/step at M=16: 7 sites x 16 layers
untamed by scan site-sharing on the per-step path) was dominated by
quantized trees skipping the engine-side QKV and gate+up fusions —
`models.transformer._concat_out_axis` now concatenates packed NF4 (and
int8) leaves exactly, so a layer runs FOUR launches (wqkv, wo, wgu,
wd), each one `pallas_call` whose grid walks the fused weight's full N
extent, and under `lax.scan` those four SITES serve every layer of the
step. Cross-layer aggregation into a single launch is structurally
impossible — attention and norms sit between the matmuls — so 4 sites
is the floor for this architecture, pinned (with the `_launches`
counter below) by the launch-count guard in tests/test_burst.py.

`nf4_dot` is the dispatch wrapper used by the model's matmul sites when
`NF4_KERNEL=1` (utils env flag): it falls back to dequant-then-matmul
for any shape the kernel does not cover, so enabling the flag can never
change reachability — only speed. Token parity with the dequant path is
pinned by tests/test_nf4_kernel.py.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

# NOTE on enablement: the NF4_KERNEL env flag is consumed in
# models.quant.dequant_tree (which decides whether packed NF4 leaves
# reach the matmul sites at all); nf4_dot itself dispatches purely on
# leaf type and shape.
from ..models.quant import NF4_LEVELS, NF4Tensor, _lut16

TILE_N = 128

# Tests flip this to run the kernel through the Pallas interpreter on the
# CPU backend (slow, exact semantics) — the kernel itself targets TPU.
_INTERPRET = False

# Trace-time dispatch counter: incremented once per kernel-path call SITE
# per trace (under lax.scan the body traces once for all layers), so
# tests can pin "launch sites per decode step" without running on-chip.
_launches = 0


def _vmem_bytes(m: int, p: int, sb: int, tn: int, x_bytes: int) -> int:
    """Per-program VMEM footprint estimate, double-buffered: two x blocks
    [m, p], packed [p, tn] u8, scales [sb, tn] f32, two dequantized weight
    tiles [p, tn], and the out tile [m, tn] f32."""
    one = (2 * m * p * x_bytes + p * tn + sb * tn * 4
           + 2 * p * tn * x_bytes + m * tn * 4)
    return 2 * one


def _tile_n(n: int, k: int, m: int, x_bytes: int) -> int:
    """Widest N tile that divides N AND fits the VMEM budget: 256 halves
    the grid steps per launch (measured +3.8% flagship nf4 decode,
    7.04 -> 6.78 ms/step; post gate+up fusion every flagship/gpt2 N
    divides 256). The budget guard matters: 512 already exceeded VMEM at
    the flagship K (compile failure, measured), and a larger-K model or a
    big prefill m would hit the same wall at 256 — fall back to 128
    rather than fail a shape that used to serve."""
    p, sb = k // 2, k // 64
    budget = 12 * 1024 * 1024          # ~16 MB/core minus headroom
    if n % 256 == 0 and _vmem_bytes(m, p, sb, 256, x_bytes) <= budget:
        return 256
    return TILE_N

# MOSAIC CONSTRAINT on quant._lut16 (one shared select tree): the level
# constants must stay f32 — bf16 levels would make Mosaic relayout the
# int32-derived (8,128) i1 mask tiles into (16,128) bf16 selects, which
# it cannot ('Invalid relayout ... vector<...xi1>'). quant.py documents
# the same requirement from its side.


@functools.lru_cache(maxsize=64)
def _make_kernel(m: int, k: int, n: int, out_dtype: str,
                 interpret: bool = False):
    from jax.experimental import pallas as pl

    p = k // 2
    sb = k // 64
    tn = _tile_n(n, k, m, jnp.dtype(out_dtype).itemsize)

    def kernel(xe_ref, xo_ref, pk_ref, sc_ref, out_ref):
        packed = pk_ref[:].astype(jnp.int32)   # int32 FIRST: Mosaic has no
        hi = (packed >> 4) & 0xF               # vector i8 shrui
        lo = packed & 0xF
        scale = jnp.repeat(sc_ref[:], p // sb, axis=0)      # [P, tn]
        # Weights take the ACTIVATION dtype (bf16 serving feeds the MXU at
        # bf16 rate; an f32 activation keeps f32 — also what the CPU
        # interpreter's dot supports).
        wdt = xe_ref.dtype
        wh = (_lut16(hi, NF4_LEVELS) * scale).astype(wdt)
        wl = (_lut16(lo, NF4_LEVELS) * scale).astype(wdt)
        acc = jnp.dot(xe_ref[:], wh, preferred_element_type=jnp.float32)
        acc = acc + jnp.dot(xo_ref[:], wl,
                            preferred_element_type=jnp.float32)
        out_ref[:] = acc.astype(out_ref.dtype)

    @jax.jit
    def fn(xe, xo, packed, scales):
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((m, n), jnp.dtype(out_dtype)),
            grid=(n // tn,),
            in_specs=[
                pl.BlockSpec((m, p), lambda j: (0, 0)),
                pl.BlockSpec((m, p), lambda j: (0, 0)),
                pl.BlockSpec((p, tn), lambda j: (0, j)),
                pl.BlockSpec((sb, tn), lambda j: (0, j)),
            ],
            out_specs=pl.BlockSpec((m, tn), lambda j: (0, j)),
            interpret=interpret,
        )(xe, xo, packed, scales)

    return fn


def _supported(m: int, w: NF4Tensor) -> bool:
    in_dim = w.in_dim
    n = w.packed.shape[-1]
    assert m % 8 == 0, "caller pads rows to a multiple of 8"
    return (w.packed.ndim == 2            # one layer's weight, not a stack
            and in_dim == w.packed.shape[0] * 2   # no in-axis padding
            and in_dim % 128 == 0
            and n % TILE_N == 0
            and (jax.default_backend() == "tpu" or _INTERPRET))


def nf4_dot(x: jnp.ndarray, w: NF4Tensor) -> jnp.ndarray:
    """x [..., K] @ NF4 weight [K, N] -> [..., N] in x.dtype.

    Kernel path when the shape qualifies (see `_supported`); exact
    dequant-then-matmul fallback otherwise — enabling the kernel never
    changes which shapes serve."""
    global _launches
    lead = x.shape[:-1]
    k = x.shape[-1]
    x2 = x.reshape(-1, k)
    m = x2.shape[0]
    m_pad = -(-max(m, 8) // 8) * 8
    if _supported(m_pad, w):
        _launches += 1
        if m_pad != m:
            x2 = jnp.pad(x2, ((0, m_pad - m), (0, 0)))
        fn = _make_kernel(m_pad, k, w.packed.shape[-1], str(x.dtype),
                          interpret=_INTERPRET)
        out = fn(x2[:, 0::2], x2[:, 1::2], w.packed,
                 w.scales.astype(jnp.float32))
        return out[:m].reshape(*lead, -1)
    return x @ w.dequant().astype(x.dtype)
