"""Multi-session ring decode: concurrent sessions fill the pipeline bubble.

The GPipe-style fused pipeline (`parallel.pipeline`) serves ONE session's
microbatches: during decode, a token must traverse all S stages before the
next token can start, so S-1 of S chips idle every tick (measured
bubble_frac 0.33-0.49 in BENCH_r03 `pipeline_microbatch_s4`). The fix —
and the reference's whole serving model, which its GPU deployment could
never exploit because each stage was a separate host
(`petals/server/handler.py:132-195`: every handler serves many concurrent
sessions; task pools `petals/server/task_pool.py:29-167` exist to batch
them) — is MULTI-SESSION decode: G >= S independent session groups rotate
through the stages, stage s advancing group ``(t - s) mod G`` at tick t.

Steady state: every stage busy every tick, one sampled token per tick
(times the per-group slot batch B). The only bubble is the S-1-tick
pipeline fill at the start of a chunk:

    bubble_frac = (S - 1) / (G * n_steps + S - 1)      -> ~0 for long runs

Design (one jitted program, ``lax.ppermute`` ring under ``shard_map``):

  * the KV layout IS the fused pipeline's ([S, L/S, G, B, max_len, Hkv, Dh],
    stage-sharded, group axis == the GPipe microbatch axis), so prefill
    reuses ``IciPipeline.forward`` with M = G unchanged and ring decode
    continues on the same buffers;
  * the ring carry is (hidden [B,1,D], token [B]): intermediate edges use
    the hidden, the wrap edge S-1 -> 0 uses the token — the last stage's
    freshly sampled token re-enters the pipeline as the embedding input of
    that group's next position. With G == S it is consumed the very next
    tick; with G > S stage 0 parks it in a [G, B] token buffer until the
    rotation comes back around (write-before-read in the same tick makes
    G == S a degenerate no-wait case of the same code path);
  * embedding (stage 0) and final-norm + head + argmax (last stage) run
    INSIDE the shard-mapped body — sampling is part of the ring, not a host
    round trip. The head runs under ``lax.cond`` so intermediate stages
    skip its FLOPs; note this makes the LAST stage the per-tick critical
    path (span + head) — balance by giving it fewer layers if profiling
    shows it dominating (the TCP path's balance_quality analogue);
  * per-group cache lengths [G] are device-local state: each stage
    increments only the group it just served, so positions/caches stay
    correct even though stages touch a group at different ticks.

Chunked use mirrors `runtime.fused_decode`: the caller runs N steps per
call (n is TRACED — one compile serves every chunk size), checks stop
conditions between chunks, and a finished group's slot can be re-prefilled
by a masked single-group prefill (see `ring_prefill_group`) without
touching the other groups' caches — continuous batching across the
pipeline, not just across slots of one stage.

Greedy sampling (argmax) is fused here; distributed sampled serving stays
on the per-step final-hop sampler which needs live request metadata
(`runtime.executor._sample_last`).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import PartitionSpec as P

from ..models.config import ModelConfig
from ..models.transformer import _norm, stack_forward
from .pipeline import IciPipeline, _kv_spec

Params = Dict[str, Any]


def _ring_body(cfg: ModelConfig, num_stages: int, num_groups: int,
               max_steps: int, exact_head: bool,
               tp_axis: Optional[str] = None):
    """shard_map body: the tick loop. Local views per stage device:
    layers [1, L/S, ...]; kv [1, L/S, G, B, max_len, Hkv, Dh];
    tokens0 [G, B], lens0 [G] (replicated in, device-local thereafter)."""
    S, G = num_stages, num_groups

    def body(layers, embed_p, head_p, tokens0, k_all, v_all, lens0, n):
        layers = jax.tree.map(lambda x: x[0], layers)
        k_all, v_all = k_all[0], v_all[0]     # [L/S, G, B, max_len, Hkv, Dh]
        s = jax.lax.axis_index("stage")
        is_last = s == S - 1
        perm = [(i, (i + 1) % S) for i in range(S)]
        B = tokens0.shape[1]
        D = cfg.hidden_size
        wte = embed_p["wte"]

        def embed_tok(tok, pos):
            # tok [B] -> [B, 1, D]; mirrors fused_decode._decode_step.
            x = jnp.take(wte, tok[:, None], axis=0)
            if cfg.positional == "learned":
                p = jnp.clip(pos, 0, cfg.max_position_embeddings - 1)
                x = x + jnp.take(embed_p["wpe"], p, axis=0)
            return x

        if cfg.tie_word_embeddings:
            w_head = wte                                   # [V, D]
        else:
            w_head = head_p["lm_head"]["w"].T              # [V, D]
        hdt = jnp.float32 if exact_head else w_head.dtype

        def head_argmax(h):
            # h [B, 1, D] -> greedy token [B]; transposed weights-stationary
            # head fused with argmax (fused_decode's measured layout).
            hn = _norm(cfg, head_p["final_norm"], h)[:, 0]  # [B, D]
            logits_t = w_head.astype(hdt) @ hn.T.astype(hdt)  # [V, B]
            return jnp.argmax(logits_t.astype(jnp.float32), axis=0).astype(
                jnp.int32)

        def tick(t, carry):
            hid_rx, tok_rx, tok_buf, k_all, v_all, lens, outs = carry
            # Stage 0 first PARKS the wrap token (sampled at tick t-1 by the
            # last stage for group (t - S) mod G), THEN reads its current
            # group's token — write-before-read makes G == S the no-buffer
            # case of the same code.
            wg = jnp.mod(t - S, G)
            parked = jax.lax.dynamic_update_index_in_dim(
                tok_buf, tok_rx, wg, 0)
            tok_buf = jnp.where((s == 0) & (t >= S), parked, tok_buf)

            g = jnp.mod(t - s, G)
            valid = (t >= s) & (t - s < G * n)
            myl = jax.lax.dynamic_index_in_dim(lens, g, 0, keepdims=False)
            pos = myl + jnp.zeros((B, 1), jnp.int32)
            tok_in = jax.lax.dynamic_index_in_dim(
                tok_buf, jnp.mod(t, G), 0, keepdims=False)       # [B]
            x_in = jnp.where(s == 0, embed_tok(tok_in, pos), hid_rx)

            kc = jax.lax.dynamic_index_in_dim(k_all, g, 1, keepdims=False)
            vc = jax.lax.dynamic_index_in_dim(v_all, g, 1, keepdims=False)
            out, nk, nv = stack_forward(
                cfg, layers, x_in, pos, kc, vc, myl, tp_axis=tp_axis)
            # Bubble ticks (fill/drain) compute on garbage; their writes
            # must not land.
            nk = jnp.where(valid, nk, kc)
            nv = jnp.where(valid, nv, vc)
            k_all = jax.lax.dynamic_update_index_in_dim(k_all, nk, g, 1)
            v_all = jax.lax.dynamic_update_index_in_dim(v_all, nv, g, 1)
            lens = jnp.where(
                valid,
                jax.lax.dynamic_update_index_in_dim(lens, myl + 1, g, 0),
                lens)

            # Only the last stage pays the head matmul (lax.cond, runtime
            # branch per device — intermediate stages skip the FLOPs).
            tok_out = jax.lax.cond(
                is_last & valid,
                lambda: head_argmax(out),
                lambda: jax.lax.pcast(jnp.zeros((B,), jnp.int32),
                                      ("stage",), to="varying"))
            step_i = (t - (S - 1)) // G
            rec = jax.lax.dynamic_update_slice(
                outs, tok_out[None, None, :], (step_i, g, 0))
            outs = jnp.where(is_last & valid, rec, outs)

            hid_rx = jax.lax.ppermute(out, "stage", perm)
            tok_rx = jax.lax.ppermute(tok_out, "stage", perm)
            return hid_rx, tok_rx, tok_buf, k_all, v_all, lens, outs

        varying = lambda x: jax.lax.pcast(x, ("stage",), to="varying")
        hid0 = varying(jnp.zeros((B, 1, D), wte.dtype))
        tok0 = varying(jnp.zeros((B,), jnp.int32))
        outs0 = varying(jnp.zeros((max_steps, G, B), jnp.int32))
        tok_buf0 = varying(tokens0)
        lens = varying(lens0)

        _, _, _, k_all, v_all, lens, outs = jax.lax.fori_loop(
            0, G * n + S - 1, tick,
            (hid0, tok0, tok_buf0, k_all, v_all, lens, outs0))
        # Only the last stage populated outs; psum replicates it.
        outs = jax.lax.psum(
            jnp.where(is_last, outs, jnp.zeros_like(outs)), "stage")
        return outs, k_all[None], v_all[None]

    return body


@dataclasses.dataclass
class RingDecoder:
    """Compiled multi-session ring-decode runner over an IciPipeline's mesh,
    params, and KV buffers. ``pipe.num_micro`` is the session-group count G
    (must be >= num_stages for gapless rotation)."""

    pipe: IciPipeline
    max_steps: int
    _step: Any

    @staticmethod
    def build(pipe: IciPipeline, max_steps: int = 128,
              exact_head: bool = True) -> "RingDecoder":
        S, G = pipe.num_stages, pipe.num_micro
        if G < S:
            raise ValueError(
                f"ring decode needs sessions >= stages for a gapless "
                f"rotation: num_micro (session groups) {G} < num_stages {S}"
                " — a sampled token would be needed before the wrap edge "
                "delivers it")
        cfg = pipe.cfg
        tp_axis = "tp" if pipe.tp > 1 else None
        body = _ring_body(cfg, S, G, max_steps, exact_head, tp_axis=tp_axis)
        spec_kv = _kv_spec(pipe.tp)
        layer_specs = jax.tree.map(lambda x: x.sharding.spec,
                                   pipe.layers_stacked)
        mesh = pipe.mesh

        # Donation ungated: single-controller engine (see the rationale in
        # parallel/pipeline.py step()).
        @partial(jax.jit, donate_argnums=(4, 5))
        def step(embed_p, head_p, layers_p, tokens0, k_all, v_all, lens, n):
            sharded = shard_map(
                body, mesh=mesh,
                in_specs=(layer_specs, P(), P(), P(), spec_kv, spec_kv,
                          P(), P()),
                out_specs=(P(), spec_kv, spec_kv),
            )
            return sharded(layers_p, embed_p, head_p, tokens0, k_all, v_all,
                           lens, n)

        return RingDecoder(pipe=pipe, max_steps=max_steps, _step=step)

    def decode(
        self,
        tokens0: jnp.ndarray,     # [G, B] int32: last token per session row
        k_all: jnp.ndarray,
        v_all: jnp.ndarray,
        lens: jnp.ndarray,        # [G] int32 per-group cache lengths
        n: int,                   # steps this chunk (traced; <= max_steps)
    ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
        """Run n ring-decode steps for every session group. Returns
        (toks [max_steps, G, B] — rows >= n are zero, toks[i, g, b] is the
        i-th new token of session (g, b) —, new k, new v). New per-group
        lengths are deterministically ``lens + n``."""
        G, B = tokens0.shape
        if n > self.max_steps:
            raise ValueError(
                f"n {n} > max_steps {self.max_steps} (the output buffer is "
                "statically sized; chunk the call)")
        if G != self.pipe.num_micro:
            raise ValueError(
                f"tokens0 has {G} session groups, pipeline compiled for "
                f"{self.pipe.num_micro}")
        if B != k_all.shape[3]:
            raise ValueError(
                f"tokens0 slot batch {B} != KV cache batch {k_all.shape[3]}")
        return self._step(self.pipe.embed, self.pipe.head,
                          self.pipe.layers_stacked, tokens0, k_all, v_all,
                          lens, jnp.int32(n))


def make_ring_prefill_group(pipe: IciPipeline, exact_head: bool = True):
    """Build a jitted SINGLE-GROUP prefill: write a new session's prompt KV
    into group slot ``g`` without touching any other group's cache — the
    continuous-batching join path (a finished session's slot is re-prefilled
    between decode chunks while the other G-1 groups' caches stay live).

    Returns ``fn(ids [B, T], k_all, v_all, g) -> (tok0 [B], k, v)`` where
    ``tok0`` is the greedy first token (the caller then sets
    ``lens[g] = T`` and hands tok0 to the next ``RingDecoder.decode`` call
    via its tokens0 row).
    """
    cfg = pipe.cfg
    S = pipe.num_stages
    tp_axis = "tp" if pipe.tp > 1 else None
    spec_kv = _kv_spec(pipe.tp)
    layer_specs = jax.tree.map(lambda x: x.sharding.spec,
                               pipe.layers_stacked)
    mesh = pipe.mesh

    def body(layers, embed_p, head_p, x, k_all, v_all, g):
        layers = jax.tree.map(lambda q: q[0], layers)
        k_all, v_all = k_all[0], v_all[0]
        s = jax.lax.axis_index("stage")
        is_last = s == S - 1
        perm = [(i, (i + 1) % S) for i in range(S)]
        b, t, _ = x.shape

        kc = jax.lax.dynamic_index_in_dim(k_all, g, 1, keepdims=False)
        vc = jax.lax.dynamic_index_in_dim(v_all, g, 1, keepdims=False)
        positions = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32)[None],
                                     (b, t))

        def tick(ti, carry):
            received, kc, vc, last_h = carry
            x_in = jnp.where(s == 0, x, received)
            out, nk, nv = stack_forward(
                cfg, layers, x_in, positions, kc, vc, jnp.int32(0),
                tp_axis=tp_axis)
            active = ti == s          # sequential: stage s fires at tick s
            kc = jnp.where(active, nk, kc)
            vc = jnp.where(active, nv, vc)
            last_h = jnp.where(active & is_last, out, last_h)
            received = jax.lax.ppermute(out, "stage", perm)
            return received, kc, vc, last_h

        varying = lambda q: jax.lax.pcast(q, ("stage",), to="varying")
        received = varying(jnp.zeros_like(x))
        last_h = varying(jnp.zeros_like(x))
        received, kc, vc, last_h = jax.lax.fori_loop(
            0, S, tick, (received, kc, vc, last_h))
        k_all = jax.lax.dynamic_update_index_in_dim(k_all, kc, g, 1)
        v_all = jax.lax.dynamic_update_index_in_dim(v_all, vc, g, 1)

        if cfg.tie_word_embeddings:
            w_head = embed_p["wte"]
        else:
            w_head = head_p["lm_head"]["w"].T
        hdt = jnp.float32 if exact_head else w_head.dtype
        hn = _norm(cfg, head_p["final_norm"], last_h)[:, -1]     # [B, D]
        logits_t = w_head.astype(hdt) @ hn.T.astype(hdt)         # [V, B]
        tok0 = jnp.argmax(logits_t.astype(jnp.float32), axis=0).astype(
            jnp.int32)
        tok0 = jax.lax.psum(
            jnp.where(is_last, tok0, jnp.zeros_like(tok0)), "stage")
        return tok0, k_all[None], v_all[None]

    from ..models.transformer import embed_tokens

    @partial(jax.jit, donate_argnums=(4, 5))
    def fn(embed_p, head_p, layers_p, ids, k_all, v_all, g):
        b, t = ids.shape
        positions = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32)[None],
                                     (b, t))
        x = embed_tokens(cfg, embed_p, ids, positions)
        sharded = shard_map(
            body, mesh=mesh,
            in_specs=(layer_specs, P(), P(), P(), spec_kv, spec_kv, P()),
            out_specs=(P(), spec_kv, spec_kv),
        )
        return sharded(layers_p, embed_p, head_p, x, k_all, v_all, g)

    def run(ids: jnp.ndarray, k_all, v_all, g) -> Tuple[jnp.ndarray, Any, Any]:
        return fn(pipe.embed, pipe.head, pipe.layers_stacked,
                  jnp.asarray(ids, jnp.int32), k_all, v_all, jnp.int32(g))

    return run


def ring_generate(pipe: IciPipeline, rd: RingDecoder, ids: jnp.ndarray,
                  k_all: jnp.ndarray, v_all: jnp.ndarray,
                  n_tokens: int) -> jnp.ndarray:
    """Convenience driver: GPipe prefill (M = G microbatches, one per
    session group) + greedy ring decode. ids [G, B, T] (equal prompt
    lengths; pad shorter prompts). Returns tokens [n_tokens, G, B]."""
    G, B, T = ids.shape
    logits, k_all, v_all = pipe.forward(ids, k_all, v_all, jnp.int32(0))
    tokens0 = jnp.argmax(
        logits[:, :, -1].astype(jnp.float32), axis=-1).astype(jnp.int32)
    if n_tokens == 1:
        return tokens0[None]
    lens = jnp.full((G,), T, jnp.int32)
    # tokens0 (from the prefill logits) IS generated token 1; the ring
    # produces tokens 2..n_tokens.
    toks, k_all, v_all = rd.decode(tokens0, k_all, v_all, lens, n_tokens - 1)
    return jnp.concatenate([tokens0[None], toks[: n_tokens - 1]], axis=0)
