"""Sequence-parallel stage serving: long-context prefill + decode with the
KV cache SHARDED along the sequence axis of an intra-stage mesh.

SURVEY.md §5.7: the reference's only long-context mechanism is single-server
chunked prefill (bounding one GPU's peak activation memory —
``petals/server/backend.py:129-143``); its KV cache still must fit one
machine. This module is the TPU-native capability the survey marks as the
place to EXCEED the reference: P devices hold P× the context at the same
per-device HBM.

Two phases, one engine (`SpStageRunner`):

  * **prefill** — the prompt is sharded along T over the "sp" axis; every
    layer runs ring attention (parallel.ring_attention: KV chunks rotate via
    ppermute while each device accumulates its queries' online softmax).
    The resulting per-layer K/V stay SHARDED — the prefix cache is a global
    array with its sequence axis split across the mesh, never gathered.
  * **decode** — the new token's hidden state is replicated; each device
    attends over ITS prefix shard and the partial softmaxes combine with a
    pmax/psum log-sum-exp reduction. Freshly generated tokens append to a
    small REPLICATED tail cache (bounded by ``tail_max``), so decode writes
    never cross devices: long context lives in the sharded prefix, the
    generation tail is cheap everywhere.

Numerics are exact (online softmax, fp32 accumulation), so outputs are
asserted token-identical to the single-device oracle in
tests/test_sp_stage.py. Sliding-window configs are rejected (ring masking
is causal-only today).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.config import ModelConfig
from ..models.partition import StageSpec
from ..models.transformer import (
    _dot,
    _mlp,
    _norm,
    embed_tokens,
    make_rope,
    qkv_proj,
)
from ..ops.rotary import apply_rope
from ..utils.platform import engine_donation
from .ring_attention import (
    NEG_INF,
    online_combine,
    online_partial,
    ring_attention,
    zigzag_order,
    zigzag_ring_attention,
)


def _zigzag_device_positions(idx, c, p):
    """Absolute sequence positions of device ``idx``'s zigzag chunk of
    size c (= two half-chunks of c//2: low chunk idx, high 2P-1-idx)."""
    c2 = c // 2
    ar = jnp.arange(c2, dtype=jnp.int32)
    return jnp.concatenate([idx * c2 + ar, (2 * p - 1 - idx) * c2 + ar])

Params = Dict[str, Any]


class SpSession:
    """One session's mesh-wide cache state: prefix KV sharded on the
    sequence axis, generation tail replicated. Multiple sessions coexist on
    one runner (multi-session sp serving, VERDICT r3 item 5) — each holds
    its own buffers; the runner's compiled programs are shared, jit
    re-specializing per padded prompt length."""

    __slots__ = ("pk", "pv", "tk", "tv", "prefix_pad", "prefix_len",
                 "tail_len")

    def __init__(self):
        self.pk = self.pv = None   # [L, B, prefix_pad, Hkv, Dh] sharded on T
        self.tk = self.tv = None   # [L, B, tail_max, Hkv, Dh] replicated
        self.prefix_pad = 0
        self.prefix_len = 0
        self.tail_len = 0

    @property
    def cache_len(self) -> int:
        return self.prefix_len + self.tail_len


class SpStageRunner:
    """One stage's span executed sequence-parallel over `mesh[axis_name]`.

    The role contract matches StageExecutor's (stage0 consumes token ids,
    later stages hidden states; the last stage owns norm + head), but the
    session cache is mesh-wide: prefix sharded on T, tail replicated.
    """

    def __init__(
        self,
        cfg: ModelConfig,
        spec: StageSpec,
        params: Params,
        mesh: Mesh,
        axis_name: str = "sp",
        *,
        tail_max: int = 512,
        dtype=jnp.float32,
        zigzag: bool = False,
    ):
        if cfg.sliding_window:
            raise ValueError("sp serving is causal-only (no sliding window)")
        from ..models.config import custom_engine_unsupported

        reason = custom_engine_unsupported(cfg)
        if reason:
            raise ValueError(f"sp engine: {reason}")
        self.cfg = cfg
        self.spec = spec
        self.mesh = mesh
        self.axis = axis_name
        self.p = int(mesh.shape[axis_name])
        self.tail_max = tail_max
        self.dtype = jnp.dtype(dtype)
        # Zigzag sequence layout (parallel.ring_attention zigzag): device i
        # holds half-chunks i and 2P-1-i, so causal-prefill work is FLAT
        # across devices ((2P+1)/4 block-equivalents each) instead of
        # skewed 1..P — the slowest device's critical path roughly halves.
        # The session's prefix KV then LIVES in zigzag order; decode is
        # layout-agnostic (its per-device softmax partial only needs the
        # right position array) and returned hiddens are restored to
        # natural order, so the flag is invisible outside this class.
        self.zigzag = zigzag
        # Engine-side fused-QKV layout (one projection matmul per layer,
        # bitwise-identical — models/transformer.fuse_qkv_params); the sp
        # axis shards the SEQUENCE, never the projections, so fusion is
        # always safe here.
        from ..models.transformer import fuse_qkv_params

        params = fuse_qkv_params(params)
        # Replicate the span's params over the mesh once.
        repl = NamedSharding(mesh, P())
        self.params = jax.device_put(params, repl)

        # Legacy single-session facade state (prefill/decode/reset); the
        # session-explicit API (start_session/decode_step) carries its own.
        self._default = SpSession()
        self._prefill_fn = None
        self._decode_fn = None

    # ------------------------------------------------------------------

    @property
    def cache_len(self) -> int:
        return self._default.cache_len

    @property
    def prefix_len(self) -> int:
        return self._default.prefix_len

    @property
    def tail_len(self) -> int:
        return self._default.tail_len

    @property
    def prefix_pad(self) -> int:
        return self._default.prefix_pad

    @property
    def pk(self):
        return self._default.pk

    @property
    def pv(self):
        return self._default.pv

    # -- per-device session cost (the admission currency) ---------------

    def prefix_bytes_per_device(self, t: int, batch: int = 1) -> int:
        """Per-device bytes of a session's sharded prefix KV for a t-token
        prompt (k + v, padded to the mesh — 2P-aligned under zigzag, the
        same rounding start_session applies, or admission control would
        undercount the real allocation and overcommit HBM)."""
        mult = 2 * self.p if self.zigzag else self.p
        t_pad = -(-t // mult) * mult
        l = max(self.spec.num_layers, 1)
        return (2 * l * batch * (t_pad // self.p) * self.cfg.num_kv_heads
                * self.cfg.head_dim * self.dtype.itemsize)

    def tail_bytes_per_device(self, batch: int = 1) -> int:
        """Per-device bytes of a session's REPLICATED tail KV (k + v)."""
        l = max(self.spec.num_layers, 1)
        return (2 * l * batch * self.tail_max * self.cfg.num_kv_heads
                * self.cfg.head_dim * self.dtype.itemsize)

    def session_bytes_per_device(self, t: int, batch: int = 1) -> int:
        return (self.prefix_bytes_per_device(t, batch)
                + self.tail_bytes_per_device(batch))

    def _shard_seq(self):
        return NamedSharding(self.mesh, P(None, None, self.axis))

    # ------------------------------------------------------------------
    # Prefill: ring attention, collect sharded prefix KV
    # ------------------------------------------------------------------

    def _build_prefill(self):
        # Built ONCE; jax.jit specializes per input shape, so alternating
        # prompt lengths each compile once instead of retracing every call.
        cfg, spec, axis = self.cfg, self.spec, self.axis
        mesh = self.mesh
        in_spec = (P(),                                    # params (replicated)
                   P(None, axis) if spec.is_first else P(None, axis, None))
        out_spec = (P(None, axis, None),                   # hidden
                    P(None, None, axis),                   # k [L,B,C,...]
                    P(None, None, axis))                   # v

        zigzag = self.zigzag

        @jax.jit
        @partial(jax.shard_map, mesh=mesh, in_specs=in_spec,
                 out_specs=out_spec)
        def fn(params, x):
            idx = jax.lax.axis_index(axis)
            p = jax.lax.psum(1, axis)
            c = x.shape[1]
            b = x.shape[0]
            if zigzag:
                # x arrives PRE-PERMUTED to zigzag order (start_session);
                # this device holds half-chunks idx and 2P-1-idx.
                pos_dev = _zigzag_device_positions(idx, c, p)
            else:
                pos_dev = idx * c + jnp.arange(c, dtype=jnp.int32)
            positions = jnp.broadcast_to(pos_dev[None, :], (b, c))
            if spec.is_first:
                h = embed_tokens(cfg, params["embed"], x, positions)
            else:
                h = x
            rope = make_rope(cfg, positions)

            def layer(h, lp):
                from ..models.quant import dequant_tree

                lp = dequant_tree(lp, keep_experts=cfg.is_moe)
                a = _norm(cfg, lp["ln1"], h)
                q, k, v = qkv_proj(cfg, lp["attn"], a)
                if rope is not None:
                    q = apply_rope(q, *rope)
                    k = apply_rope(k, *rope)
                if zigzag:
                    out = zigzag_ring_attention(q, k, v, axis)
                else:
                    out = ring_attention(q, k, v, axis, q_offset=idx * c)
                out = _dot(out.reshape(h.shape[0], c, -1), lp["attn"]["wo"])
                if "bo" in lp["attn"]:
                    out = out + lp["attn"]["bo"]
                h = h + out
                h = h + _mlp(cfg, lp["mlp"], _norm(cfg, lp["ln2"], h), None)
                return h, (k, v)

            # NO final_norm here even for the last stage: logits_at's lm_head
            # applies it (models/transformer.py lm_head = norm + projection);
            # norming twice diverges for any non-unit norm weights.
            h, (ks, vs) = jax.lax.scan(layer, h, params["layers"])
            # ks/vs: [L, B, C, Hkv, Dh] — this device's chunk of the prefix.
            return h, ks.astype(self.dtype), vs.astype(self.dtype)

        return fn

    def start_session(self, x) -> Tuple[SpSession, jnp.ndarray]:
        """Prefill a NEW session. x: int ids [B, T] for the first stage,
        else hidden [B, T, D]. Returns (session, hidden [B, T, D]) — the
        hidden is global, sequence-sharded, padded rows trimmed."""
        x = jnp.asarray(x)
        b, t = x.shape[0], x.shape[1]
        # Zigzag needs an even half-chunk split per device (2 per device).
        mult = 2 * self.p if self.zigzag else self.p
        t_pad = -(-t // mult) * mult
        if t_pad != t:
            padw = ((0, 0), (0, t_pad - t)) + (((0, 0),) if x.ndim == 3 else ())
            x = jnp.pad(x, padw)
        if self.zigzag:
            x = jnp.take(x, zigzag_order(t_pad, self.p), axis=1)
        x = jax.device_put(
            x, NamedSharding(self.mesh,
                             P(None, self.axis) if x.ndim == 2
                             else P(None, self.axis, None)))
        if self._prefill_fn is None:
            self._prefill_fn = self._build_prefill()
        sess = SpSession()
        h, sess.pk, sess.pv = self._prefill_fn(self.params, x)
        if self.zigzag:
            # Callers see natural order; only the SESSION's prefix KV stays
            # zigzag-resident (decode is layout-agnostic given positions).
            h = jnp.take(h, jnp.argsort(zigzag_order(t_pad, self.p)), axis=1)
        sess.prefix_pad = t_pad
        sess.prefix_len = t
        sess.tail_len = 0
        l = max(self.spec.num_layers, 1)
        shape = (l, b, self.tail_max, self.cfg.num_kv_heads, self.cfg.head_dim)
        repl = NamedSharding(self.mesh, P())
        sess.tk = jax.device_put(jnp.zeros(shape, self.dtype), repl)
        sess.tv = jax.device_put(jnp.zeros(shape, self.dtype), repl)
        return sess, h[:, :t]

    def prefill(self, x) -> jnp.ndarray:
        """Legacy single-session facade: restarts THE session."""
        self._default, h = self.start_session(x)
        return h

    # ------------------------------------------------------------------
    # Decode: replicated token, sharded-prefix + replicated-tail attention
    # ------------------------------------------------------------------

    def _build_decode(self):
        cfg, spec, axis = self.cfg, self.spec, self.axis
        mesh = self.mesh
        seq_spec = P(None, None, axis)
        in_spec = (P(),                                     # params
                   P(None, None) if spec.is_first else P(),  # x (replicated)
                   seq_spec, seq_spec,                      # prefix k/v
                   P(), P(),                                # tail k/v
                   P(), P(), P())                           # prefix_len, tail_len, pos
        out_spec = (P(), P(), P())                          # h, tail k, tail v

        zigzag = self.zigzag

        # Donate the tail caches (updated every step) so the append is
        # in-place; the prefix caches are NOT donated — the same buffers are
        # re-passed for the whole session.
        @partial(jax.jit, donate_argnums=engine_donation(4, 5))
        @partial(jax.shard_map, mesh=mesh, in_specs=in_spec,
                 out_specs=out_spec)
        def fn(params, x, pk, pv, tk, tv, prefix_len, tail_len, pos):
            idx = jax.lax.axis_index(axis)
            p_dev = jax.lax.psum(1, axis)
            b = x.shape[0]
            positions = jnp.full((b, 1), pos, jnp.int32)
            if spec.is_first:
                h = embed_tokens(cfg, params["embed"], x, positions)
            else:
                h = x
            rope = make_rope(cfg, positions)
            c = pk.shape[2]                                  # prefix chunk
            scale = cfg.head_dim ** -0.5
            groups = cfg.num_heads // cfg.num_kv_heads

            def layer(h, lp):
                from ..models.quant import dequant_tree

                lp, (pk_l, pv_l, tk_l, tv_l) = lp
                lp = dequant_tree(lp, keep_experts=cfg.is_moe)
                a = _norm(cfg, lp["ln1"], h)
                q, k, v = qkv_proj(cfg, lp["attn"], a)           # [B,1,H/Hkv,Dh]
                if rope is not None:
                    q = apply_rope(q, *rope)
                    k = apply_rope(k, *rope)
                # Append to the tail (replicated write, same on every device).
                tk_n = jax.lax.dynamic_update_slice_in_dim(
                    tk_l, k.astype(tk_l.dtype), tail_len, axis=1)
                tv_n = jax.lax.dynamic_update_slice_in_dim(
                    tv_l, v.astype(tv_l.dtype), tail_len, axis=1)

                qg = q.reshape(b, 1, cfg.num_kv_heads, groups, cfg.head_dim)
                # Partial over MY prefix shard. The prefix KV lives in the
                # layout prefill produced — contiguous (positions idx*c+j)
                # or zigzag (two half-chunks); the online-softmax partial
                # only needs the matching position array, the psum combine
                # is order-independent.
                if zigzag:
                    ppos = _zigzag_device_positions(idx, c, p_dev)
                else:
                    ppos = idx * c + jnp.arange(c, dtype=jnp.int32)
                pmask = jnp.broadcast_to((ppos < prefix_len)[None, :], (b, c))
                part = online_partial(qg, pk_l.astype(q.dtype),
                                      pv_l.astype(q.dtype), pmask, scale)
                # Log-sum-exp combine across the mesh.
                m, l, o = part
                mg = jax.lax.pmax(m, axis)
                safe = jnp.where(mg <= NEG_INF / 2, 0.0, mg)
                corr = jnp.where(m <= NEG_INF / 2, 0.0, jnp.exp(m - safe))
                lg = jax.lax.psum(l * corr, axis)
                og = jax.lax.psum(o * corr[..., None], axis)
                # Tail partial (identical on every device; includes the token
                # just written at index tail_len).
                tpos = jnp.arange(tk_l.shape[1], dtype=jnp.int32)
                tmask = jnp.broadcast_to((tpos <= tail_len)[None, :],
                                         (b, tk_l.shape[1]))
                tpart = online_partial(qg, tk_n.astype(q.dtype),
                                       tv_n.astype(q.dtype), tmask, scale)
                m2, l2, o2 = online_combine((mg, lg, og), tpart)
                out = (o2 / jnp.maximum(l2, 1e-20)[..., None]).astype(h.dtype)
                out = _dot(out.reshape(b, 1, -1), lp["attn"]["wo"])
                if "bo" in lp["attn"]:
                    out = out + lp["attn"]["bo"]
                h = h + out
                h = h + _mlp(cfg, lp["mlp"], _norm(cfg, lp["ln2"], h), None)
                return h, (tk_n, tv_n)

            # No final_norm: lm_head (logits_at) owns it — see prefill.
            h, (tks, tvs) = jax.lax.scan(
                layer, h, (params["layers"], (pk, pv, tk, tv)))
            return h, tks, tvs

        return fn

    def decode_step(self, sess: SpSession, x) -> jnp.ndarray:
        """One decode step for `sess`. x: int ids [B, 1] for the first
        stage, else hidden [B, 1, D]. Returns hidden [B, 1, D]; appends to
        the session's tail."""
        if sess.pk is None:
            raise RuntimeError("decode before prefill")
        if sess.tail_len >= self.tail_max:
            raise RuntimeError(
                f"tail cache full ({self.tail_max}); re-prefill to fold the "
                "tail into the sharded prefix")
        if self._decode_fn is None:
            self._decode_fn = self._build_decode()
        x = jnp.asarray(x)
        h, sess.tk, sess.tv = self._decode_fn(
            self.params, x, sess.pk, sess.pv, sess.tk, sess.tv,
            jnp.int32(sess.prefix_len), jnp.int32(sess.tail_len),
            jnp.int32(sess.cache_len))
        sess.tail_len += 1
        return h

    def decode(self, x) -> jnp.ndarray:
        """Legacy single-session facade over `decode_step`."""
        return self.decode_step(self._default, x)

    def reset(self) -> None:
        """Drop THE legacy session's caches (serving end_session): the
        sharded prefix and replicated tail buffers are freed; compiled fns
        stay."""
        self._default = SpSession()

    # ------------------------------------------------------------------

    def logits_at(self, hidden: jnp.ndarray, position: int) -> jnp.ndarray:
        """lm_head over ONE position of a (possibly sequence-sharded) hidden
        — for long prompts, materializing [B, T, V] logits would dwarf the
        memory the sharded cache saved."""
        from ..models.transformer import lm_head

        h = jax.lax.dynamic_slice_in_dim(hidden, position, 1, axis=1)
        return lm_head(self.cfg, self.params, h)[:, 0]
