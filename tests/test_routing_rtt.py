"""RTT publication + latency-aware route planning.

Covers the _ping_next_servers parity surface (petals/server/server.py:760-767:
servers ping their likely next hops and publish the RTTs) and the
latency-aware client routing built on it (scheduling.routing): the planner
minimizes estimated per-step latency  Σ [rtt(prev→s) + span/throughput]
where the greedy router (src/rpc_transport.py:440-449) only maximizes span
coverage.
"""

import random

import jax
import jax.numpy as jnp

from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.models import (
    init_params,
    llama_config,
)
from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.models.partition import (
    ROLE_LAST,
    ROLE_SEGMENT,
    StagePlan,
    StageSpec,
    parse_splits,
    slice_stage_params,
)
from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.ops.sampling import (
    SamplingParams,
)
from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.runtime.client import (
    PipelineClient,
)
from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.runtime.executor import (
    StageExecutor,
)
from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.runtime.server import (
    measure_next_server_rtts,
)
from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.runtime.transport import (
    LocalTransport,
)
from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.scheduling.registry import (
    PlacementRegistry,
    ServerRecord,
    ServerState,
)
from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.scheduling.routing import (
    plan_min_latency_route,
    route_cost,
)

from test_runtime_pipeline import oracle_generate, tiny_cfg


def rec(peer, start, end, *, thr=1.0, final=False, rtts=None,
        state=ServerState.ONLINE):
    return ServerRecord(peer_id=peer, start_block=start, end_block=end,
                        throughput=thr, state=state, final_stage=final,
                        next_server_rtts=rtts)


# ---------------------------------------------------------------------------
# Pure planner
# ---------------------------------------------------------------------------

def test_planner_prefers_fast_links_over_max_coverage():
    # One server covers the whole remainder but sits behind a 1s link; a
    # two-hop chain of fast links is cheaper end-to-end. Greedy (max
    # end_block) would take the big span; the planner must not.
    records = [
        rec("big", 2, 8, final=True),
        rec("a", 2, 5, rtts={"b": 0.001}),
        rec("b", 5, 8, final=True),
    ]
    route = plan_min_latency_route(
        records, 2, 8,
        client_rtts={"big": 1.0, "a": 0.001}, default_rtt=0.5)
    assert [h.record.peer_id for h in route] == ["a", "b"]
    assert (route[0].entry, route[0].end) == (2, 5)
    assert (route[1].entry, route[1].end) == (5, 8)


def test_planner_takes_single_hop_when_links_are_equal():
    # Same topology, uniform latency: fewer hops ⇒ fewer RTTs ⇒ single hop.
    records = [
        rec("big", 2, 8, final=True),
        rec("a", 2, 5, rtts={"b": 0.01}),
        rec("b", 5, 8, final=True),
    ]
    route = plan_min_latency_route(
        records, 2, 8, client_rtts={"big": 0.01, "a": 0.01})
    assert [h.record.peer_id for h in route] == ["big"]


def test_planner_uses_published_next_hop_rtts():
    # Second hop has two equal-throughput candidates; the first hop's
    # published RTT table must decide between them.
    records = [
        rec("a", 2, 5, rtts={"slow": 2.0, "fast": 0.001}),
        rec("slow", 5, 8, final=True),
        rec("fast", 5, 8, final=True),
    ]
    route = plan_min_latency_route(records, 2, 8, client_rtts={"a": 0.001})
    assert [h.record.peer_id for h in route] == ["a", "fast"]


def test_planner_charges_default_rtt_for_unmeasured_links():
    # "fast" was never pinged: it gets default_rtt (0.1), not zero — so the
    # measured 0.05 link must win.
    records = [
        rec("a", 2, 5, rtts={"m": 0.05}),
        rec("m", 5, 8, final=True),
        rec("fast", 5, 8, final=True),
    ]
    route = plan_min_latency_route(records, 2, 8, client_rtts={"a": 0.0},
                                   default_rtt=0.1)
    assert [h.record.peer_id for h in route] == ["a", "m"]


def test_planner_weighs_throughput_against_latency():
    # Fast link to a slow server vs slow link to a fast server.
    records = [
        rec("slowcompute", 0, 4, thr=1.0, final=True),   # 4 blocks / 1 rps = 4s
        rec("fastcompute", 0, 4, thr=100.0, final=True),  # 0.04s compute
    ]
    route = plan_min_latency_route(
        records, 0, 4, client_rtts={"slowcompute": 0.01, "fastcompute": 1.0})
    assert route[0].record.peer_id == "fastcompute"  # 1.04 < 4.01


def test_planner_requires_final_stage_and_exclusion():
    records = [rec("a", 0, 4)]  # covers everything but is not final
    assert plan_min_latency_route(records, 0, 4) is None
    records = [rec("a", 0, 4, final=True), rec("b", 0, 4, final=True)]
    route = plan_min_latency_route(records, 0, 4, exclude=("a",))
    assert [h.record.peer_id for h in route] == ["b"]
    assert plan_min_latency_route(records, 0, 4, exclude=("a", "b")) is None


def test_planner_can_enter_span_mid_block():
    # Coverage requires entering "wide" at block 3 (mid-span) after "head".
    records = [
        rec("head", 0, 3, rtts={"wide": 0.001}),
        rec("wide", 1, 6, final=True),
    ]
    route = plan_min_latency_route(records, 0, 6, client_rtts={"head": 0.001})
    assert [(h.record.peer_id, h.entry, h.end) for h in route] == [
        ("head", 0, 3), ("wide", 3, 6)]


def test_route_cost_is_the_minimized_objective():
    records = [
        rec("a", 2, 5, rtts={"b": 0.25}),
        rec("b", 5, 8, thr=2.0, final=True),
    ]
    route = plan_min_latency_route(records, 2, 8, client_rtts={"a": 0.5})
    got = route_cost(route, client_rtts={"a": 0.5})
    # 0.5 + 3/1.0 + 0.25 + 3/2.0
    assert abs(got - (0.5 + 3.0 + 0.25 + 1.5)) < 1e-9


# ---------------------------------------------------------------------------
# Server-side measurement + registry round trip
# ---------------------------------------------------------------------------

def test_measure_next_server_rtts_pings_successors_only():
    reg = PlacementRegistry(rng=random.Random(0))
    reg.register(rec("me", 0, 4))
    reg.register(rec("next1", 4, 8))
    reg.register(rec("next2", 2, 6))          # covers block 4 too
    reg.register(rec("unrelated", 6, 8))      # does not serve block 4
    pings = {"next1": 0.02, "next2": 0.05}
    rtts = measure_next_server_rtts(
        reg, lambda r: pings.get(r.peer_id), "me", 4)
    assert rtts == {"next1": 0.02, "next2": 0.05}


def test_measure_skips_unreachable_peers():
    reg = PlacementRegistry(rng=random.Random(0))
    reg.register(rec("me", 0, 4))
    reg.register(rec("dead", 4, 8))
    rtts = measure_next_server_rtts(reg, lambda r: None, "me", 4)
    assert rtts == {}


def test_heartbeat_carries_rtts_into_registry_record():
    reg = PlacementRegistry(rng=random.Random(0))
    reg.register(rec("a", 0, 4))
    assert reg.heartbeat("a", next_server_rtts={"b": 0.01})
    assert reg.get("a").next_server_rtts == {"b": 0.01}
    # absent -> preserved, not cleared
    assert reg.heartbeat("a", throughput=2.0)
    assert reg.get("a").next_server_rtts == {"b": 0.01}


def test_empty_sweep_retracts_stale_rtts():
    # {} must CLEAR previously published RTTs (None means "no update") —
    # otherwise a dead link's 5ms measurement is advertised forever.
    reg = PlacementRegistry(rng=random.Random(0))
    reg.register(rec("a", 0, 4))
    assert reg.heartbeat("a", next_server_rtts={"b": 0.005})
    assert reg.heartbeat("a", next_server_rtts={})
    assert reg.get("a").next_server_rtts == {}


def test_sweep_budget_bounds_heartbeat_stretch():
    reg = PlacementRegistry(rng=random.Random(0))
    reg.register(rec("me", 0, 4))
    for i in range(5):
        reg.register(rec(f"n{i}", 4, 8))
    calls = []

    def slow_ping(r):
        calls.append(r.peer_id)
        import time as t
        t.sleep(0.05)
        return 0.05

    rtts = measure_next_server_rtts(reg, slow_ping, "me", 4, budget_s=0.08)
    # Budget cuts the sweep short: strictly fewer than all 5 candidates.
    assert 1 <= len(calls) < 5
    assert set(rtts) == set(calls)


def test_remote_registry_restores_freshness_ordering():
    import time as t

    from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.runtime.net import (
        RegistryServer,
        RemoteRegistry,
    )

    srv = RegistryServer(port=0)
    srv.start()
    try:
        remote = RemoteRegistry(srv.address)
        remote.register(rec("old", 0, 4))
        t.sleep(0.25)
        remote.register(rec("new", 0, 4))
        got = {r.peer_id: r.timestamp for r in remote.live_servers()}
        # Raw monotonic timestamps are meaningless across hosts; the wire
        # carries age_s so newest-first ordering survives deserialization.
        assert got["new"] > got["old"]
        assert got["new"] - got["old"] > 0.1
    finally:
        srv.stop()


def test_rtts_survive_the_tcp_registry_wire():
    from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.runtime.net import (
        RegistryServer,
        RemoteRegistry,
    )

    srv = RegistryServer(port=0)
    srv.start()
    try:
        remote = RemoteRegistry(srv.address)
        remote.register(rec("a", 0, 4, rtts={"b": 0.125}))
        remote.heartbeat("a", next_server_rtts={"b": 0.25, "c": 0.5})
        got = remote.get("a")
        assert got.next_server_rtts == {"b": 0.25, "c": 0.5}
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# Client integration: route choice + token parity
# ---------------------------------------------------------------------------

def _spec(start, end, total):
    role = ROLE_LAST if end >= total else ROLE_SEGMENT
    return StageSpec(index=start, role=role, start=start, end=end)


def test_latency_client_picks_fast_replica_and_matches_oracle():
    cfg = tiny_cfg()
    params = init_params(jax.random.PRNGKey(0), cfg)
    plan = StagePlan.from_splits(cfg.num_layers, parse_splits("4"))
    total = cfg.num_layers
    transport = LocalTransport()
    registry = PlacementRegistry(rng=random.Random(0))

    # Two replicas of the remote span [4, 8): one behind a slow link.
    for peer, link in (("fast", 0.0), ("slow", 0.35)):
        spec = _spec(4, total, total)
        ex = StageExecutor(cfg, spec, slice_stage_params(cfg, params, spec),
                           peer_id=peer)
        transport.add_peer(peer, ex)
        transport.rtts[peer] = link
        registry.register(rec(peer, 4, total, final=True))

    stage0 = StageExecutor(cfg, plan.stages[0],
                           slice_stage_params(cfg, params, plan.stages[0]),
                           peer_id="client-local")
    client = PipelineClient(cfg, plan, stage0, transport, registry,
                            use_module_routing=True, route_by_latency=True,
                            settle_seconds=0.0, seed=0)
    route = client.route()
    assert [h.peer_id for h in route] == ["fast"]

    sampling = SamplingParams(temperature=0.0)
    prompt = [5, 9, 23, 7, 81]
    res = client.generate(prompt, max_new_tokens=6, sampling=sampling)
    assert res.tokens == oracle_generate(cfg, params, prompt, 6, sampling)


def test_latency_client_falls_back_to_greedy_without_final_coverage():
    # Planner dead-ends (no final-stage server), greedy raises NoRouteError
    # identically — but with a PARTIAL coverage the greedy path still works;
    # here we give greedy a valid route that the planner also finds, plus a
    # failed peer the planner must exclude.
    cfg = tiny_cfg()
    params = init_params(jax.random.PRNGKey(0), cfg)
    plan = StagePlan.from_splits(cfg.num_layers, parse_splits("4"))
    total = cfg.num_layers
    transport = LocalTransport()
    registry = PlacementRegistry(rng=random.Random(0))
    for peer in ("r0", "r1"):
        spec = _spec(4, total, total)
        ex = StageExecutor(cfg, spec, slice_stage_params(cfg, params, spec),
                           peer_id=peer)
        transport.add_peer(peer, ex)
        registry.register(rec(peer, 4, total, final=True))
    stage0 = StageExecutor(cfg, plan.stages[0],
                           slice_stage_params(cfg, params, plan.stages[0]),
                           peer_id="client-local")
    client = PipelineClient(cfg, plan, stage0, transport, registry,
                            use_module_routing=True, route_by_latency=True,
                            settle_seconds=0.0, seed=0)
    client.failed_peers["blocks4"] = {"r0"}
    route = client.route(refresh=True)
    assert [h.peer_id for h in route] == ["r1"]


def test_elastic_server_publishes_next_hop_rtts():
    from global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.runtime.server import (
        FixedStageServer,
    )

    cfg = tiny_cfg()
    params = init_params(jax.random.PRNGKey(0), cfg)
    total = cfg.num_layers
    transport = LocalTransport()
    registry = PlacementRegistry(rng=random.Random(0))

    front_spec = _spec(2, 5, total)
    back_spec = _spec(5, total, total)
    front = FixedStageServer("front", cfg, front_spec,
                             slice_stage_params(cfg, params, front_spec),
                             registry, transport)
    back = FixedStageServer("back", cfg, back_spec,
                            slice_stage_params(cfg, params, back_spec),
                            registry, transport)
    front.start_serving()
    back.start_serving()
    transport.rtts["back"] = 0.07

    front.heartbeat_once()          # measures after refreshing
    front.heartbeat_once()          # publishes last beat's measurement
    assert registry.get("front").next_server_rtts == {"back": 0.07}
    # The final stage never publishes RTTs (no next hop).
    back.heartbeat_once()
    back.heartbeat_once()
    assert registry.get("back").next_server_rtts is None
    # Next hop dies -> the sweep comes back empty -> the stale 0.07 must be
    # RETRACTED, not pinned forever.
    transport.kill("back")
    front.heartbeat_once()          # measures {} after refreshing with stale
    front.heartbeat_once()          # publishes the retraction
    assert registry.get("front").next_server_rtts == {}
