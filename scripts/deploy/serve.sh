#!/usr/bin/env bash
# Run one stage server with crash-restart — the runnable counterpart of the
# reference's deploy_direct.sh server loop (scripts/deploy_direct.sh:47-99).
#
# Config comes from an env file (default /etc/mpt/server.env, override with
# MPT_ENV), so the same script serves fixed-split and elastic roles:
#
#   MPT_REGISTRY=10.0.0.1:31330     # control plane
#   MPT_CHECKPOINT=/data/llama-3-8b # local HF checkpoint dir (omit = random)
#   MPT_MODEL=llama-3-8b            # preset + registry scoping name
#   MPT_ROLE=elastic                # elastic | fixed
#   MPT_STAGE=1                     # fixed role: stage index
#   MPT_SPLITS=8,16,24              # fixed role: stage boundaries
#   MPT_NUM_BLOCKS=                 # elastic: blocks (empty = auto-size
#                                   #  from device HBM, quant-aware)
#   MPT_QUANT=none                  # none | int8 | nf4
#   MPT_RPC_PORT=31331
#   MPT_PUBLIC_IP=                  # advertise this IP instead of --host
#   MPT_EXTRA_ARGS=                 # anything else (e.g. --use_cpu_offload)
set -euo pipefail

ENV_FILE="${MPT_ENV:-/etc/mpt/server.env}"
[ -f "$ENV_FILE" ] && . "$ENV_FILE"

: "${MPT_REGISTRY:?set MPT_REGISTRY (host:port of the registry)}"
MPT_ROLE="${MPT_ROLE:-elastic}"
MPT_MODEL="${MPT_MODEL:-gpt2}"
MPT_RPC_PORT="${MPT_RPC_PORT:-31331}"
REPO="$(cd "$(dirname "$0")/../.." && pwd)"
PYTHON="${MPT_PYTHON:-python3}"

args=(--mode serve --registry_addr "$MPT_REGISTRY" --model "$MPT_MODEL"
      --rpc_port "$MPT_RPC_PORT" --host 0.0.0.0)
[ -n "${MPT_CHECKPOINT:-}" ] && args+=(--checkpoint "$MPT_CHECKPOINT")
[ -n "${MPT_PUBLIC_IP:-}" ] && args+=(--public_ip "$MPT_PUBLIC_IP")
[ -n "${MPT_QUANT:-}" ] && [ "${MPT_QUANT}" != none ] && args+=(--quant "$MPT_QUANT")
if [ "$MPT_ROLE" = elastic ]; then
    args+=(--use_load_balancing)
    [ -n "${MPT_SPLITS:-}" ] && args+=(--splits "$MPT_SPLITS")
    [ -n "${MPT_NUM_BLOCKS:-}" ] && args+=(--num_blocks "$MPT_NUM_BLOCKS")
else
    : "${MPT_STAGE:?fixed role needs MPT_STAGE}"
    : "${MPT_SPLITS:?fixed role needs MPT_SPLITS}"
    args+=(--stage "$MPT_STAGE" --splits "$MPT_SPLITS")
fi
# shellcheck disable=SC2206
[ -n "${MPT_EXTRA_ARGS:-}" ] && args+=($MPT_EXTRA_ARGS)

# Crash-restart with backoff (systemd Restart= does this too; the loop makes
# the bare-script path equally durable — reference deploy_direct.sh behavior).
backoff=2
while true; do
    echo "[serve.sh] starting: $PYTHON -m ..main ${args[*]}" >&2
    set +e
    (cd "$REPO" && "$PYTHON" -m \
        global_capstone_design_distributed_inference_of_llms_over_the_internet_tpu.main \
        "${args[@]}")
    rc=$?
    set -e
    [ $rc -eq 0 ] && exit 0            # clean shutdown (SIGINT handled)
    echo "[serve.sh] server exited rc=$rc; restarting in ${backoff}s" >&2
    sleep "$backoff"
    backoff=$(( backoff < 60 ? backoff * 2 : 60 ))
done
