"""ctypes bindings for the native wire codec, with numpy fallbacks.

Auto-builds ``libcodec.so`` on first import when a compiler is available
(`make -C native`); otherwise the numpy implementations serve — identical
semantics (round-to-nearest-even bf16, CRC-32C), just slower.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
from typing import Optional

import numpy as np

logger = logging.getLogger(__name__)

_DIR = os.path.dirname(os.path.abspath(__file__))
_LIB_PATH = os.path.join(_DIR, "libcodec.so")
_lib: Optional[ctypes.CDLL] = None


def _load() -> Optional[ctypes.CDLL]:
    global _lib
    if _lib is not None:
        return _lib
    if not os.path.exists(_LIB_PATH):
        try:
            subprocess.run(
                ["make", "-C", _DIR, "-s"], check=True,
                capture_output=True, timeout=60,
            )
        except Exception as exc:
            logger.info("native codec build unavailable (%s); numpy fallback", exc)
            return None
    try:
        lib = ctypes.CDLL(_LIB_PATH)
        lib.fp32_to_bf16.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_size_t]
        lib.bf16_to_fp32.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_size_t]
        lib.crc32c.argtypes = [ctypes.c_void_p, ctypes.c_size_t]
        lib.crc32c.restype = ctypes.c_uint32
        _lib = lib
        return lib
    except OSError as exc:
        logger.info("native codec load failed (%s); numpy fallback", exc)
        return None


def have_native() -> bool:
    return _load() is not None


def fp32_to_bf16_bytes(arr: np.ndarray) -> bytes:
    """fp32 array -> bf16 wire bytes (round-to-nearest-even)."""
    arr = np.ascontiguousarray(arr, dtype=np.float32)
    lib = _load()
    out = np.empty(arr.size, np.uint16)
    if lib is not None:
        lib.fp32_to_bf16(arr.ctypes.data, out.ctypes.data, arr.size)
        return out.tobytes()
    bits = arr.view(np.uint32).reshape(-1)
    nan = (bits & 0x7FFFFFFF) > 0x7F800000
    bias = 0x7FFF + ((bits >> 16) & 1)
    rounded = ((bits + bias) >> 16).astype(np.uint16)
    qnan = ((bits >> 16) | 0x0040).astype(np.uint16)
    return np.where(nan, qnan, rounded).tobytes()


def bf16_bytes_to_fp32(data: bytes, shape) -> np.ndarray:
    """bf16 wire bytes -> fp32 array of `shape`."""
    raw = np.frombuffer(data, np.uint16)
    lib = _load()
    if lib is not None:
        src = np.ascontiguousarray(raw)
        out = np.empty(raw.size, np.float32)
        lib.bf16_to_fp32(src.ctypes.data, out.ctypes.data, raw.size)
        return out.reshape(shape)
    return (raw.astype(np.uint32) << 16).view(np.float32).reshape(shape)


def crc32c(data: bytes) -> int:
    lib = _load()
    if lib is not None:
        buf = (ctypes.c_char * len(data)).from_buffer_copy(data)
        return int(lib.crc32c(buf, len(data)))
    # numpy fallback: table-driven CRC-32C
    table = _py_table()
    crc = np.uint32(0xFFFFFFFF)
    arr = np.frombuffer(data, np.uint8)
    for b in arr:
        crc = table[(crc ^ b) & 0xFF] ^ (crc >> np.uint32(8))
    return int(crc ^ np.uint32(0xFFFFFFFF))


_TABLE = None


def _py_table():
    global _TABLE
    if _TABLE is None:
        poly = np.uint32(0x82F63B78)
        t = np.zeros(256, np.uint32)
        for i in range(256):
            c = np.uint32(i)
            for _ in range(8):
                c = (poly ^ (c >> np.uint32(1))) if (c & np.uint32(1)) else (c >> np.uint32(1))
            t[i] = c
        _TABLE = t
    return _TABLE
