from .attention import cached_attention, update_kv_cache
from .norms import layer_norm, rms_norm
from .rotary import apply_rope, rope_cos_sin
from .sampling import (
    RECENT_WINDOW,
    SamplingParams,
    apply_repetition_penalty,
    make_recent_buffer,
    push_recent,
    sample_probs,
    sample_token,
)

__all__ = [
    "cached_attention",
    "update_kv_cache",
    "layer_norm",
    "rms_norm",
    "apply_rope",
    "rope_cos_sin",
    "RECENT_WINDOW",
    "SamplingParams",
    "apply_repetition_penalty",
    "make_recent_buffer",
    "push_recent",
    "sample_probs",
    "sample_token",
]
